"""SLO-burn-driven serving autopilot: the controller over the sensors.

PRs 9/13/14 built the sensors and actuators of a self-healing serving
plane but no controller: the perf model predicts per-bucket latency
(`perf/model.py`), the SLO engine measures multi-window burn rate
(`obs/slo.py`), quantized builds stay resident beside f32
(`serving/fleet.py`), and breakers/watchdog handle hard faults — yet
overload response was a static config (queue bound + priority shed).
This module closes the loop (the ML-productivity-goodput thesis, arxiv
2502.06982, as an actual control loop): a supervisor thread reads the
burn signal each tick and actuates remediation in ESCALATING order up a
rung ladder, one rung per dwell window:

1. **rebucket re-arm** — the PR-9 auto-rebucket path fires one shot
   organically; under burn the controller re-arms it (cooldown-gated)
   so the ladder re-derives from the storm's traffic mix;
2. **adaptive fidelity** — route a burning model to its resident
   int8-calibrated sibling member (`FleetService.set_fidelity_route`)
   and back when burn clears: both builds stay resident (their
   programs never adopt each other), so the swap is a table write —
   no compile, no dropped request;
3. **predictive admission** — write a synthetic queue pressure for
   each primary model from the perf model's predicted queue-drain
   time vs the deadline budget (`Router.set_pressure`), shedding low
   classes BEFORE the bounded queue observes saturation. A cold model
   predicts None → pressure stays 0 → admission is bit-identical to
   observed-queue shedding;
4. **warm-spare activation** — `add_model` a configured spare member
   (program-pool adoption makes it near-free), removed on release.

Every transition carries hysteresis — distinct engage/release burn
thresholds plus a min-dwell between transitions, so boundary load
cannot flap a route — and is recorded as an `autopilot_actuation`
flight-recorder event embedding the exact burn window and prediction
that justified it. Every actuation is reversible; the release path
walks the ladder back down, and the controller's steady state on a
healthy fleet is ZERO actuations.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

__all__ = ["Autopilot", "AutopilotParams"]


def _record_event(name: str, **attrs: Any) -> None:
    try:
        from transmogrifai_tpu.obs.export import record_event
        record_event(name, **attrs)
    except Exception:
        log.debug("%s event emission failed", name, exc_info=True)


@dataclass
class AutopilotParams:
    """JSON-loadable controller knobs (`FleetConfig.autopilot`)."""

    enabled: bool = True
    # tick cadence of the supervisor thread
    period_s: float = 0.25
    # hysteresis: the burn signal (max over SLO windows of
    # min(long, short) burn / window threshold; >= 1.0 iff some window
    # fires) must reach `engage_burn` to climb a rung and fall to
    # `release_burn` to descend one — distinct thresholds so boundary
    # load cannot flap a route
    engage_burn: float = 1.0
    release_burn: float = 0.5
    # minimum seconds between rung transitions (engage OR release):
    # at most one transition per dwell window
    min_dwell_s: float = 1.0
    # a release additionally requires the burn to have stayed at or
    # below `release_burn` CONTINUOUSLY for this long: one healthy
    # window sample mid-storm (bursty completions, a starved SLO
    # engine) must not walk a cure back while the overload is still on
    release_hold_s: float = 0.0
    # cooldown between controller-driven rebucket re-arms
    rebucket_cooldown_s: float = 5.0
    # fidelity flips: burning model -> resident quantized sibling
    # member name (both must be hosted; the flip is a route-table write)
    fidelity: Dict[str, str] = field(default_factory=dict)
    # predictive admission: pressure = predicted_drain_s /
    # (admission_headroom * deadline_budget_s); 1.0 sheds everything
    # below the top priority class
    admission_headroom: float = 1.0
    # warm spare member spec: {"name": ..., "path": ...,
    # "overrides": {...}} added at the top rung, removed on release
    spare: Optional[Dict[str, Any]] = None

    _FIELDS = ("enabled", "period_s", "engage_burn", "release_burn",
               "min_dwell_s", "release_hold_s", "rebucket_cooldown_s",
               "fidelity", "admission_headroom", "spare")

    def __post_init__(self) -> None:
        if self.period_s <= 0:
            raise ValueError(f"period_s must be > 0: {self.period_s}")
        if self.engage_burn <= self.release_burn:
            raise ValueError(
                f"engage_burn ({self.engage_burn}) must exceed "
                f"release_burn ({self.release_burn}) — equal thresholds "
                f"remove the hysteresis band and the loop can flap")
        if self.release_burn < 0:
            raise ValueError(
                f"release_burn must be >= 0: {self.release_burn}")
        if self.min_dwell_s < 0:
            raise ValueError(
                f"min_dwell_s must be >= 0: {self.min_dwell_s}")
        if self.release_hold_s < 0:
            raise ValueError(
                f"release_hold_s must be >= 0: {self.release_hold_s}")
        if self.rebucket_cooldown_s < 0:
            raise ValueError(f"rebucket_cooldown_s must be >= 0: "
                             f"{self.rebucket_cooldown_s}")
        if self.admission_headroom <= 0:
            raise ValueError(f"admission_headroom must be > 0: "
                             f"{self.admission_headroom}")
        if self.spare is not None and not (
                isinstance(self.spare, dict) and self.spare.get("name")
                and self.spare.get("path")):
            raise ValueError(
                f'spare must be {{"name": ..., "path": ...}}: '
                f"{self.spare!r}")

    @staticmethod
    def from_json(d: Optional[Dict[str, Any]]) -> "AutopilotParams":
        d = d or {}
        return AutopilotParams(**{k: d[k] for k in AutopilotParams._FIELDS
                                  if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}


class Autopilot:
    """The supervisor. `start()` spawns the tick thread; `tick(now=...)`
    is directly callable (tests drive it with a fake clock). All shared
    controller state lives under `self._lock`; actuations and event
    emission happen OUTSIDE it (never block under a lock — C003)."""

    def __init__(self, fleet, params: Optional[AutopilotParams] = None):
        self.fleet = fleet
        self.params = params or AutopilotParams()
        # the actuation ladder this config can actually climb: rungs
        # with nothing to do (no fidelity map, no spare spec) are left
        # out rather than burned as no-op dwell windows
        self.ladder: Tuple[str, ...] = tuple(
            ["rebucket"]
            + (["fidelity"] if self.params.fidelity else [])
            + ["admission"]
            + (["spare"] if self.params.spare else []))
        self._lock = threading.Lock()
        self._rung = 0               # guarded-by: self._lock
        self._last_transition = 0.0  # guarded-by: self._lock
        self._rebucket_last = -1e18  # guarded-by: self._lock
        self._last_burn = 0.0        # guarded-by: self._lock
        # start of the current continuous at-or-below-release_burn
        # streak; None while burn is above it or unmeasured
        self._below_since: Optional[float] = None  # guarded-by: self._lock
        self._last_window: Optional[Dict[str, Any]] = None  # guarded-by: self._lock
        self._actuations = 0         # guarded-by: self._lock
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_actuations = fleet.registry.counter(
            "autopilot_actuations_total",
            "autopilot engage/release actuations by action")
        self._m_rung = fleet.registry.gauge(
            "autopilot_rung", "current autopilot escalation rung")

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "Autopilot":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._halt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="serving-autopilot",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._halt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def _run(self) -> None:
        while not self._halt.wait(timeout=self.params.period_s):
            try:
                self.tick()
            except Exception:
                # one bad tick (a member mid-removal, a racing health
                # read) must not kill the controller
                log.warning("autopilot tick failed", exc_info=True)

    # -- sensing ----------------------------------------------------------- #

    def burn_signal(self) -> Tuple[Optional[float],
                                   Optional[Dict[str, Any]]]:
        """(signal, justifying window). The signal is the max over every
        SLO's burn windows of min(long_burn, short_burn) / threshold —
        >= 1.0 exactly when some window fires (both of its rates over
        budget) — and the window dict names the SLO, window key, scope,
        and the measured rates, embedded verbatim in actuation events.

        Each SLO contributes its FLEET burn windows when the engine's
        fleet fold is attached and fresh (K replicas' traffic summed —
        the controller damps the fleet's burn, not its 1/K local
        shadow of it); a stale cell falls back to the LOCAL windows, so
        a dead publisher degrades sensing to per-replica instead of
        reading frozen fleet counters as health.

        Returns ``(None, None)`` on a SENSING GAP: no engine, a failed
        status read, or every window missing a rate (a rate is None
        when its sample delta spans no completed traffic — e.g. the
        engine thread was starved under the very overload the
        controller is damping). A gap is not health: the caller holds
        state rather than treating it as burn 0.0."""
        engine = getattr(self.fleet, "slo_engine", None)
        if engine is None:
            return None, None
        try:
            status = engine.status()
        except Exception:
            log.debug("autopilot: SLO status read failed", exc_info=True)
            return None, None
        best, best_window, sensed = 0.0, None, False
        for name, slo in (status.get("slos") or {}).items():
            fleet = slo.get("fleet") or {}
            scope, windows = "local", slo.get("windows") or {}
            fw = fleet.get("windows") or {}
            if fleet.get("fresh") and any(
                    w.get("long_burn") is not None
                    and w.get("short_burn") is not None
                    for w in fw.values()):
                scope, windows = "fleet", fw
            for wkey, w in windows.items():
                long_b = w.get("long_burn")
                short_b = w.get("short_burn")
                if long_b is None or short_b is None:
                    continue
                sensed = True
                threshold = float(w.get("threshold") or 1.0)
                signal = min(float(long_b), float(short_b)) \
                    / max(1e-9, threshold)
                if signal > best:
                    best = signal
                    best_window = {"slo": name, "window": wkey,
                                   "scope": scope, **w}
                    if scope == "fleet":
                        best_window["replicas"] = fleet.get("replicas")
        if not sensed:
            return None, None
        return best, best_window

    def _members(self) -> Dict[str, Any]:
        return self.fleet._live_services()

    def _primary_members(self) -> Dict[str, Any]:
        """Members that take first-line traffic: everything except the
        fidelity targets and the spare (they absorb overflow — writing
        pressure against them would shed the traffic we just moved)."""
        skip = set(self.params.fidelity.values())
        if self.params.spare:
            skip.add(self.params.spare["name"])
        return {n: s for n, s in self._members().items() if n not in skip}

    def _drain_prediction(self, svc) -> Optional[Any]:
        from transmogrifai_tpu import perf
        try:
            top = max(svc.ladder) if svc.ladder else svc.config.max_batch
            return perf.predict_drain_seconds(
                max(1, svc._batcher.depth()), top)
        except Exception:
            log.debug("autopilot: drain prediction failed", exc_info=True)
            return None

    # -- control loop ------------------------------------------------------ #

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One controller evaluation. Reads the burn signal, applies the
        hysteresis ladder (at most ONE rung transition per call, and
        only after `min_dwell_s` since the last), then maintains the
        predictive-admission pressure while that rung is engaged.
        Returns a status snapshot (tests assert on it)."""
        if now is None:
            now = time.monotonic()
        burn, window = self.burn_signal()
        transition: Optional[Tuple[str, str]] = None
        with self._lock:
            if burn is None:
                # sensing gap: hold the rung (and break any
                # below-release streak — unmeasured is not healthy),
                # but keep maintaining admission pressure below
                self._below_since = None
                burn = self._last_burn
                window = self._last_window
            else:
                self._last_burn = burn
                self._last_window = window
                # a release streak: burn continuously at or below the
                # release threshold since `_below_since`
                if burn <= self.params.release_burn:
                    if self._below_since is None:
                        self._below_since = now
                else:
                    self._below_since = None
                dwell_ok = (now - self._last_transition) \
                    >= self.params.min_dwell_s
                held = (self._below_since is not None
                        and now - self._below_since
                        >= self.params.release_hold_s)
                if burn >= self.params.engage_burn and dwell_ok \
                        and self._rung < len(self.ladder):
                    self._rung += 1
                    self._last_transition = now
                    transition = ("engage", self.ladder[self._rung - 1])
                elif burn <= self.params.release_burn and dwell_ok \
                        and held and self._rung > 0:
                    transition = ("release", self.ladder[self._rung - 1])
                    self._rung -= 1
                    self._last_transition = now
            rung = self._rung
            admission_on = "admission" in self.ladder[:rung]
        self._m_rung.set(rung)
        if transition is not None:
            kind, action = transition
            self._actuate(kind, action, burn, window, now)
        if admission_on and (transition is None
                             or transition[1] != "admission"):
            # maintain pressure from FRESH predictions every tick while
            # the rung stays engaged (engage/release themselves wrote it)
            self._update_pressure(burn, window, emit=False)
        return self.status()

    def _actuate(self, kind: str, action: str, burn: float,
                 window: Optional[Dict[str, Any]], now: float) -> None:
        try:
            if action == "rebucket":
                self._act_rebucket(kind, burn, window, now)
            elif action == "fidelity":
                self._act_fidelity(kind, burn, window)
            elif action == "admission":
                if kind == "engage":
                    self._update_pressure(burn, window, emit=True)
                else:
                    self._clear_pressure(burn, window)
            elif action == "spare":
                self._act_spare(kind, burn, window)
        except Exception:
            log.warning("autopilot: %s %s failed", kind, action,
                        exc_info=True)
        with self._lock:
            self._actuations += 1
        self._m_actuations.inc()
        if kind == "engage":
            try:
                from transmogrifai_tpu.obs import flight
                # OFF the control thread: the ring is fullest exactly
                # when actuations happen (overload = span flood), and a
                # multi-second artifact write here would freeze the
                # ladder for dozens of dwell windows mid-incident — the
                # one time the controller must keep ticking. The dump
                # snapshots the ring when the writer runs; the
                # actuation event is already in it (recorded above).
                threading.Thread(
                    target=flight.request_dump,
                    args=(f"autopilot_{action}",),
                    name="autopilot-dump", daemon=True).start()
            except Exception:
                log.debug("autopilot flight dump failed", exc_info=True)

    def _event(self, action: str, kind: str, burn: float,
               window: Optional[Dict[str, Any]], **attrs: Any) -> None:
        with self._lock:
            rung = self._rung
        # the attr is `transition`, not `kind`: flight-dump events.jsonl
        # records already use a top-level `kind` ("event"/"span") and
        # event attrs are splatted into the same record
        _record_event("autopilot_actuation", action=action,
                      transition=kind, rung=rung, burn=round(burn, 4),
                      burn_window=window, **attrs)

    def _act_rebucket(self, kind: str, burn: float,
                      window: Optional[Dict[str, Any]],
                      now: float) -> None:
        """Re-arm the members' auto-rebucket shot so the next scored
        batch re-derives the ladder from the storm's size mix. The
        controller owns the cooldown; release re-arms once more so the
        ladder can re-derive from the RECOVERED traffic too."""
        with self._lock:
            cooled = (now - self._rebucket_last
                      >= self.params.rebucket_cooldown_s)
            if cooled:
                self._rebucket_last = now
        if not cooled:
            self._event("rebucket", kind, burn, window,
                        skipped="cooldown")
            return
        rearmed = [name for name, svc in self._members().items()
                   if svc.rearm_auto_rebucket()]
        self._event("rebucket", kind, burn, window, rearmed=rearmed)

    def _act_fidelity(self, kind: str, burn: float,
                      window: Optional[Dict[str, Any]]) -> None:
        for model, target in self.params.fidelity.items():
            try:
                if kind == "engage":
                    self.fleet.set_fidelity_route(model, target)
                else:
                    self.fleet.set_fidelity_route(model, None)
            except Exception:
                log.warning("autopilot: fidelity %s %s->%s failed",
                            kind, model, target, exc_info=True)
                continue
            self._event("fidelity", kind, burn, window, model=model,
                        target=(target if kind == "engage" else None),
                        restored=(model if kind == "release" else None))

    def _update_pressure(self, burn: float,
                         window: Optional[Dict[str, Any]],
                         emit: bool) -> None:
        """Predictive admission: per primary member, pressure =
        predicted drain seconds / (headroom x deadline budget), clamped
        to [0, 1]. Cold model -> None prediction -> pressure cleared,
        leaving admission bit-identical to observed-queue shedding."""
        members = self._members()
        for name, svc in self._primary_members().items():
            # pressure is keyed by the logical model name, but the drain
            # prediction must read the queue of the member that name
            # currently RESOLVES to (fidelity flips move the traffic)
            svc = members.get(self.fleet.resolve_model(name), svc)
            pred = self._drain_prediction(svc)
            deadline_s = max(1e-3,
                             svc.config.default_deadline_ms / 1000.0)
            if pred is None:
                self.fleet.router.set_pressure(name, 0.0)
                if emit:
                    self._event("admission", "engage", burn, window,
                                model=name, prediction=None,
                                pressure=0.0, note="model cold")
                continue
            ratio = pred.value / (self.params.admission_headroom
                                  * deadline_s)
            pressure = max(0.0, min(1.0, ratio))
            self.fleet.router.set_pressure(name, pressure)
            if emit:
                self._event("admission", "engage", burn, window,
                            model=name, prediction=pred.to_json(),
                            deadline_budget_s=round(deadline_s, 3),
                            pressure=round(pressure, 4))

    def _clear_pressure(self, burn: float,
                        window: Optional[Dict[str, Any]]) -> None:
        for name in self._primary_members():
            self.fleet.router.set_pressure(name, 0.0)
            self._event("admission", "release", burn, window,
                        model=name, pressure=0.0)

    def _act_spare(self, kind: str, burn: float,
                   window: Optional[Dict[str, Any]]) -> None:
        spare = self.params.spare or {}
        name = spare.get("name")
        if kind == "engage":
            if name in self._members():
                self._event("spare", kind, burn, window, member=name,
                            skipped="already hosted")
                return
            self.fleet.add_model(name, spare["path"],
                                 dict(spare.get("overrides") or {}))
            self._event("spare", kind, burn, window, member=name)
        else:
            try:
                self.fleet.remove_model(name)
            except Exception:
                log.debug("autopilot: spare %s already gone", name,
                          exc_info=True)
            self._event("spare", kind, burn, window, member=name)

    # -- introspection ----------------------------------------------------- #

    def status(self) -> Dict[str, Any]:
        with self._lock:
            rung = self._rung
            return {
                "rung": rung,
                "ladder": list(self.ladder),
                "engaged": list(self.ladder[:rung]),
                "burn": round(self._last_burn, 4),
                "burn_window": self._last_window,
                "actuations": self._actuations,
                "running": (self._thread is not None
                            and self._thread.is_alive()),
            }
