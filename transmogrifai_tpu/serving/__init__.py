"""serving/ — online scoring: shape-bucketed micro-batching, model
hot-swap, runtime metrics, stdlib-HTTP frontend.

The subsystem that turns a saved `WorkflowModel` into a servable,
observable endpoint (ROADMAP north star: "serves heavy traffic ... as
fast as the hardware allows"):

- `metrics`  — Counter/Gauge/Histogram registry, JSON + Prometheus text
- `batcher`  — bounded queue, deadlines, load-shedding, bucket ladder
- `service`  — ScoringService: AOT bucket warmup, versioned hot-swap
               with rollback, per-request error quarantine
- `http`     — /score /healthz /metrics /reload over http.server
- `smoke`    — self-contained boot-score-scrape-shutdown check
               (`make serve-smoke`)
"""

from transmogrifai_tpu.serving.batcher import (  # noqa: F401
    MicroBatcher, Request, ScoreError, bucket_for, bucket_ladder)
from transmogrifai_tpu.serving.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry)
from transmogrifai_tpu.serving.service import (  # noqa: F401
    ModelVersion, ScoreResult, ScoringService, ServingConfig)

__all__ = [
    "MicroBatcher", "Request", "ScoreError", "bucket_for", "bucket_ladder",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "ModelVersion", "ScoreResult", "ScoringService", "ServingConfig",
]
