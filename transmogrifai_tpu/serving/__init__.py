"""serving/ — online scoring: shape-bucketed micro-batching, model
hot-swap, runtime metrics, stdlib-HTTP frontend.

The subsystem that turns a saved `WorkflowModel` into a servable,
observable endpoint (ROADMAP north star: "serves heavy traffic ... as
fast as the hardware allows"):

- `metrics`  — Counter/Gauge/Histogram registry, JSON + Prometheus text
- `batcher`  — bounded queue, deadlines, load-shedding, bucket ladder
- `service`  — ScoringService: AOT bucket warmup, versioned hot-swap
               with rollback, per-request error quarantine
- `fleet`    — FleetService: N named models per process, shared bucket
               programs across same-signature models (ProgramPool),
               warmup-manifest/persistent-compile cold starts
- `router`   — per-tenant token-bucket quotas, priority shedding,
               per-tenant metrics
- `resilience` — per-member health state machine (HEALTHY/DEGRADED/
               QUARANTINED), circuit breaker + degraded fallback onto
               the resident previous version, hang watchdog
- `chaos`    — deterministic fault-storm harness over the fleet
               (`make chaos-smoke`, `python bench.py chaos`)
- `http`     — /score /healthz /metrics /reload over http.server
               (single-model `serve` + multi-model `serve_fleet`)
- `smoke`    — self-contained boot-score-scrape-shutdown check
               (`make serve-smoke`); `fleet_smoke` covers the
               multi-tenant fleet path (`make fleet-smoke`)
"""

from transmogrifai_tpu.obs.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry)
from transmogrifai_tpu.serving.batcher import (  # noqa: F401
    MicroBatcher, Request, ScoreError, bucket_for, bucket_ladder)
from transmogrifai_tpu.serving.fleet import (  # noqa: F401
    FleetConfig, FleetService, ProgramPool, scoring_signature)
from transmogrifai_tpu.serving.resilience import (  # noqa: F401
    DEGRADED, HEALTHY, QUARANTINED, MemberHealth, ResilienceParams,
    Watchdog)
from transmogrifai_tpu.serving.router import (  # noqa: F401
    Router, TenantPolicy, TokenBucket)
from transmogrifai_tpu.serving.service import (  # noqa: F401
    ModelVersion, ScoreResult, ScoringService, ServingConfig)

__all__ = [
    "MicroBatcher", "Request", "ScoreError", "bucket_for", "bucket_ladder",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "ModelVersion", "ScoreResult", "ScoringService", "ServingConfig",
    "FleetConfig", "FleetService", "ProgramPool", "scoring_signature",
    "Router", "TenantPolicy", "TokenBucket",
    "HEALTHY", "DEGRADED", "QUARANTINED",
    "MemberHealth", "ResilienceParams", "Watchdog",
]
