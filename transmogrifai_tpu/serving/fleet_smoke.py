"""Fleet-serving smoke: multi-model tenancy end to end, in one process.

`make fleet-smoke` runs this module. Under a minute on CPU it must
prove the acceptance surface of the fleet subsystem
(`serving/fleet.py` + `serving/router.py`):

1. THREE models in one FleetService across TWO tenants — two models
   same-shaped (forest pipelines differing only in fitted tree values)
   and one differently-shaped;
2. shared bucket programs: the second same-shaped model's warmup
   performs ZERO new traces (`RetraceMonitor.delta()`-asserted) while
   the differently-shaped model compiles its own ladder;
3. per-tenant quota enforcement under mixed HTTP load: the over-quota
   tenant collects 429s, the in-quota tenant collects NONE;
4. a rolling swap of one model under live traffic drops ZERO in-flight
   requests on the untouched models — and the same-shaped replacement
   itself warms with zero new compiles;
5. cold-start-to-first-score measured WITHOUT (fresh cache dir, cold
   XLA compiles, warmup manifest written) and WITH the persistent
   compile cache (second service instance over the same artifacts:
   manifest hit, `serving_compile_cache_saved_s` recorded).

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.serving.fleet_smoke``
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request


def _train_models(tmp: str) -> None:
    """a + b: forest pipelines over IDENTICAL features with different
    labels — identical scoring signatures, different fitted trees.
    c: a logistic pipeline — its own signature."""
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import (
        OpLogisticRegression, OpRandomForestClassifier)
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(7)
    n = 160
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)

    def fit(name: str, y, forest: bool) -> None:
        ds = Dataset({"x1": x1, "x2": x2, "y": y},
                     {"x1": t.Real, "x2": t.Real, "y": t.Integral})
        preds, label = FeatureBuilder.from_dataset(ds, response="y")
        vec = RealVectorizer(track_nulls=False).set_input(
            *preds).get_output()
        est = (OpRandomForestClassifier(n_trees=4, max_depth=3) if forest
               else OpLogisticRegression(max_iter=40))
        pred = est.set_input(label, vec).get_output()
        model = Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds).train()
        model.save(f"{tmp}/{name}")

    lrng = np.random.default_rng(3)
    ya = ((x1 + 0.5 * x2 + lrng.normal(0, 0.3, n)) > 0).astype(np.float64)
    yb = ((x1 - 0.5 * x2 + lrng.normal(0, 0.3, n)) > 0).astype(np.float64)
    fit("a", ya, forest=True)
    fit("b", yb, forest=True)
    fit("a_v2", yb, forest=True)   # same-shaped swap candidate for `a`
    fit("c", ya, forest=False)


def _post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


ROWS = [{"x1": 0.3, "x2": -1.2}, {"x1": -0.5, "x2": 0.8}]


def main() -> int:  # noqa: C901 (one linear acceptance script)
    os.environ.setdefault("TRANSMOGRIFAI_PERF_MODEL", "0")
    from transmogrifai_tpu.analysis.retrace import MONITOR
    from transmogrifai_tpu.serving.fleet import FleetConfig, FleetService
    from transmogrifai_tpu.serving.http import serve_fleet
    from transmogrifai_tpu.workflow.serialization import (
        load_warmup_manifest)

    with tempfile.TemporaryDirectory(prefix="fleet-smoke-") as tmp:
        _train_models(tmp)
        cache_dir = f"{tmp}/xla-cache"

        def config() -> FleetConfig:
            return FleetConfig(
                tenants={"gold": {"rate": 100_000, "priority": 1},
                         "trial": {"rate": 40, "burst": 40,
                                   "priority": 0}},
                serving={"max_batch": 8, "batch_wait_ms": 1.0,
                         "max_queue": 256},
                compile_cache=True, compile_cache_dir=cache_dir)

        # -- 1+2: three models, shared programs, COLD start ------------- #
        t0 = time.perf_counter()
        fleet = FleetService(config())
        fleet.add_model("a", f"{tmp}/a")
        before = MONITOR.snapshot()
        fleet.add_model("b", f"{tmp}/b")
        delta_b = MONITOR.delta(before)
        before = MONITOR.snapshot()
        fleet.add_model("c", f"{tmp}/c")
        delta_c = sum(MONITOR.delta(before).values())
        fleet.start()
        fleet.score("a", ROWS, tenant="gold")
        cold_s = time.perf_counter() - t0
        try:
            assert delta_b == {}, \
                f"same-shaped model b re-traced: {delta_b}"
            assert delta_c > 0, "differently-shaped model c compiled 0"
            shared = fleet.pool.report()
            groups = [e for e in shared.values() if len(e["members"]) > 1]
            assert len(shared) == 2 and groups and \
                len(groups[0]["members"]) == 2, shared
            for m in ("a", "b", "c"):
                fleet.score(m, ROWS, tenant="gold")

            # -- 3: mixed HTTP load, quota sheds only the offender ------ #
            server, _ = serve_fleet(fleet, port=0, block=False)
            base = f"http://127.0.0.1:{server.port}"
            health = json.loads(urllib.request.urlopen(
                f"{base}/healthz", timeout=30).read())
            assert health["status"] == "ok", health
            assert health["shared_programs"], health
            counts = {"gold_429": 0, "trial_429": 0, "gold_ok": 0,
                      "trial_ok": 0, "other": 0}
            lock = threading.Lock()

            def client(tenant: str, model: str, stop_at: float) -> None:
                while time.perf_counter() < stop_at:
                    try:
                        _post(f"{base}/score",
                              {"model": model, "rows": ROWS,
                               "tenant": tenant, "deadline_ms": 10_000})
                        key = f"{tenant}_ok"
                    except urllib.error.HTTPError as e:
                        key = (f"{tenant}_429" if e.code == 429
                               else "other")
                    except Exception:
                        key = "other"
                    with lock:
                        counts[key] += 1

            stop_at = time.perf_counter() + 2.0
            specs = (("gold", "a", stop_at), ("gold", "b", stop_at),
                     ("gold", "c", stop_at), ("trial", "c", stop_at),
                     ("trial", "c", stop_at))
            threads = [threading.Thread(target=client, args=args,
                                        name=f"smoke-client-{i}")
                       for i, args in enumerate(specs)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            assert counts["trial_429"] > 0, \
                f"over-quota tenant never shed: {counts}"
            assert counts["gold_429"] == 0, \
                f"in-quota tenant was shed: {counts}"
            assert counts["other"] == 0, counts
            assert counts["gold_ok"] > 0 and counts["trial_ok"] > 0, counts

            # -- 4: rolling swap, zero drops on untouched models -------- #
            errors = {"b": 0, "c": 0}
            served = {"b": 0, "c": 0}
            halt = threading.Event()

            def steady(model: str) -> None:
                while not halt.is_set():
                    try:
                        fleet.score(model, ROWS, tenant="gold",
                                    deadline_ms=10_000)
                        served[model] += 1
                    except Exception:
                        errors[model] += 1

            steady_threads = [threading.Thread(target=steady, args=(m,),
                                               name=f"smoke-steady-{m}")
                              for m in ("b", "c")]
            for th in steady_threads:
                th.start()
            before = MONITOR.snapshot()
            swap = _post(f"{base}/reload",
                         {"model": "a", "model_location": f"{tmp}/a_v2"})
            swap_traces = MONITOR.delta(before)
            time.sleep(0.3)
            halt.set()
            for th in steady_threads:
                th.join()
            assert swap["status"] == "swapped", swap
            assert errors == {"b": 0, "c": 0}, \
                f"rolling swap dropped in-flight requests: {errors}"
            assert served["b"] > 0 and served["c"] > 0, served
            assert swap_traces == {}, \
                f"same-shaped swap candidate re-traced: {swap_traces}"
            new_version = fleet.models()["a"]["model_version"]
            assert new_version == swap["version"], (swap, new_version)
            server.shutdown()
            server.server_close()
        finally:
            fleet.stop()

        # -- 5: warm start over the same artifacts ---------------------- #
        manifest = load_warmup_manifest(f"{tmp}/a")
        assert manifest and manifest.get("warm_s", 0) > 0, manifest
        t0 = time.perf_counter()
        fleet2 = FleetService(config())
        fleet2.add_model("a", f"{tmp}/a")
        fleet2.add_model("b", f"{tmp}/b")
        fleet2.add_model("c", f"{tmp}/c")
        fleet2.start()
        fleet2.score("a", ROWS, tenant="gold")
        warm_s = time.perf_counter() - t0
        try:
            info = fleet2.models()["a"]["versions"][-1]
            assert "compile_cache_saved_s" in info, info
            reg = fleet2._services["a"].registry.to_json()
            assert "serving_compile_cache_saved_s" in reg, sorted(reg)
        finally:
            fleet2.stop()

    print(f"fleet-smoke OK: 3 models / 2 tenants in one process; "
          f"same-shaped pair shares programs (0 new traces, "
          f"{delta_c} own compiles for the odd one); quota shed "
          f"{counts['trial_429']} trial vs 0 gold under load; rolling "
          f"swap dropped 0 in-flight (b={served['b']}, c={served['c']} "
          f"served); cold-start-to-first-score {cold_s:.2f}s uncached "
          f"vs {warm_s:.2f}s with persistent cache + manifest")
    return 0


if __name__ == "__main__":
    sys.exit(main())
