"""Arrow-IPC-style binary framing of the PR-15 columnar request wire.

The compiled row codec killed the per-row JSON pivot inside the service;
this kills the JSON decode in front of it. A frame is length-prefixed
and self-describing — magic, version, flags, a small JSON schema block
(column names/dtypes/null flags, row count, routing fields), then one
contiguous little-endian buffer per column — so the router can route on
the header without touching the payload, and the replica feeds the
buffers straight into `columns_dataset` with zero per-cell work.

Layout (all integers little-endian):

    0   4  magic  b"TMGW"
    4   1  version (1)
    5   1  flags   bit0 = payload buffers little-endian
    6   2  reserved (zero)
    8   4  u32 header length H
    12  H  JSON header: {"n_rows", "model", "tenant", "deadline_ms",
                         "columns": [{"name", "dtype", "nulls",
                                      "nbytes"}, ...]}
    ...    per-column buffers, concatenated in header order; a column
           with nulls leads with a ceil(n_rows/8) validity bitmap
           (bit set = null), then the data buffer

Numeric columns decode to the exact arrays the JSON wire would produce
(same dtype, same IEEE bits), object columns ride as a JSON-array
buffer — so binary-wire scores are bit-identical to JSON-wire scores by
construction, which the framing tests assert.

EVERY malformed frame — short prefix, bad magic, torn payload, hostile
header — raises ``ScoreError("bad_request")``: a client framing bug
must never feed the circuit breaker or the health window (same contract
as a malformed JSON body).
"""

from __future__ import annotations

import json
import math
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from transmogrifai_tpu.serving.batcher import ScoreError

__all__ = ["MAGIC", "WIRE_VERSION", "CONTENT_TYPE", "encode_frame",
           "decode_frame"]

MAGIC = b"TMGW"
WIRE_VERSION = 1
CONTENT_TYPE = "application/x-transmogrifai-columnar"

_FLAG_LE = 0x01

# bounds a hostile header can't push past (frames are request-sized;
# anything bigger is a framing bug, not a workload)
_MAX_ROWS = 10_000_000
_MAX_COLUMNS = 4096
_MAX_NAME = 256

# wire dtype -> numpy struct code (itemsize derived)
_DTYPES: Dict[str, str] = {
    "f64": "f8", "f32": "f4", "i64": "i8", "i32": "i4", "u8": "u1",
    "bool": "u1",
}


def _bad(reason: str) -> ScoreError:
    return ScoreError("bad_request", f"binary frame: {reason}")


def _pack_mask(values: List[Any]) -> bytes:
    mask = bytearray(math.ceil(len(values) / 8) or 0)
    for i, v in enumerate(values):
        if v is None:
            mask[i // 8] |= 1 << (i % 8)
    return bytes(mask)


def encode_frame(columns: Dict[str, Any], model: Optional[str] = None,
                 tenant: Optional[str] = None,
                 deadline_ms: Optional[float] = None) -> bytes:
    """Encode a columnar request. Numeric ndarrays keep their dtype;
    Python lists become f64 (with a null bitmap when Nones are present)
    or, for anything non-numeric, a JSON-array buffer."""
    cols: List[Dict[str, Any]] = []
    buffers: List[bytes] = []
    n_rows: Optional[int] = None
    for name, values in columns.items():
        if isinstance(values, np.ndarray):
            n = int(values.shape[0]) if values.ndim else 1
        else:
            values = list(values)
            n = len(values)
        if n_rows is None:
            n_rows = n
        elif n != n_rows:
            raise ValueError(
                f"ragged columns: {name!r} has {n} rows, expected {n_rows}")
        entry: Dict[str, Any] = {"name": str(name), "nulls": False}
        if isinstance(values, np.ndarray) and values.dtype.kind in "fiub":
            code = {"f8": "f64", "f4": "f32", "i8": "i64", "i4": "i32",
                    "u1": "u8", "b1": "bool"}.get(values.dtype.str[1:])
            if code == "bool":
                values = values.astype(np.uint8)
            elif code is None:
                values = values.astype(np.float64)
                code = "f64"
            buf = np.ascontiguousarray(values).astype(
                values.dtype.newbyteorder("<"), copy=False).tobytes()
            entry["dtype"] = code
        elif all(isinstance(v, (int, float, bool)) or v is None
                 for v in values):
            has_null = any(v is None for v in values)
            arr = np.asarray(
                [0.0 if v is None else float(v) for v in values],
                dtype="<f8")
            buf = (_pack_mask(values) if has_null else b"") + arr.tobytes()
            entry["dtype"] = "f64"
            entry["nulls"] = has_null
        else:
            buf = json.dumps(list(values)).encode("utf-8")
            entry["dtype"] = "json"
        entry["nbytes"] = len(buf)
        cols.append(entry)
        buffers.append(buf)
    header = {
        "n_rows": int(n_rows or 0),
        "model": model,
        "tenant": tenant,
        "deadline_ms": deadline_ms,
        "columns": cols,
    }
    hbytes = json.dumps(header).encode("utf-8")
    head = MAGIC + struct.pack(
        "<BBHI", WIRE_VERSION, _FLAG_LE, 0, len(hbytes))
    return head + hbytes + b"".join(buffers)


def _decode_column(entry: Any, n_rows: int, buf: bytes,
                   byteorder: str) -> Tuple[str, Any]:
    if not isinstance(entry, dict):
        raise _bad("column entry is not an object")
    name = entry.get("name")
    if not isinstance(name, str) or not name or len(name) > _MAX_NAME:
        raise _bad(f"illegal column name {name!r}")
    dtype = entry.get("dtype")
    if dtype == "json":
        try:
            values = json.loads(buf.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            raise _bad(f"column {name!r}: json buffer unparseable")
        if not isinstance(values, list) or len(values) != n_rows:
            raise _bad(f"column {name!r}: json buffer is not a "
                       f"{n_rows}-row array")
        return name, values
    code = _DTYPES.get(dtype) if isinstance(dtype, str) else None
    if code is None:
        raise _bad(f"column {name!r}: unknown dtype {dtype!r}")
    itemsize = int(np.dtype(code).itemsize)
    nulls = bool(entry.get("nulls"))
    mask_bytes = math.ceil(n_rows / 8) if nulls else 0
    if len(buf) != mask_bytes + n_rows * itemsize:
        raise _bad(
            f"column {name!r}: buffer is {len(buf)} bytes, expected "
            f"{mask_bytes + n_rows * itemsize}")
    data = np.frombuffer(buf, dtype=byteorder + code, offset=mask_bytes,
                         count=n_rows)
    if dtype == "bool":
        data = data.astype(bool)
    if not nulls:
        return name, data
    mask = buf[:mask_bytes]
    values = data.tolist()
    for i in range(n_rows):
        if mask[i // 8] & (1 << (i % 8)):
            values[i] = None
    return name, values


def decode_frame(buf: bytes) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(columns, meta) from a frame; meta carries the routing fields
    ("n_rows", "model", "tenant", "deadline_ms"). Raises
    ScoreError("bad_request") on ANY malformation."""
    if not isinstance(buf, (bytes, bytearray, memoryview)):
        raise _bad("payload is not bytes")
    buf = bytes(buf)
    if len(buf) < 12:
        raise _bad(f"truncated prefix ({len(buf)} bytes)")
    if buf[:4] != MAGIC:
        raise _bad("bad magic")
    version, flags, _reserved, header_len = struct.unpack(
        "<BBHI", buf[4:12])
    if version != WIRE_VERSION:
        raise _bad(f"unsupported version {version}")
    if header_len <= 0 or 12 + header_len > len(buf):
        raise _bad(f"header length {header_len} exceeds frame")
    try:
        header = json.loads(buf[12:12 + header_len].decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        raise _bad("header is not valid JSON")
    if not isinstance(header, dict):
        raise _bad("header is not an object")
    n_rows = header.get("n_rows")
    if not isinstance(n_rows, int) or isinstance(n_rows, bool) \
            or not 0 <= n_rows <= _MAX_ROWS:
        raise _bad(f"illegal n_rows {n_rows!r}")
    entries = header.get("columns")
    if not isinstance(entries, list) or len(entries) > _MAX_COLUMNS:
        raise _bad("illegal columns table")
    byteorder = "<" if (flags & _FLAG_LE) else ">"
    columns: Dict[str, Any] = {}
    offset = 12 + header_len
    for entry in entries:
        nbytes = entry.get("nbytes") if isinstance(entry, dict) else None
        if not isinstance(nbytes, int) or isinstance(nbytes, bool) \
                or nbytes < 0:
            raise _bad(f"illegal column nbytes {nbytes!r}")
        if offset + nbytes > len(buf):
            raise _bad("torn frame: column buffers exceed payload")
        name, values = _decode_column(
            entry, n_rows, buf[offset:offset + nbytes], byteorder)
        if name in columns:
            raise _bad(f"duplicate column {name!r}")
        columns[name] = values
        offset += nbytes
    if offset != len(buf):
        raise _bad(f"{len(buf) - offset} trailing bytes after columns")
    meta = {
        "n_rows": n_rows,
        "model": header.get("model"),
        "tenant": header.get("tenant"),
        "deadline_ms": header.get("deadline_ms"),
    }
    return columns, meta
