"""Chaos harness: deterministic fault storms against a live fleet.

"Graceful degradation" is a claim; this module makes it falsifiable.
It boots the standard 3-model / 2-tenant fleet (`serving/fleet.py`)
with the resilience layer tuned for fast transitions, drives live
traffic, and injects seeded `FaultPlan` storms at the serving fault
sites (`runtime/faults`):

1. **Device-error storm** on one member: consecutive dispatch failures
   trip its circuit breaker (HEALTHY → QUARANTINED), degraded-mode
   fallback serves from the resident PREVIOUS version while the breaker
   is open (responses carry the old version id — provable), half-open
   probes close the breaker once the storm exhausts, and the measured
   MTTR lands in the report. The untouched members' traffic must
   complete with ZERO errors and bounded p99.
2. **Killed scoring thread**: an injected `kill` (a BaseException, like
   a real fatal runtime error) kills the member's scoring thread
   mid-batch; the watchdog restarts it and every in-flight request is
   ANSWERED (structured error, never a hang).
3. **Stalled dispatch**: an injected `delay` wedges the scoring loop
   past `watchdog_stall_s`; clients get answers within the stall budget
   (+ one watchdog period), not after the multi-second hang.
4. **Corrupt reload under traffic**: a bit-flipped artifact is rejected
   by integrity verification while the resident version keeps serving
   concurrent traffic error-free (PR-4 behavior, now asserted under
   load).
5. **Crashing continual cycle** (`run_continual_crash`): an injected
   kill escapes a continual cycle's own handling; the supervisor
   restarts (`continual_supervisor_restarts_total`) and the NEXT cycle
   completes — used by ``python bench.py chaos``.

6. **Overload storm** (`run_storm`, ``--storm``): a seeded flood plus
   an injected dispatch delay overload one member beyond what the
   static config (queue bound + priority shed) can absorb — the gold
   tenant's availability SLO burns until the flood stops. The same
   storm against an autopilot fleet (`serving/autopilot.py`) must be
   DAMPED: the controller climbs its actuation ladder (rebucket
   re-arm, fidelity flip to the resident int8 member, predictive
   admission, warm spare), gold availability and p99 beat the static
   arm, and every actuation is released after the storm.

7. **Fleet-observability storm** (``--fleet``): delegates to
   `serving/fleetobs_smoke.py` — a seeded error storm against TWO
   replica processes sharing one store must fire the FLEET
   availability alert exactly once (CAS-latch dedup, not once per
   replica), clear it, and leave ONE merged cross-host incident
   artifact.

`make chaos-smoke` runs ``main()`` (scenarios 1-4 with hard
assertions); `make autopilot-smoke` runs ``storm_main()`` (scenario 6,
static arm vs autopilot arm); `make fleetobs-smoke` runs scenario 7;
``python bench.py chaos`` reuses `run_chaos` + `run_continual_crash`
and emits per-tenant availability, p99, breaker transition counts,
MTTR, and the goodput resilience section into the bench payload;
``python bench.py autopilot`` emits the storm comparison.

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.serving.chaos``
(``--storm`` for the autopilot acceptance, ``--fleet`` for the
fleet-observability acceptance)
"""

from __future__ import annotations

import logging
import os
import shutil
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

D = 3          # features per model
_MAX_BATCH = 4  # small ladder: chaos exercises failure paths, not shapes

ROW = {f"x{j}": 0.2 * (j + 1) for j in range(D)}


def _train_models(tmp: str) -> Dict[str, str]:
    """Four small logistic pipelines: members a/b/c plus a_v2, the
    same-shaped swap candidate that gives member `a` its resident
    rollback chain (the degraded-fallback target)."""
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(23)
    n = 160
    X = rng.normal(size=(n, D))
    beta = rng.normal(size=D)

    def fit(name: str, y: np.ndarray) -> str:
        ds = Dataset({**{f"x{j}": X[:, j] for j in range(D)}, "y": y},
                     {**{f"x{j}": t.Real for j in range(D)},
                      "y": t.Integral})
        preds, label = FeatureBuilder.from_dataset(ds, response="y")
        vec = RealVectorizer(track_nulls=False).set_input(
            *preds).get_output()
        pred = OpLogisticRegression(max_iter=40).set_input(
            label, vec).get_output()
        Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds).train().save(f"{tmp}/{name}")
        return f"{tmp}/{name}"

    return {
        "a": fit("a", (X @ beta > 0).astype(np.float64)),
        "a_v2": fit("a_v2", (X @ beta > 0.3).astype(np.float64)),
        "b": fit("b", (X @ -beta > 0).astype(np.float64)),
        "c": fit("c", (X @ beta > -0.3).astype(np.float64)),
    }


class _LoadClient(threading.Thread):
    """Steady in-process traffic to one (tenant, model): records ok /
    error counts, latencies, and the serving version of each response
    (how the fallback-serves-the-previous-version claim is proven)."""

    def __init__(self, fleet, tenant: str, model: str, idx: int,
                 rows: int = 1, pace: float = 0.004,
                 deadline_ms: float = 10_000):
        super().__init__(daemon=True, name=f"chaos-client-{idx}")
        self.fleet = fleet
        self.tenant = tenant
        self.model = model
        self.idx = idx
        self.n_rows = rows
        self.pace = pace
        self.deadline_ms = deadline_ms
        self.ok = 0
        self.errors: List[str] = []
        self.latencies: List[float] = []
        self.versions: Dict[str, int] = {}
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.is_set():
            t0 = time.perf_counter()
            try:
                res = self.fleet.score(self.model,
                                       [dict(ROW)
                                        for _ in range(self.n_rows)],
                                       tenant=self.tenant,
                                       deadline_ms=self.deadline_ms)
                self.ok += 1
                self.latencies.append(time.perf_counter() - t0)
                self.versions[res.model_version] = \
                    self.versions.get(res.model_version, 0) + 1
            except Exception as e:
                # an error answer still took this long to arrive: the
                # latency distribution is time-to-ANSWER, not
                # time-to-success (a deadline drop that surfaces after
                # 600 ms in queue IS the client's tail)
                self.latencies.append(time.perf_counter() - t0)
                self.errors.append(
                    f"{getattr(e, 'code', type(e).__name__)}: {e}"[:120])
            time.sleep(self.pace)

    def stop(self) -> None:
        self._halt.set()

    def mark(self) -> Dict[str, int]:
        """Counter snapshot for phase-scoped stats (`_stats_since`)."""
        return {"ok": self.ok, "errors": len(self.errors),
                "latencies": len(self.latencies)}

    def stats(self) -> Dict[str, Any]:
        import numpy as np
        total = self.ok + len(self.errors)
        lat = np.asarray(self.latencies) if self.latencies \
            else np.zeros(1)
        return {
            "tenant": self.tenant, "model": self.model,
            "requests": total, "ok": self.ok,
            "errors": len(self.errors),
            "error_sample": self.errors[:3],
            "availability": round(self.ok / total, 4) if total else 1.0,
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "versions": dict(self.versions),
        }


def _wait_state(fleet, member: str, state: str,
                timeout_s: float = 15.0) -> bool:
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout_s:
        health = fleet.models()[member].get("health") or {}
        if health.get("state") == state:
            return True
        time.sleep(0.02)
    return False


def _wait_slo(fleet, name: str, firing: bool,
              timeout_s: float = 10.0) -> bool:
    """Poll the fleet SLO engine until `name` is (not) firing."""
    if fleet.slo_engine is None:
        return False
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout_s:
        if (name in fleet.slo_engine.firing()) == firing:
            return True
        time.sleep(0.02)
    return False


def _flight_proof(dumps_before: int) -> Dict[str, Any]:
    """The breaker-open flight dump, validated: it must exist, parse as
    a VALID Chrome trace, and contain the failing device-dispatch
    spans that caused the incident (the 30-seconds-before story)."""
    import json

    from transmogrifai_tpu.obs import flight
    from transmogrifai_tpu.obs.export import validate_chrome_trace

    dumps = flight.get_recorder().dumps[dumps_before:]
    breaker = [d for d in dumps if d.endswith("breaker_open")]
    out: Dict[str, Any] = {"dumps": len(dumps),
                           "breaker_dump": bool(breaker)}
    if not breaker:
        return out
    path = breaker[0]
    out["path"] = path
    try:
        with open(os.path.join(path, "trace.json"),
                  encoding="utf-8") as fh:
            trace = json.load(fh)
        problems = validate_chrome_trace(trace)
        out["valid_chrome_trace"] = not problems
        out["problems"] = problems[:3]
        out["failing_dispatch_spans"] = sum(
            1 for ev in trace.get("traceEvents", [])
            if ev.get("ph") == "X"
            and ev.get("name") == "serving:device_dispatch"
            and ev.get("args", {}).get("error"))
    except Exception as e:
        out["valid_chrome_trace"] = False
        out["problems"] = [f"{type(e).__name__}: {e}"]
    return out


def _corrupt_copy(src: str, dst: str) -> str:
    """Copy a sealed model artifact and flip bytes in one payload file
    (never integrity.json itself — the manifest must DETECT the flip)."""
    shutil.copytree(src, dst)
    for name in sorted(os.listdir(dst)):
        if name in ("integrity.json", "warmup.json"):
            continue
        path = os.path.join(dst, name)
        if os.path.isfile(path) and os.path.getsize(path) > 0:
            with open(path, "r+b") as fh:
                first = fh.read(1)
                fh.seek(0)
                fh.write(bytes([first[0] ^ 0xFF]))
            return path
    raise RuntimeError(f"no corruptible payload file in {dst}")


def run_chaos(dirs: Dict[str, str], seed: int = 0,
              load_s: float = 3.0,
              flight_dir: Optional[str] = None) -> Dict[str, Any]:
    """Scenarios 1-4 against one fleet; returns the falsifiability
    report (see module docstring). `dirs` maps a/a_v2/b/c to trained
    artifact dirs (`_train_models`).

    The storm scenario also proves the PR-14 observability loop: the
    fleet runs an availability SLO (time-scaled burn windows so a
    seconds-long storm exercises the same multi-window machinery a
    real outage would) whose alert must FIRE during the storm and
    CLEAR after recovery, and the breaker-open flight dump must
    contain the failing dispatch spans and validate as a Chrome
    trace."""
    from transmogrifai_tpu.obs import flight
    from transmogrifai_tpu.obs.goodput import build_report
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.runtime.faults import (
        SITE_DEVICE_DISPATCH, FaultPlan, FaultSpec)
    from transmogrifai_tpu.serving.fleet import FleetConfig, FleetService
    from transmogrifai_tpu.workflow.serialization import model_fingerprint

    resilience = {
        "window": 32, "min_window": 8,
        "breaker_failures": 3, "half_open_after_s": 0.25,
        "probe_successes": 1,
        "watchdog_period_s": 0.05, "watchdog_stall_s": 0.75,
    }
    # availability SLO over the gold tenant, burn windows scaled so the
    # fast pair is ~2.4s/1.2s wall: a storm lasting a second+ burns the
    # 0.1% budget orders of magnitude too fast -> both windows trip
    slo = {
        "slos": [{"name": "gold-availability",
                  "kind": "availability", "objective": 0.999,
                  "tenant": "gold"}],
        "windows": [[2.4, 1.2, 2.0, "page"]],
        "time_scale": 1.0, "eval_period_s": 0.05,
    }
    config = FleetConfig(
        models={"a": dirs["a"], "b": dirs["b"], "c": dirs["c"]},
        tenants={"gold": {"priority": 1}, "trial": {"priority": 0}},
        serving={"max_batch": _MAX_BATCH, "batch_wait_ms": 1.0,
                 "max_queue": 256},
        resilience=resilience, slo=slo)
    if flight_dir:
        flight.get_recorder().configure(dump_dir=flight_dir,
                                        min_interval_s=0.0)
    report: Dict[str, Any] = {"resilience_params": resilience,
                              "slo_params": slo}
    with TRACER.span("run:chaos", category="run", new_trace=True) as root:
        fleet = FleetService(config).start()
        try:
            v_a_old = model_fingerprint(dirs["a"])
            # the rollback chain the degraded fallback rides: member a
            # now holds [a, a_v2] with a_v2 active
            swap = fleet.reload_model("a", dirs["a_v2"])
            assert swap["status"] == "swapped", swap
            v_a_new = swap["version"]

            # -- scenario 1: device-error storm on member a ------------- #
            clients = [_LoadClient(fleet, "gold", "a", 0),
                       _LoadClient(fleet, "gold", "b", 1),
                       _LoadClient(fleet, "trial", "c", 2)]
            for c in clients:
                c.start()
            time.sleep(0.4)  # clean baseline traffic first
            storm = FaultPlan(
                [FaultSpec(site=f"{SITE_DEVICE_DISPATCH}#a", at=1,
                           times=8, kind="error")], seed=seed)
            dumps_before = len(flight.get_recorder().dumps)
            t_storm = time.perf_counter()
            with storm.active():
                slo_fired = _wait_slo(fleet, "gold-availability",
                                      firing=True, timeout_s=10.0)
                slo_alert_s = (time.perf_counter() - t_storm
                               if slo_fired else None)
                quarantined = _wait_state(fleet, "a", "quarantined",
                                          timeout_s=10.0)
                recovered = _wait_state(fleet, "a", "healthy",
                                        timeout_s=15.0)
            recovery_wall = time.perf_counter() - t_storm
            # the alert must CLEAR after recovery: healthy traffic keeps
            # flowing while the bad samples age out of the burn windows
            t_clear0 = time.perf_counter()
            slo_cleared = _wait_slo(fleet, "gold-availability",
                                    firing=False, timeout_s=15.0)
            slo_clear_s = (time.perf_counter() - t_clear0
                           if slo_cleared else None)
            elapsed = time.perf_counter() - t_storm
            time.sleep(max(0.2, load_s - elapsed - 0.4))
            for c in clients:
                c.stop()
            for c in clients:
                c.join(timeout=5)
            report["slo"] = {
                "fired": slo_fired, "cleared": slo_cleared,
                "alert_s": (round(slo_alert_s, 4)
                            if slo_alert_s is not None else None),
                "clear_s": (round(slo_clear_s, 4)
                            if slo_clear_s is not None else None),
                "status": (fleet.slo_engine.status()["slos"]
                           ["gold-availability"]
                           if fleet.slo_engine else None),
            }
            report["flight"] = _flight_proof(dumps_before)
            a_health = fleet.models()["a"]["health"]
            member_a = fleet._services["a"]
            fallback_series = member_a.registry.to_json().get(
                "serving_degraded_fallback_total", {"series": []})["series"]
            fallback_n = int(sum(s.get("value", 0)
                                 for s in fallback_series))
            mttrs = [t.get("recovery_s") for t in a_health["transitions"]
                     if t.get("recovery_s") is not None]
            report["storm"] = {
                "member": "a", "fired": len(storm.fired),
                "quarantined": quarantined, "recovered": recovered,
                "breaker_opens": a_health["breaker_opens"],
                "breaker_closes": a_health["breaker_closes"],
                "transitions": a_health["transitions"],
                "mttr_s": (round(float(mttrs[-1]), 4) if mttrs else None),
                "fallback_requests": fallback_n,
                "fallback_version_responses":
                    clients[0].versions.get(v_a_old, 0),
                "active_version_before": v_a_new,
                "fallback_version": v_a_old,
            }
            report["tenants"] = {f"{c.tenant}:{c.model}": c.stats()
                                 for c in clients}

            # -- scenario 2: killed scoring thread on member b ---------- #
            report["kill"] = _run_thread_death(
                fleet, "b", FaultPlan(
                    [FaultSpec(site=f"{SITE_DEVICE_DISPATCH}#b", at=1,
                               kind="kill")], seed=seed))

            # -- scenario 3: stalled dispatch on member c --------------- #
            stall_budget = resilience["watchdog_stall_s"]
            report["stall"] = _run_thread_death(
                fleet, "c", FaultPlan(
                    [FaultSpec(site=f"{SITE_DEVICE_DISPATCH}#c", at=1,
                               kind="delay", delay_s=3.0)], seed=seed),
                stall_budget_s=stall_budget)
            # give the stale (sleeping) thread time to wake and exit
            # before scenario 4's traffic lands on the same fleet
            time.sleep(0.3)

            # -- scenario 4: corrupt reload under concurrent traffic ---- #
            corrupt_dir = os.path.join(
                os.path.dirname(dirs["b"]), "b_corrupt")
            flipped = _corrupt_copy(dirs["b"], corrupt_dir)
            steady = _LoadClient(fleet, "gold", "b", 9)
            steady.start()
            time.sleep(0.2)
            v_b = fleet.models()["b"]["model_version"]
            rejected: Optional[str] = None
            try:
                fleet.reload_model("b", corrupt_dir)
            except Exception as e:
                rejected = f"{type(e).__name__}: {e}"[:160]
            time.sleep(0.3)
            steady.stop()
            steady.join(timeout=5)
            report["reload"] = {
                "flipped_file": os.path.basename(flipped),
                "rejected": rejected is not None,
                "rejection": rejected,
                "resident_version_kept":
                    fleet.models()["b"]["model_version"] == v_b,
                "traffic": steady.stats(),
            }
        finally:
            fleet.stop()
    gp = build_report(root, TRACER.trace_spans(root.trace_id)).to_json()
    report["goodput_resilience"] = gp.get("resilience") or {}
    report["goodput_slo"] = gp.get("slo") or {}
    return report


def _run_thread_death(fleet, member: str, plan,
                      stall_budget_s: Optional[float] = None
                      ) -> Dict[str, Any]:
    """One request into an injected thread-death (kill) or wedge
    (delay): the client MUST be answered (response or structured error,
    never a hang), the watchdog must restart the loop, and the next
    request must score normally."""
    from transmogrifai_tpu.serving.batcher import ScoreError

    svc = fleet._services[member]
    before = _restart_count(svc)
    outcome: Dict[str, Any] = {}

    def fire() -> None:
        t0 = time.perf_counter()
        try:
            fleet.score(member, [dict(ROW)], tenant="gold",
                        deadline_ms=10_000)
            outcome["answer"] = "scored"
        except ScoreError as e:
            outcome["answer"] = e.code
        except Exception as e:  # pragma: no cover - diagnostics only
            outcome["answer"] = f"{type(e).__name__}"
        outcome["answered_in_s"] = round(time.perf_counter() - t0, 4)

    with plan.active():
        th = threading.Thread(target=fire, name=f"chaos-{member}-victim")
        th.start()
        th.join(timeout=10.0)
        hung = th.is_alive()
        # wait for the watchdog restart to land before clearing the plan
        t0 = time.perf_counter()
        while _restart_count(svc) == before and \
                time.perf_counter() - t0 < 5.0:
            time.sleep(0.02)
    restarts = _restart_count(svc) - before
    # post-recovery: the member must score again
    recovered = None
    for _ in range(40):
        try:
            fleet.score(member, [dict(ROW)], tenant="gold",
                        deadline_ms=10_000)
            recovered = True
            break
        except Exception:
            recovered = False
            time.sleep(0.05)
    out = {"member": member, "hung": hung, "restarts": restarts,
           "recovered": bool(recovered), **outcome}
    if stall_budget_s is not None:
        period = svc.resilience.watchdog_period_s
        out["stall_budget_s"] = stall_budget_s
        out["within_budget"] = (
            not hung and outcome.get("answered_in_s", 99.0)
            <= stall_budget_s + 4 * period + 0.5)
    return out


def _restart_count(svc) -> int:
    series = svc.registry.to_json().get(
        "serving_watchdog_restarts_total", {"series": []})["series"]
    return int(sum(s.get("value", 0) for s in series))


def run_continual_crash(tmp: str) -> Dict[str, Any]:
    """Scenario 5 (bench): an injected kill escapes a continual cycle's
    own handling mid-flight; the supervisor restarts under backoff and
    the NEXT cycle still runs — continual training must never silently
    stop. Returns {supervisor_restarts, next_cycle_ran, ...}."""
    import numpy as np

    from transmogrifai_tpu.continual import ContinualLoop, ContinualParams
    from transmogrifai_tpu.data.columnar_store import ColumnarStore
    from transmogrifai_tpu.obs.metrics import get_registry
    from transmogrifai_tpu.runtime.faults import (
        SITE_HOLDOUT_EVAL, FaultPlan, FaultSpec, InjectedKill)

    rng = np.random.default_rng(29)
    n, d = 600, 4
    beta = rng.normal(size=d)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ beta > 0).astype(np.float32)
    w = ColumnarStore.create(f"{tmp}/chaos-store", n, d, dtype="float32")
    w.write_chunk(0, X, y)
    store = w.close()
    params = ContinualParams(window_rows=512, min_window_rows=128,
                             check_interval_s=0.1)
    loop = ContinualLoop(store, f"{tmp}/chaos-model", params=params,
                         seed=29)
    loop.train_initial()

    cycles: List[str] = []
    real_cycle = loop.run_cycle

    def cycle_with_kill():
        from transmogrifai_tpu.runtime.faults import fault_point
        fault_point(SITE_HOLDOUT_EVAL)
        result = real_cycle()
        cycles.append(result["status"])
        return result

    loop.run_cycle = cycle_with_kill
    reg = get_registry()

    def restarts() -> int:
        series = reg.to_json().get(
            "continual_supervisor_restarts_total",
            {"series": []})["series"]
        return int(sum(s.get("value", 0) for s in series))

    before = restarts()
    plan = FaultPlan([FaultSpec(site=SITE_HOLDOUT_EVAL, at=1,
                                kind="kill")])
    loop.start()
    try:
        with plan.active():
            loop._wake.set()
            t0 = time.perf_counter()
            while restarts() == before and \
                    time.perf_counter() - t0 < 10.0:
                time.sleep(0.05)
        # the restarted supervisor's next poll must complete a cycle
        t0 = time.perf_counter()
        while not cycles and time.perf_counter() - t0 < 10.0:
            loop._wake.set()
            time.sleep(0.05)
    finally:
        loop.stop()
    return {
        "supervisor_restarts": restarts() - before,
        "next_cycle_ran": bool(cycles),
        "next_cycle_status": cycles[0] if cycles else None,
        "kill_type": InjectedKill.__name__,
    }


# --------------------------------------------------------------------------- #
# overload storm: static config vs the serving autopilot (PR 19)
# --------------------------------------------------------------------------- #

# the pinned cost model's per-batch latency slope AND the injected
# per-dispatch delay: the storm's physics must not depend on how fast
# THIS host happens to score, or the smoke flakes on slow CI
_STORM_BATCH_S = 0.05


def _storm_cost_model():
    """Pin a deterministic warm cost model (per-batch latency
    ``_STORM_BATCH_S * bucket``): a dozen-deep queue at bucket 4
    predicts a ~0.6 s drain against the 0.3 s deadline budget —
    pressure clamps to 1.0, far past the 0.5 shed watermark — so
    predictive admission has an unambiguous signal. Caller must
    ``perf_model.set_model(None)`` when done."""
    from transmogrifai_tpu.perf import model as perf_model
    m = perf_model.CostModel(min_rows=8)
    for _ in range(12):
        for b in (1, 2, _MAX_BATCH):
            m.observe("serving_bucket", {"bucket": float(b)},
                      _STORM_BATCH_S * b)
    perf_model.set_model(m)
    return m


def _collect_autopilot_events(dumps: List[str]) -> List[Dict[str, Any]]:
    """autopilot_actuation events parsed from flight-dump artifacts
    (the in-memory ring evicts under sustained traffic; the dumps each
    engage wrote — plus the forced end-of-storm dump — are the durable
    record), deduped across overlapping ring snapshots, oldest first."""
    import json
    seen: Dict[Any, Dict[str, Any]] = {}
    for d in dumps:
        try:
            with open(os.path.join(d, "events.jsonl"),
                      encoding="utf-8") as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    rec = json.loads(line)
                    if rec.get("name") != "autopilot_actuation":
                        continue
                    key = (rec.get("ts_s"), rec.get("action"),
                           rec.get("transition"), rec.get("model"))
                    seen[key] = rec
        except OSError:
            continue
    return [seen[k] for k in sorted(seen, key=lambda k: k[0] or 0.0)]


def _stats_since(clients: List[_LoadClient],
                 marks: Dict[_LoadClient, Dict[str, int]]) -> Dict[str, Any]:
    """Aggregate stats over the requests `clients` completed since
    their `mark()` snapshots — the storm arms are compared on the
    late-storm window, not whole-run numbers that average the healthy
    baseline in. The latency distribution is time-to-ANSWER: error
    answers count, or the failing arm would report a rosy p99 from its
    one lucky success."""
    import numpy as np
    ok = sum(c.ok - marks[c]["ok"] for c in clients)
    errors = sum(len(c.errors) - marks[c]["errors"] for c in clients)
    lats: List[float] = []
    for c in clients:
        lats.extend(c.latencies[marks[c]["latencies"]:])
    lat = np.asarray(lats) if lats else np.zeros(1)
    total = ok + errors
    return {
        "tenant": clients[0].tenant, "model": clients[0].model,
        "requests": total, "ok": ok, "errors": errors,
        "availability": round(ok / total, 4) if total else 1.0,
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
    }


def _shed_by_reason(fleet) -> Dict[str, int]:
    shed: Dict[str, int] = {}
    for s in fleet.registry.to_json().get(
            "fleet_shed_total", {"series": []})["series"]:
        reason = (s.get("labels") or {}).get("reason", "?")
        shed[reason] = shed.get(reason, 0) + int(s.get("value", 0))
    return shed


def run_storm(dirs: Dict[str, str], autopilot: bool = True,
              seed: int = 0, flood_s: float = 2.0,
              flight_dir: Optional[str] = None) -> Dict[str, Any]:
    """One seeded OVERLOAD storm against one fleet. ``autopilot=False``
    is the static-config control arm; ``autopilot=True`` the treatment.

    Unlike `run_chaos` this storm is load, not faults: an injected
    per-dispatch delay caps member `a`'s drain rate while low-priority
    flood clients keep its queue deep. The static config's own graded
    priority shedding DOES keep gold admitted (that is PR-13 working)
    — but admitted is not served: the queue's drain time under the
    delay is ~2x the gold tenant's deadline, so every admitted gold
    request expires in queue (``deadline_exceeded``), device time is
    burned on answers nobody is waiting for, and the availability SLO
    burns until the flood stops. That is the overload shape a static
    config cannot damp — no admission threshold on OBSERVED depth
    helps when the queue is short but slow. The autopilot arm must
    climb the actuation ladder — rebucket re-arm, fidelity flip to the
    resident int8-calibrated member (no injected delay: the overload
    is member-a physics, and the flip routes around it with no compile
    and no dropped request), predictive admission shedding the flood
    because PREDICTED drain time exceeds the deadline budget,
    warm-spare activation — then walk it back down after the storm.

    Caller owns the pinned deterministic cost model
    (`_storm_cost_model`) and the perf-model env. Returns the per-arm
    report; `storm_main` compares the two arms."""
    from transmogrifai_tpu.obs import flight
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.runtime.faults import (
        SITE_DEVICE_DISPATCH, FaultPlan, FaultSpec)
    from transmogrifai_tpu.serving.fleet import FleetConfig, FleetService

    slo = {
        "slos": [{"name": "gold-availability",
                  "kind": "availability", "objective": 0.999,
                  "tenant": "gold"}],
        "windows": [[2.4, 1.2, 2.0, "page"]],
        "time_scale": 1.0, "eval_period_s": 0.05,
    }
    config = FleetConfig(
        models={"a": dirs["a"], "b": dirs["b"],
                # the resident int8-calibrated sibling the fidelity
                # rung flips routes to: same artifact, quantized build
                # (distinct programs; both stay resident)
                "a_int8": {"path": dirs["a"],
                           "serving": {"quantize": "int8-calibrated"}}},
        tenants={"gold": {"priority": 1}, "trial": {"priority": 0}},
        # the deadline budget is the pressure denominator: a queue
        # whose PREDICTED drain exceeds ~300ms is already failing its
        # gold clients even though its observed depth looks fine
        serving={"max_batch": _MAX_BATCH, "batch_wait_ms": 1.0,
                 "max_queue": 16, "auto_ladder": True,
                 "default_deadline_ms": 300.0},
        slo=slo,
        # release_burn 0.25: the 2.4s long window holds storm errors
        # past the comparison window, so a cure that DILUTES the short
        # window (gold healthy again) cannot release the ladder while
        # the flood is still on — release happens in recovery
        autopilot=({"period_s": 0.05, "min_dwell_s": 0.2,
                    "engage_burn": 1.0, "release_burn": 0.25,
                    "release_hold_s": 1.0,
                    "rebucket_cooldown_s": 0.5,
                    "fidelity": {"a": "a_int8"},
                    "admission_headroom": 1.0,
                    "spare": {"name": "a_spare", "path": dirs["a_v2"]}}
                   if autopilot else None))
    if flight_dir:
        # a storm's span volume would scroll actuation events out of
        # the default ring before the post-run read
        flight.get_recorder().configure(dump_dir=flight_dir,
                                        capacity=65536,
                                        min_interval_s=0.0)
    dumps_before = len(flight.get_recorder().dumps)
    report: Dict[str, Any] = {"arm": "autopilot" if autopilot
                              else "static"}
    with TRACER.span("run:storm", category="run", new_trace=True):
        fleet = FleetService(config).start()
        try:
            # gold's deadline: comfortable against a healthy member
            # (single-digit ms), fatal against the delayed queue. A
            # POOL of gold clients, not one: a single client stuck in a
            # ~600ms request cycle gives the 1.2s short burn window ~2
            # samples and the burn estimate flickers across the release
            # threshold — four staggered clients keep the error-rate
            # estimate dense enough to hold the ladder engaged
            gold_a = [_LoadClient(fleet, "gold", "a", i, deadline_ms=300)
                      for i in range(4)]
            gold_b = _LoadClient(fleet, "gold", "b", 8)
            gold = [*gold_a, gold_b]
            for c in gold:
                c.start()
            # -- healthy phase: the controller must do NOTHING -------- #
            time.sleep(0.6)
            if fleet.autopilot is not None:
                st = fleet.autopilot.status()
                report["healthy"] = {"actuations": st["actuations"],
                                     "rung": st["rung"]}
            marks = {c: c.mark() for c in gold}
            # -- flood: trial tenant offers ~100x member a's capacity - #
            flood = [_LoadClient(fleet, "trial", "a", 10 + i,
                                 rows=_MAX_BATCH, pace=0.004)
                     for i in range(12)]
            storm = FaultPlan(
                [FaultSpec(site=f"{SITE_DEVICE_DISPATCH}#a", at=1,
                           times=1_000_000, kind="delay",
                           delay_s=_STORM_BATCH_S)], seed=seed)
            t0 = time.perf_counter()
            with storm.active():
                for c in flood:
                    c.start()
                fired = _wait_slo(fleet, "gold-availability", True,
                                  timeout_s=10.0)
                # control-latency allowance: the ladder climbs one rung
                # per dwell window; the static arm gets the SAME grace,
                # then the arms are compared over the late-storm window
                # (the flood is still on — a static config is still
                # failing here, a controller must not be)
                time.sleep(1.5)
                late = {c: c.mark() for c in gold}
                time.sleep(flood_s)
                report["storm"] = {
                    "slo_fired": fired,
                    "flood_s": round(time.perf_counter() - t0, 3),
                    "gold_a": _stats_since(gold_a, late),
                    "gold_b": _stats_since([gold_b], late),
                    "gold_a_whole_storm": _stats_since(gold_a, marks),
                }
                for c in flood:
                    c.stop()
                for c in flood:
                    c.join(timeout=5)
            # -- recovery: burn clears, the ladder walks back down ---- #
            report["slo_cleared"] = _wait_slo(
                fleet, "gold-availability", False, timeout_s=20.0)
            if fleet.autopilot is not None:
                rung0 = False
                t1 = time.perf_counter()
                while time.perf_counter() - t1 < 25.0:
                    if fleet.autopilot.status()["rung"] == 0:
                        rung0 = True
                        break
                    time.sleep(0.05)
                health = fleet.health()
                report["release"] = {
                    "rung0": rung0,
                    "fidelity_routes":
                        health.get("fidelity_routes") or {},
                    "pressure_a": fleet.router.pressure("a"),
                    "spare_hosted": "a_spare" in fleet._live_services(),
                }
                # durable record of the release events before the ring
                # scrolls them out under post-storm traffic
                flight.request_dump("storm_end", force=True)
            for c in gold:
                c.stop()
            for c in gold:
                c.join(timeout=5)
            report["tenants"] = {f"{c.tenant}:{c.model}:{c.idx}": c.stats()
                                 for c in (*gold, *flood)}
            report["shed"] = _shed_by_reason(fleet)
            if fleet.autopilot is not None:
                report["autopilot"] = fleet.autopilot.status()
                new_dumps = flight.get_recorder().dumps[dumps_before:]
                report["events"] = _collect_autopilot_events(new_dumps)
                report["flight_dumps"] = [os.path.basename(d)
                                          for d in new_dumps]
        finally:
            fleet.stop()
    return report


def storm_main() -> int:  # noqa: C901 (one linear acceptance script)
    """``python -m transmogrifai_tpu.serving.chaos --storm`` — the
    autopilot acceptance: the same seeded storm is driven at a static
    fleet and an autopilot fleet, and the controller must measurably
    damp what the static config cannot (`make autopilot-smoke`)."""
    # predictive admission needs the perf model ON (chaos `main` turns
    # it off; the storm is the one chaos path that requires it)
    os.environ["TRANSMOGRIFAI_PERF_MODEL"] = "1"
    from transmogrifai_tpu.perf import model as perf_model
    with tempfile.TemporaryDirectory(prefix="storm-smoke-") as tmp:
        os.environ.setdefault("TRANSMOGRIFAI_PERF_CORPUS_DIR",
                              os.path.join(tmp, "perf-corpus"))
        dirs = _train_models(tmp)
        _storm_cost_model()
        try:
            static = run_storm(dirs, autopilot=False, seed=0,
                               flight_dir=os.path.join(tmp, "flight"))
            auto = run_storm(dirs, autopilot=True, seed=0,
                             flight_dir=os.path.join(tmp, "flight"))
        finally:
            perf_model.set_model(None)
        try:
            s_gold = static["storm"]["gold_a"]
            a_gold = auto["storm"]["gold_a"]
            assert static["storm"]["slo_fired"], static["storm"]
            assert s_gold["availability"] < 0.9, \
                f"storm did not hurt the static arm: {s_gold}"
            # zero actuations on a healthy fleet
            assert auto["healthy"]["actuations"] == 0 \
                and auto["healthy"]["rung"] == 0, auto["healthy"]
            evs = auto["events"]
            assert evs, "autopilot made no actuations under storm"
            missing = [e for e in evs if "burn_window" not in e]
            assert not missing, \
                f"actuation events without a burn window: {missing}"
            engages = [e for e in evs
                       if e.get("transition") == "engage"]
            assert engages and all(e.get("burn_window")
                                   for e in engages), engages
            fid = [e for e in engages if e.get("action") == "fidelity"]
            shed_pred = auto["shed"].get("shed_predictive", 0)
            assert fid or shed_pred > 0, \
                f"neither fidelity downshift nor predictive shed " \
                f"fired: {engages} {auto['shed']}"
            # the headline: the controller damps what static cannot
            assert a_gold["availability"] > s_gold["availability"], \
                f"controller did not improve gold availability: " \
                f"{a_gold} vs {s_gold}"
            assert a_gold["p99_ms"] < s_gold["p99_ms"], \
                f"controller did not damp gold p99: {a_gold} vs {s_gold}"
            # full release: every actuation reversed after the storm
            rel = auto["release"]
            assert rel["rung0"] and not rel["fidelity_routes"] \
                and rel["pressure_a"] == 0.0 \
                and not rel["spare_hosted"], rel
            assert auto["slo_cleared"], auto
            assert any("autopilot_" in d for d in auto["flight_dumps"]), \
                auto["flight_dumps"]
        except AssertionError as e:
            print(f"autopilot-smoke FAILED: {e}", file=sys.stderr)
            for ev in auto.get("events", []):
                print(f"  event ts={ev.get('ts_s')} "
                      f"{ev.get('transition')}:{ev.get('action')} "
                      f"burn={ev.get('burn')}", file=sys.stderr)
            return 1
        acts = {}
        for e in auto["events"]:
            k = f"{e.get('transition')}:{e.get('action')}"
            acts[k] = acts.get(k, 0) + 1
        print(f"autopilot-smoke OK: storm gold availability "
              f"{s_gold['availability']} static -> "
              f"{a_gold['availability']} autopilot, p99 "
              f"{s_gold['p99_ms']}ms -> {a_gold['p99_ms']}ms; "
              f"actuations {acts}; predictive sheds "
              f"{auto['shed'].get('shed_predictive', 0)}; healthy-phase "
              f"actuations 0; released to rung 0 with routes/pressure/"
              f"spare cleared; {len(auto['flight_dumps'])} flight "
              f"dump(s)")
    return 0


def main() -> int:  # noqa: C901 (one linear acceptance script)
    os.environ.setdefault("TRANSMOGRIFAI_PERF_MODEL", "0")
    with tempfile.TemporaryDirectory(prefix="chaos-smoke-") as tmp:
        dirs = _train_models(tmp)
        report = run_chaos(dirs, seed=0,
                           flight_dir=os.path.join(tmp, "flight"))
        try:
            storm = report["storm"]
            assert storm["quarantined"] and storm["recovered"], \
                f"no HEALTHY->QUARANTINED->HEALTHY round trip: {storm}"
            assert storm["breaker_opens"] >= 1 \
                and storm["breaker_closes"] >= 1, storm
            assert storm["mttr_s"] is not None and storm["mttr_s"] > 0, \
                f"no measured MTTR: {storm}"
            assert storm["fallback_requests"] > 0, \
                f"breaker open but no degraded fallback served: {storm}"
            assert storm["fallback_version_responses"] > 0, \
                "no response carried the resident PREVIOUS version id " \
                f"during the storm: {storm}"
            by_model = {c["model"]: c
                        for c in report["tenants"].values()}
            for m in ("b", "c"):
                assert by_model[m]["errors"] == 0, \
                    f"untouched member {m} saw errors: {by_model[m]}"
                assert by_model[m]["p99_ms"] < 2000.0, by_model[m]
            kill = report["kill"]
            assert not kill["hung"] and kill["restarts"] >= 1, kill
            assert kill["answer"] != "scored" and "answered_in_s" in kill, \
                f"killed-thread client not answered structurally: {kill}"
            assert kill["recovered"], kill
            stall = report["stall"]
            assert stall["within_budget"], \
                f"stall not recovered within budget: {stall}"
            assert stall["restarts"] >= 1 and stall["recovered"], stall
            rel = report["reload"]
            assert rel["rejected"] and rel["resident_version_kept"], rel
            assert rel["traffic"]["errors"] == 0, \
                f"corrupt reload disturbed live traffic: {rel}"
            gp = report["goodput_resilience"]
            assert gp.get("breaker_opens", 0) >= 1 \
                and gp.get("recoveries", 0) >= 1, gp
            slo = report["slo"]
            assert slo["fired"] and slo["cleared"], \
                f"SLO alert did not fire-then-clear: {slo}"
            assert slo["alert_s"] is not None and slo["alert_s"] < 10, slo
            fl = report["flight"]
            assert fl["breaker_dump"], \
                f"breaker open produced no flight dump: {fl}"
            assert fl.get("valid_chrome_trace"), \
                f"flight dump is not a valid Chrome trace: {fl}"
            assert fl.get("failing_dispatch_spans", 0) >= 1, \
                f"flight dump has no failing dispatch spans: {fl}"
            gslo = report["goodput_slo"]
            assert gslo.get("alerts_fired", 0) >= 1 \
                and gslo.get("alerts_resolved", 0) >= 1, gslo
        except AssertionError as e:
            print(f"chaos-smoke FAILED: {e}", file=sys.stderr)
            return 1
    a = report["storm"]
    print(f"chaos-smoke OK: storm tripped member a's breaker "
          f"({a['breaker_opens']} open/{a['breaker_closes']} close, "
          f"MTTR {a['mttr_s']}s), fallback served "
          f"{a['fallback_requests']} request(s) on the previous version; "
          f"untouched members 0 errors "
          f"(p99 b={by_model['b']['p99_ms']}ms "
          f"c={by_model['c']['p99_ms']}ms); killed thread answered in "
          f"{report['kill']['answered_in_s']}s "
          f"({report['kill']['answer']}); stall answered in "
          f"{report['stall']['answered_in_s']}s (budget "
          f"{report['stall']['stall_budget_s']}s); corrupt reload "
          f"rejected with resident version serving; SLO alert fired in "
          f"{report['slo']['alert_s']}s and cleared in "
          f"{report['slo']['clear_s']}s; breaker flight dump valid with "
          f"{report['flight']['failing_dispatch_spans']} failing "
          f"dispatch span(s)")
    return 0


def fleet_main() -> int:
    """Scenario 7: the fleet-observability storm (2 replica processes,
    one fleet alert, one incident artifact) — the full script lives in
    `serving/fleetobs_smoke.py`."""
    from transmogrifai_tpu.serving import fleetobs_smoke
    return fleetobs_smoke.main()


if __name__ == "__main__":
    if "--fleet" in sys.argv[1:]:
        sys.exit(fleet_main())
    sys.exit(storm_main() if "--storm" in sys.argv[1:] else main())
