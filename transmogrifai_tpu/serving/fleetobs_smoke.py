"""Fleet observability smoke: the PR-20 acceptance script.

Boots TWO replica PROCESSES (real `FleetService`s behind `serve_fleet`
HTTP, each publishing trace shards / metrics snapshots / SLO burn
samples to one shared store) plus an in-orchestrator `Frontend` over
`HTTPReplica` handles, and proves the fleet observability plane
end-to-end:

1. **Cross-process trace stitching** — sampled requests through the
   frontend come back as ONE validated Chrome trace per trace id
   (`merge_fleet_trace`), containing the frontend leg AND the serving
   leg from whichever replica process scored it (distinct pids,
   skew-normalized clocks).
2. **Federated metrics** — the frontend's `/metrics/fleet` view folds
   both replicas' PUBLISHED snapshots (no in-process registry reach).
3. **Fleet SLO burn, one alert** — a seeded deadline-error storm
   through BOTH replicas trips the fleet availability alert EXACTLY
   once (CAS latch: fired == 1, not K), with both replicas' traffic in
   the firing burn window, and the alert clears after recovery.
4. **One incident, one artifact** — the alert's flight dump opens a
   fleet incident; both replica processes contribute their rings
   within the capture window and `merge_incident` returns one
   validated cross-host Chrome trace.

Run: ``python -m transmogrifai_tpu.serving.fleetobs_smoke`` (the
``--replica`` flag is the internal worker entry). Also wired as
``make fleetobs-smoke`` and ``python -m transmogrifai_tpu.serving.chaos
--fleet``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, Optional, Tuple

from transmogrifai_tpu.serving.batcher import ScoreError

D = 4  # features per model

# time-scaled availability SLO shared by both replicas: a seconds-long
# error storm burns the 0.1% budget orders of magnitude too fast, so
# both burn windows trip; eval ticks fast enough that fleet folds stay
# fresh across the 2-process fleet
SLO = {
    "slos": [{"name": "gold-availability", "kind": "availability",
              "objective": 0.999, "tenant": "gold"}],
    "windows": [[2.4, 1.2, 2.0, "page"]],
    "time_scale": 1.0, "eval_period_s": 0.05,
}


def _fit_model(path: str, seed: int = 23) -> None:
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(seed)
    n = 160
    X = rng.normal(size=(n, D))
    beta = rng.normal(size=D)
    y = (X @ beta > 0).astype(np.float64)
    ds = Dataset({**{f"x{j}": X[:, j] for j in range(D)}, "y": y},
                 {**{f"x{j}": t.Real for j in range(D)},
                  "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    pred = OpLogisticRegression(max_iter=40).set_input(
        label, vec).get_output()
    Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train().save(path)


def _cols(n_rows: int = 4) -> Dict[str, Any]:
    return {f"x{j}": [0.2 * (j + 1) - 0.1 * i for i in range(n_rows)]
            for j in range(D)}


# --------------------------------------------------------------------------- #
# Replica worker process                                                      #
# --------------------------------------------------------------------------- #

def replica_main(argv) -> int:
    """Internal worker: one fleet replica process. Serves until stdin
    closes (the orchestrator holds the pipe), then stops cleanly so
    final metrics/shard flushes land in the store."""
    p = argparse.ArgumentParser(prog="fleetobs_smoke --replica")
    p.add_argument("--name", required=True)
    p.add_argument("--store", required=True)
    p.add_argument("--model-dir", required=True)
    p.add_argument("--port-file", required=True)
    args = p.parse_args(argv)

    from transmogrifai_tpu.serving.fleet import FleetConfig, FleetService
    from transmogrifai_tpu.serving.http import serve_fleet

    config = FleetConfig(
        models={"m": args.model_dir},
        tenants={"gold": {"priority": 1}},
        serving={"max_batch": 8, "batch_wait_ms": 1.0, "max_queue": 256,
                 # zero-debounce black box: the fleet alert dump must
                 # never be debounced away, it opens the incident
                 "flight": {"dir": os.path.join(args.store, "..",
                                                f"flight-{args.name}"),
                            "min_interval_s": 0.0}},
        store_dir=args.store, replica=args.name, slo=SLO,
        obs={"metrics_period_s": 0.2, "capture_window_s": 10.0})
    fleet = FleetService(config).start()
    server, _ = serve_fleet(fleet, port=0, block=False)
    tmp = args.port_file + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(str(server.port))
    os.replace(tmp, args.port_file)
    try:
        sys.stdin.read()  # parent closes the pipe to stop us
    except KeyboardInterrupt:
        pass
    server.shutdown()
    fleet.stop()
    return 0


def spawn_replica(tmp: str, store: str, name: str, model_dir: str,
                  timeout_s: float = 240.0
                  ) -> Tuple[subprocess.Popen, str]:
    """Boot one replica worker; returns (process, base_url). The
    worker's stdout/stderr go to ``<tmp>/<name>.log``."""
    port_file = os.path.join(tmp, f"{name}.port")
    logf = open(os.path.join(tmp, f"{name}.log"), "w", encoding="utf-8")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("TRANSMOGRIFAI_PERF_MODEL", "0")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "transmogrifai_tpu.serving.fleetobs_smoke", "--replica",
         "--name", name, "--store", store, "--model-dir", model_dir,
         "--port-file", port_file],
        stdin=subprocess.PIPE, stdout=logf, stderr=subprocess.STDOUT,
        env=env)
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if os.path.exists(port_file):
            with open(port_file, encoding="utf-8") as fh:
                return proc, f"http://127.0.0.1:{int(fh.read())}"
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica {name} died during boot "
                f"(see {tmp}/{name}.log)")
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError(f"replica {name} never published its port")


def stop_replica(proc: subprocess.Popen) -> None:
    try:
        if proc.stdin is not None:
            proc.stdin.close()
        proc.wait(timeout=20)
    except Exception:
        proc.kill()


def _get_json(url: str, timeout_s: float = 10.0) -> Dict[str, Any]:
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


# --------------------------------------------------------------------------- #
# Orchestrator                                                                #
# --------------------------------------------------------------------------- #

def _sampled_ctx(tid: str):
    from transmogrifai_tpu.obs.trace import TraceContext
    return TraceContext(trace_id=tid, parent_hex="0123456789abcdef",
                        sampled=True)


def _stitched(frontend, store: str, n: int) -> Dict[str, Any]:
    """Fire `n` sampled requests through the frontend and merge each
    trace id fleet-wide. Returns coverage counts."""
    from transmogrifai_tpu.obs.federate import merge_fleet_trace

    tids = []
    for _ in range(n):
        tid = uuid.uuid4().hex
        frontend.score_columns("m", _cols(), tenant="gold",
                               trace=_sampled_ctx(tid))
        tids.append(tid)
    time.sleep(0.3)  # replica shard appends are flush-per-record
    stitched = 0
    sample = None
    for tid in tids:
        merged = merge_fleet_trace(tid, store)
        ok = (not merged["problems"] and len(merged["hosts"]) >= 2
              and "frontend" in merged["hosts"]
              and merged["spans"] >= 3)
        stitched += int(ok)
        if sample is None:
            sample = {k: merged[k] for k in
                      ("hosts", "spans", "skew_s", "problems",
                       "missing_shards", "torn_shards")}
    return {"requests": n, "stitched": stitched, "sample": sample}


def _storm(replicas, duration_s: float = 2.5) -> int:
    """Seeded overload: deadline-doomed gold requests through BOTH
    replicas (deadline_exceeded is a counted error, not a shed), with
    good traffic interleaved so total counts keep flowing."""
    errors = 0
    stop_at = time.perf_counter() + duration_s
    while time.perf_counter() < stop_at:
        for rep in replicas:
            try:
                rep.score_columns("m", _cols(), tenant="gold",
                                  deadline_ms=0.005)
            except ScoreError:
                errors += 1
            try:
                rep.score_columns("m", _cols(), tenant="gold")
            except ScoreError:
                pass  # storm collateral: only the latch matters here
    return errors


def _good_traffic(replicas, duration_s: float) -> None:
    stop_at = time.perf_counter() + duration_s
    while time.perf_counter() < stop_at:
        for rep in replicas:
            try:
                rep.score_columns("m", _cols(), tenant="gold")
            except ScoreError:
                pass  # recovery traffic: best-effort by design
        time.sleep(0.02)


def _wait_latch(latch, slo: str, state: str,
                timeout_s: float = 20.0) -> Optional[Dict[str, Any]]:
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        rec = latch.counts().get(slo)
        if rec and rec.get("state") == state:
            return rec
        time.sleep(0.05)
    return None


def main() -> int:  # noqa: C901 (one linear acceptance script)
    os.environ.setdefault("TRANSMOGRIFAI_PERF_MODEL", "0")
    from transmogrifai_tpu.obs.federate import (
        FleetAlertLatch, merge_incident)
    from transmogrifai_tpu.serving.frontend import Frontend, HTTPReplica
    from transmogrifai_tpu.store.state import StateCell

    report: Dict[str, Any] = {}
    with tempfile.TemporaryDirectory(prefix="fleetobs-smoke-") as tmp:
        store = os.path.join(tmp, "store")
        os.makedirs(store, exist_ok=True)
        os.environ["TRANSMOGRIFAI_STORE_DIR"] = store
        os.environ.setdefault("TRANSMOGRIFAI_PERF_CORPUS_DIR",
                              os.path.join(tmp, "perf-corpus"))
        model_dir = os.path.join(tmp, "model")
        _fit_model(model_dir)
        procs: Dict[str, subprocess.Popen] = {}
        frontend = None
        try:
            urls: Dict[str, str] = {}
            for name in ("r1", "r2"):
                procs[name], urls[name] = spawn_replica(
                    tmp, store, name, model_dir)
            print(f"[fleetobs] replicas up: {urls}")
            replicas = {name: HTTPReplica(url)
                        for name, url in urls.items()}
            frontend = Frontend(replicas, store_dir=store)

            # -- 1: cross-process trace stitching ----------------------- #
            cov = _stitched(frontend, store, n=5)
            report["stitching"] = cov
            assert cov["stitched"] == cov["requests"], \
                f"stitched {cov['stitched']}/{cov['requests']}: {cov}"
            print(f"[fleetobs] stitched {cov['stitched']}/"
                  f"{cov['requests']} sampled traces: "
                  f"hosts={cov['sample']['hosts']} "
                  f"spans={cov['sample']['spans']}")

            # -- 2: federated metrics ----------------------------------- #
            time.sleep(0.5)  # ≥1 publish period on both replicas
            fm = frontend.fleet_metrics_json()
            report["metrics_replicas"] = sorted(fm["replicas"])
            assert {"r1", "r2"} <= set(fm["replicas"]), fm["replicas"]
            fam = fm["fleet"].get("fleet_requests_total")
            assert fam, "federated view lost fleet_requests_total"
            print(f"[fleetobs] /metrics/fleet folds "
                  f"{sorted(fm['replicas'])}")

            # -- 3: fleet burn, exactly one alert ----------------------- #
            latch = FleetAlertLatch(store)
            errors = _storm(list(replicas.values()))
            rec = _wait_latch(latch, "gold-availability", "firing")
            assert rec is not None, \
                f"fleet alert never fired ({errors} seeded errors)"
            assert int(rec.get("fired", 0)) == 1, \
                f"fleet alert fired {rec.get('fired')} times, want 1"
            slo_view = _get_json(urls["r1"] + "/slo")
            fleet_view = (slo_view.get("slos", {})
                          .get("gold-availability", {})
                          .get("fleet") or {})
            report["alert"] = {"fired": int(rec["fired"]),
                               "owner": rec.get("owner"),
                               "replicas_in_window":
                                   fleet_view.get("replicas")}
            assert int(fleet_view.get("replicas") or 0) >= 2, \
                f"fleet burn window missing a replica: {fleet_view}"
            print(f"[fleetobs] fleet alert fired exactly once "
                  f"(owner={rec.get('owner')}, "
                  f"replicas={fleet_view.get('replicas')})")

            _good_traffic(list(replicas.values()), 4.0)
            cleared = _wait_latch(latch, "gold-availability", "ok")
            assert cleared is not None, "fleet alert never cleared"
            assert int(cleared.get("fired", 0)) == 1, cleared
            report["alert"]["cleared"] = True
            print("[fleetobs] fleet alert cleared (fired stayed 1)")

            # -- 4: one incident, one artifact -------------------------- #
            _, inc_val = StateCell(store, "obs-incident").read()
            inc = (inc_val or {}).get("incident") or {}
            incident_id = inc.get("id")
            assert incident_id, "alert dump opened no fleet incident"
            inc_dir = os.path.join(store, "obs", "incidents",
                                   str(incident_id))
            deadline = time.perf_counter() + 15.0
            while time.perf_counter() < deadline:
                hosts = sorted(os.listdir(inc_dir)) \
                    if os.path.isdir(inc_dir) else []
                if {"r1", "r2"} <= set(hosts):
                    break
                time.sleep(0.2)
            merged = merge_incident(str(incident_id), store)
            report["incident"] = {
                "id": incident_id, "hosts": merged["hosts"],
                "dumps": merged["dumps"],
                "problems": merged["problems"][:3]}
            assert {"r1", "r2"} <= set(merged["hosts"]), \
                f"incident missing a host ring: {merged['hosts']}"
            assert not merged["problems"], merged["problems"][:3]
            assert merged["trace"].get("traceEvents"), \
                "merged incident trace is empty"
            print(f"[fleetobs] incident {incident_id}: one artifact, "
                  f"hosts={merged['hosts']}, "
                  f"{len(merged['trace']['traceEvents'])} events")
        finally:
            if frontend is not None:
                frontend.close()
            for proc in procs.values():
                stop_replica(proc)
    print("fleetobs smoke OK: " + json.dumps(report))
    return 0


if __name__ == "__main__":
    if "--replica" in sys.argv[1:]:
        argv = [a for a in sys.argv[1:] if a != "--replica"]
        sys.exit(replica_main(argv))
    sys.exit(main())
