"""Dynamic micro-batcher: bounded queue + power-of-two shape buckets.

The tf.data line of work (PAPERS.md) shows pipelined HOST-side batching
is what keeps accelerators saturated; the jit-cache corollary on TPU is
that every distinct batch shape is a fresh XLA compile. The batcher
therefore never hands the scorer a raw request size: requests coalesce
into one device batch, and the batch pads up to a small ladder of
power-of-two buckets (``1, 2, 4, ... max_batch``) so after one warmup
pass per bucket the jit cache stays warm — verified at runtime via the
``analysis/retrace`` counters the service exports per bucket.

Overload degrades gracefully instead of collapsing:

- the request queue is BOUNDED — a full queue sheds the new request with
  a structured ``queue_full`` error (load-shedding at admission, the
  cheapest point);
- every request carries a DEADLINE — requests that expire while queued
  are dropped at dequeue (no device time wasted on answers nobody is
  waiting for);
- a request that can never fit a bucket is rejected at admission.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from transmogrifai_tpu.data.dataset import Dataset


class ScoreError(Exception):
    """Structured serving error: a machine-readable ``code`` plus a human
    message. Codes: ``queue_full``, ``deadline_exceeded``, ``bad_request``,
    ``record_error``, ``internal``, ``shutdown``, ``quota_exceeded``,
    ``shed_low_priority``, ``circuit_open``, ``watchdog_restart``,
    ``not_found``.

    ``retry_after_s`` is the backoff hint a shed/fast-failed client
    should honor (token-bucket refill time, breaker half-open deadline);
    the HTTP layer surfaces it as a ``Retry-After`` header on 429/503.

    ``trace_id``/``traceparent`` (set by the service when request
    tracing is on) name the KEPT error trace this failure left behind —
    the HTTP layer echoes them on error responses too, so a failed
    request is as correlatable as a slow one."""

    def __init__(self, code: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.retry_after_s = retry_after_s
        self.trace_id: Optional[str] = None
        self.traceparent: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"error": self.code, "message": self.message}
        if self.retry_after_s is not None:
            out["retry_after_s"] = round(float(self.retry_after_s), 3)
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        return out


def bucket_ladder(max_batch: int, min_bucket: int = 1) -> Tuple[int, ...]:
    """Power-of-two bucket sizes up to and including ``max_batch``.

    ``max_batch`` itself is always the top rung even when it is not a
    power of two (the cap must be reachable, and one extra compiled
    shape is cheaper than refusing max-size batches)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    ladder: List[int] = []
    b = max(1, int(min_bucket))
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(max_batch)
    return tuple(ladder)


def derive_ladder(max_batch: int, min_bucket: int = 1,
                  sizes: Optional[List[int]] = None, model=None,
                  pad_tolerance: float = 0.08,
                  n_cols: int = 0) -> Tuple[int, ...]:
    """Bucket ladder from the OBSERVED request-size distribution plus
    the cost model's predicted per-bucket latency (`perf/`).

    Cold start (no model, or the ``serving_bucket`` target unfitted, or
    no observed sizes yet): EXACTLY ``bucket_ladder(max_batch,
    min_bucket)`` — today's power-of-two heuristic, bit for bit.

    Warm: candidate rungs are the power-of-two ladder plus the p50/p90/
    p99 of the observed sizes (rounded up), and a rung survives only if
    padding its requests up to the NEXT surviving rung would cost more
    than `pad_tolerance` predicted latency — on hardware where latency
    is flat across neighboring shapes, rungs collapse and the jit cache
    holds fewer programs; where latency climbs steeply, the
    traffic-shaped rungs stay. ``max_batch`` is always the top rung
    (every admitted request must fit).

    ``n_cols`` (the serving schema width) lets a fitted
    ``serving_parse`` target fold the HOST parse cost of a b-row
    request into each rung's predicted latency — host work is part of
    what a client waits for, so a rung whose device latency is flat
    but whose parse cost climbs is judged on the sum. A cold parse
    target adds nothing (device-only pruning, the pre-parse-target
    behavior, exactly)."""
    base = bucket_ladder(max_batch, min_bucket)
    if model is None or not sizes:
        return base
    import numpy as np
    qs = np.quantile(np.asarray(sizes, dtype=float), (0.5, 0.9, 0.99))
    cand = sorted({*base,
                   *(min(max_batch, max(min_bucket, int(np.ceil(q))))
                     for q in qs)})
    preds = {}
    for b in cand:
        p = model.predict("serving_bucket", {"bucket": float(b)})
        if p is None:
            return base  # cold target: today's ladder exactly
        preds[b] = p.value
        if n_cols > 0:
            from transmogrifai_tpu.perf.features import parse_features
            pp = model.predict("serving_parse",
                               parse_features(b, n_cols))
            if pp is not None:
                preds[b] += pp.value
    keep = [cand[-1]]  # the cap must always be reachable
    for b in reversed(cand[:-1]):
        if preds[keep[-1]] > (1.0 + pad_tolerance) * preds[b]:
            keep.append(b)
        # else: padding b-row batches up to the next rung is within
        # tolerance — drop the rung (one fewer compiled shape)
    return tuple(sorted(keep))


def bucket_for(n_rows: int, ladder: Tuple[int, ...]) -> int:
    """Smallest bucket >= n_rows; raises when no bucket fits."""
    for b in ladder:
        if n_rows <= b:
            return b
    raise ScoreError(
        "bad_request",
        f"request of {n_rows} rows exceeds the largest bucket "
        f"({ladder[-1]}); split it client-side")


class Request:
    """One in-flight scoring request: a future the caller blocks on, an
    absolute deadline, and (when request tracing is on) the
    `obs.trace.RequestTrace` span buffer the scoring thread backdates
    its per-batch phase spans into.

    The payload is EITHER an already-columnar Dataset (the columnar
    wire, internal callers) or raw row dicts + the model schema (the
    row wire): row requests defer the pivot so the scoring thread can
    encode a whole batch's rows through ONE compiled-codec pass during
    staging — per-request `dataset` access (quarantine re-scores, the
    legacy concat path) encodes lazily and caches."""

    __slots__ = ("_dataset", "rows", "_schema", "n_rows", "deadline",
                 "enqueued_at", "trace", "_event", "_result", "_error")

    def __init__(self, dataset: Optional[Dataset],
                 deadline: Optional[float], trace=None,
                 rows: Optional[List[Dict[str, Any]]] = None,
                 schema: Optional[Dict[str, type]] = None):
        if dataset is None and rows is None:
            raise ValueError("Request needs a dataset or rows")
        self._dataset = dataset
        self.rows = rows if dataset is None else None
        self._schema = schema
        self.n_rows = len(dataset) if dataset is not None else len(rows)
        self.deadline = deadline          # absolute time.monotonic() or None
        self.enqueued_at = time.monotonic()
        self.trace = trace                # Optional[RequestTrace]
        self._event = threading.Event()
        self._result: Optional[Tuple[Dict[str, Any], str]] = None
        self._error: Optional[ScoreError] = None

    @property
    def dataset(self) -> Dataset:
        """The request's columnar payload; row-wire requests encode on
        first access (scoring-thread-only by the threading model) and
        cache the result."""
        if self._dataset is None:
            from transmogrifai_tpu.data.rowcodec import encode_rows
            self._dataset = encode_rows(self.rows, self._schema)
            self.rows = None
        return self._dataset

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.monotonic()) > self.deadline)

    def resolve(self, result: Dict[str, Any], version: str) -> None:
        self._result = (result, version)
        self._event.set()

    def fail(self, error: ScoreError) -> None:
        self._error = error
        self._event.set()

    def wait(self, timeout: Optional[float] = None) -> Tuple[Dict[str, Any], str]:
        if not self._event.wait(timeout):
            raise ScoreError("deadline_exceeded",
                             "timed out waiting for a scoring slot")
        if self._error is not None:
            raise self._error
        assert self._result is not None
        return self._result


class MicroBatcher:
    """Bounded admission queue + batch assembly.

    ``put()`` runs on caller threads (admission control); ``next_batch()``
    runs on the single scoring thread and blocks up to ``batch_wait_s``
    to coalesce concurrent requests into one device batch of at most
    ``max_batch`` rows. A request that does not fit the current batch is
    carried into the next one (never reordered past its peers).
    """

    def __init__(self, max_queue: int, max_batch: int,
                 batch_wait_s: float = 0.002):
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.batch_wait_s = float(batch_wait_s)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queue: deque[Request] = deque()
        self._closed = False

    # -- admission (caller threads) --------------------------------------- #

    def put(self, req: Request) -> None:
        with self._lock:
            if self._closed:
                raise ScoreError("shutdown", "service is shutting down")
            if len(self._queue) >= self.max_queue:
                raise ScoreError(
                    "queue_full",
                    f"request queue at capacity ({self.max_queue}); "
                    "retry with backoff")
            self._queue.append(req)
            self._not_empty.notify()

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def close(self) -> List[Request]:
        """Stop admissions; return (and clear) whatever was still queued
        so the service can fail those requests explicitly."""
        with self._lock:
            self._closed = True
            drained = list(self._queue)
            self._queue.clear()
            self._not_empty.notify_all()
            return drained

    # -- assembly (scoring thread) ---------------------------------------- #

    def _pop_fitting(self, budget: int) -> Optional[Request]:  # guarded-by: _lock
        """Pop the head request if it fits `budget` rows (caller holds
        the lock)."""
        if self._queue and self._queue[0].n_rows <= budget:
            return self._queue.popleft()
        return None

    def next_batch(self, poll_s: float = 0.05
                   ) -> Tuple[List[Request], List[Request]]:
        """Block until requests are available (or closed), then linger up
        to ``batch_wait_s`` filling the batch. Returns
        ``(batch, expired)`` — expired requests are returned separately
        so the service fails them with ``deadline_exceeded`` instead of
        scoring them. Empty batch + empty expired means closed/idle."""
        batch: List[Request] = []
        expired: List[Request] = []
        rows = 0
        with self._not_empty:
            while not self._queue and not self._closed:
                if not self._not_empty.wait(timeout=poll_s):
                    return [], []
            linger_until = time.monotonic() + self.batch_wait_s
            while rows < self.max_batch:
                req = self._pop_fitting(self.max_batch - rows)
                if req is not None:
                    if req.expired():
                        expired.append(req)
                    else:
                        batch.append(req)
                        rows += req.n_rows
                    continue
                if self._queue or self._closed:
                    break  # head doesn't fit (or closed): ship what we have
                remaining = linger_until - time.monotonic()
                if remaining <= 0 or not batch:
                    break
                self._not_empty.wait(timeout=remaining)
                if not self._queue:
                    break
        return batch, expired


def pad_requests(requests: List[Request], ladder: Tuple[int, ...]
                 ) -> Tuple[Dataset, int, int]:
    """Concatenate request datasets and pick the bucket: returns
    ``(combined_dataset, n_valid, bucket)``. The actual padding to the
    bucket happens inside the compiled scorer (`score_padded`) so the
    validity mask lives next to the device call."""
    parts = [r.dataset for r in requests]
    ds = Dataset.concat(parts) if len(parts) > 1 else parts[0]
    n = len(ds)
    return ds, n, bucket_for(n, ladder)
