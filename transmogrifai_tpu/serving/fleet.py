"""FleetService: multi-model tenancy with shared bucket programs.

The reference's production story is many models served to many tenants
(TF-Serving's multi-model servables, arxiv 1605.08695); until this
module one process served exactly ONE `WorkflowModel` and every cold
start re-traced and re-compiled the whole bucket ladder. A
`FleetService` hosts N named models in one process, each member keeping
the full `ScoringService` contract (own micro-batcher and scoring
thread, versioned hot-swap with resident rollback, per-request error
quarantine), and adds the two fleet-scale mechanisms:

**Shared bucket programs.** Two models whose scoring-segment static
signature agrees compile ONE set of bucket programs — keyed the same
way `parallel/sweep.static_signature` keys compile groups: everything
that shapes the traced program goes into the key, everything that flows
as a traced ARGUMENT stays out. Concretely (`scoring_signature`): the
canonical device/host segment wiring with uids replaced by traversal
indices, each stage's class + fitted params — where a stage that routes
its fitted arrays through `device_constants()` (the tree families, the
megabyte tables that dominate compile time) contributes only their
SHAPES/dtypes, because those arrays are jit arguments, while fitted
state a `device_apply` reads off `self` is a closure constant baked
into the XLA program and is therefore value-digested. The upshot: K
replicas of one artifact, and K tree-family models that differ only in
tree-table values (e.g. a continual warm-refit candidate next to its
parent), all execute the FIRST member's compiled programs — the
`ProgramPool` rewires an adopting scorer's segment functions onto the
reference scorer's jitted callables through a uid-bijection adapter, so
the second model's warmup performs ZERO new traces
(`RetraceMonitor.delta()`-asserted in tests and `make fleet-smoke`).

**Persistent-compile cold starts.** `ServingConfig.compile_cache`
(threaded from `ServingParams`/CLI) turns on JAX's persistent
compilation cache with a 0-second persistence threshold at service
construction, and each cold warmup writes an AOT warmup manifest
(`workflow/serialization.save_warmup_manifest`) beside the model
artifact recording the ladder, scoring signature, and cold warm wall
seconds. A replica (or a same-shaped swap) that finds a matching
manifest reaches first-score on cache hits instead of fresh XLA
compiles and reports the recovered seconds as
`serving_compile_cache_saved_s` (+ a `compile_cache_saved` goodput
event).

Admission and routing (per-tenant token-bucket quotas, priority
shedding, per-tenant metrics) live in `serving/router.py`; the fleet
HTTP frontend in `serving/http.py` (`serve_fleet`).

Thread-safety note: adopted members call the reference member's jitted
callables from their own scoring threads — `jax.jit` executables are
safe for concurrent invocation; mutation of the member table itself is
lock-guarded.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from transmogrifai_tpu.obs.metrics import MetricsRegistry
from transmogrifai_tpu.obs.trace import (
    TRACER, RequestTrace, TailSampler, TraceContext, TracingParams)
from transmogrifai_tpu.serving.batcher import ScoreError
from transmogrifai_tpu.serving.router import Router, TenantPolicy
from transmogrifai_tpu.serving.service import ScoringService, ServingConfig

log = logging.getLogger(__name__)

__all__ = ["FleetConfig", "FleetService", "ProgramPool",
           "scoring_signature"]


# --------------------------------------------------------------------------- #
# Scoring-segment static signature                                            #
# --------------------------------------------------------------------------- #

def _canonical_graph(model) -> Tuple[List[Any], List[Any]]:
    """Deterministic (features, fitted stages) walk of a model graph —
    the SAME traversal `save_model` serializes with, so two loads of one
    pipeline shape enumerate in the same order. Returns (feature list,
    fitted-stage list); uids map to positions in these lists."""
    feats: Dict[str, Any] = {}
    order: List[Any] = []
    for rf in model.result_features:
        for f in rf.traverse():
            if f.uid not in feats:
                feats[f.uid] = f
                order.append(f)
    stages: List[Any] = []
    seen: set = set()
    for f in order:
        st = f.origin_stage
        if st is not None and st.uid not in seen:
            seen.add(st.uid)
            stages.append(model.fitted.get(st.uid, st))
    return order, stages


def canonical_uids(model) -> List[str]:
    """Feature uids then stage uids in canonical order: two models with
    equal `scoring_signature` zip these lists into the uid bijection the
    program-sharing adapter remaps argument pytrees with."""
    order, stages = _canonical_graph(model)
    return [f.uid for f in order] + [s.uid for s in stages]


def _digest_value(v: Any, shape_only: bool) -> Any:
    """Canonical JSON-able form of one fitted-param value. Arrays under
    `shape_only` (the stage ships them as `device_constants()` jit
    arguments) contribute shape+dtype; otherwise their BYTES are hashed
    — they are closure constants of the traced program, so their values
    are part of the compile key."""
    if isinstance(v, dict):
        return {str(k): _digest_value(x, shape_only)
                for k, x in sorted(v.items(), key=lambda kv: str(kv[0]))}
    if isinstance(v, (list, tuple, np.ndarray)) or (
            hasattr(v, "shape") and hasattr(v, "dtype")):  # jax arrays too
        try:
            arr = np.asarray(v)
        except Exception:
            arr = None
        if arr is not None and arr.dtype != object:
            if shape_only:
                return ["#array", list(arr.shape), str(arr.dtype)]
            h = hashlib.sha256(np.ascontiguousarray(arr).tobytes())
            return ["#array", list(arr.shape), str(arr.dtype),
                    h.hexdigest()[:16]]
        return [_digest_value(x, shape_only) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if callable(v):
        # stable identity only — never the repr (memory addresses drift)
        return ["#fn", getattr(v, "__module__", "?"),
                getattr(v, "__qualname__", getattr(v, "__name__", "?"))]
    return ["#repr", type(v).__name__, str(v)]


def _stage_signature(stage) -> Dict[str, Any]:
    from transmogrifai_tpu.stages.base import (
        FeatureGeneratorStage, is_host_stage)
    if isinstance(stage, FeatureGeneratorStage):
        # generators run on host per batch; only the produced ftype
        # shapes the device program (raw column NAMES stay out of the
        # key — renamed tenants still share)
        return {"kind": "raw"}
    entry: Dict[str, Any] = {
        "kind": "host" if is_host_stage(stage) else "device"}
    consts = None
    try:
        consts = stage.device_constants()
    except Exception:  # unfitted/host stages may not support it
        consts = None
    shape_only = consts is not None
    # signature_params (stages/base.py) is the stage's own statement of
    # which fitted facts shape the TRACE: lifted families (linear/GLM/
    # trees…) exclude the weight values they route through
    # device_constants() — two same-shaped fits then share — while
    # trace-steering hyperparams (GLM link, GBT learning rate) stay
    # value-digested
    try:
        params = stage.signature_params()
    except Exception:
        params = stage.get_params()
    entry["params"] = _digest_value(params, shape_only)
    if shape_only:
        # the consts pytree structure is part of the jit argument
        # structure even when its values are not
        entry["consts"] = _digest_value(consts, True)
    return entry


def scoring_signature(model, quant: Any = None) -> str:
    """The compile-group key of a model's bucket programs (the serving
    analogue of `parallel/sweep.static_signature`): a sha256 digest of
    the canonical scoring graph — segment wiring with uids replaced by
    traversal indices, stage classes, and fitted state partitioned into
    traced-argument facts (shape/dtype for `device_constants()` arrays)
    vs closure-constant facts (value digests for everything a
    `device_apply` reads off `self`). Two models with equal signatures
    trace byte-identical XLA programs per bucket and may share one
    compiled set through the `ProgramPool`.

    `quant` (a `workflow.compiled.ScoringQuant`, its mode string, or
    None) folds the quantized-inference config into the key: a
    quantized and an unquantized build of one model trace DIFFERENT
    programs (narrow wire structure, narrowed table dtypes) and must
    never adopt each other's bucket programs."""
    from transmogrifai_tpu.workflow.compiled import ScoringQuant
    q = ScoringQuant.resolve(quant)
    order, stages = _canonical_graph(model)
    fidx = {f.uid: i for i, f in enumerate(order)}
    sidx = {s.uid: i for i, s in enumerate(stages)}
    doc = {
        "quant": q.mode if q is not None else None,
        "features": [{
            "ftype": f.ftype.__name__,
            "is_response": bool(f.is_response),
            "origin": (sidx.get(f.origin_stage.uid)
                       if f.origin_stage is not None else None),
            "parents": [fidx[p.uid] for p in f.parents],
        } for f in order],
        "stages": [{
            "class": type(s).__name__,
            "op": s.operation_name,
            "inputs": [fidx[f.uid] for f in s.input_features],
            **_stage_signature(s),
        } for s in stages],
        "results": [fidx[f.uid] for f in model.result_features],
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# --------------------------------------------------------------------------- #
# Program pool                                                                #
# --------------------------------------------------------------------------- #

@dataclass
class _PoolEntry:
    signature: str
    owner: str                      # "<model>:<version>" of the reference
    scorer: Any                     # the reference CompiledScorer (alive!)
    uids: List[str]                 # its canonical uid list
    members: List[str] = field(default_factory=list)


class ProgramPool:
    """signature -> reference compiled scorer. The first model to
    register a signature keeps its own jitted segment functions and
    becomes the REFERENCE; later models with the same signature are
    ADOPTED: their scorer's segment functions are replaced by adapters
    that remap every uid-keyed argument pytree (consts / encs /
    dev_vals) onto the reference's uids, invoke the reference's
    already-compiled program, and remap the outputs back. Values that
    differ between members (device_constants arrays, host_prepare
    encodings, raw batch columns) are exactly the values that flow as
    jit ARGUMENTS, so adoption is numerics-preserving by construction;
    everything baked into the trace is signature-equal.

    The entry holds the reference scorer, so its programs outlive the
    reference model's own serving lifecycle (unloading the reference
    member never invalidates its adoptees)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _PoolEntry] = {}

    def adopt_or_register(self, member: str, model,
                          scorer) -> Optional[str]:
        """Register `scorer` as the reference for its signature, or
        adopt it onto an existing reference. Returns the reference
        owner's member id when adopted, None when this scorer IS the
        reference."""
        # the scorer's quantization config is part of the compile-group
        # key: a quantized member can never adopt an f32 member's
        # programs (different wire structure and table dtypes)
        sig = scoring_signature(model, quant=getattr(scorer, "quant", None))
        uids = canonical_uids(model)
        with self._lock:
            entry = self._entries.get(sig)
            if entry is None:
                self._entries[sig] = _PoolEntry(
                    signature=sig, owner=member, scorer=scorer,
                    uids=uids, members=[member])
                scorer.program_signature = sig
                return None
            entry.members.append(member)
        self._adopt(scorer, uids, entry)
        scorer.program_signature = sig
        scorer.shared_from = entry.owner
        log.info("fleet: %s adopts bucket programs of %s (signature %s)",
                 member, entry.owner, sig)
        return entry.owner

    @staticmethod
    def _adopt(scorer, uids: List[str], entry: _PoolEntry) -> None:
        if len(uids) != len(entry.uids) or \
                len(scorer.segments) != len(entry.scorer.segments):
            # signatures collided but graphs disagree structurally —
            # impossible short of a hash collision; serve solo
            log.warning("fleet: signature %s structural mismatch; "
                        "member keeps its own programs", entry.signature)
            return
        b2a = dict(zip(uids, entry.uids))
        a2b = {a: b for b, a in b2a.items()}
        fns: List[Any] = []
        for (kind, _), ref_fn in zip(scorer.segments,
                                     entry.scorer._seg_fns):
            if kind != "device":
                fns.append(None)
                continue

            def adapter(consts, encs, dev_vals, _ref=ref_fn):
                out = _ref({b2a[k]: v for k, v in consts.items()},
                           {b2a[k]: v for k, v in encs.items()},
                           {b2a[k]: v for k, v in dev_vals.items()})
                return {a2b[k]: v for k, v in out.items()}

            fns.append(adapter)
        scorer._seg_fns = fns

    def report(self) -> Dict[str, Dict[str, Any]]:
        """signature -> {owner, members}: the dedup proof surface the
        fleet exposes on /healthz."""
        with self._lock:
            return {sig: {"owner": e.owner, "members": list(e.members)}
                    for sig, e in self._entries.items()}


# --------------------------------------------------------------------------- #
# Fleet service                                                               #
# --------------------------------------------------------------------------- #

class FleetMemberService(ScoringService):
    """One named model inside a fleet: a full ScoringService whose every
    installed version (initial load, hot-swap candidates) first offers
    its compiled scorer to the fleet's ProgramPool — so a same-shaped
    swap candidate adopts the resident programs and warms with zero new
    traces."""

    def __init__(self, fleet_name: str, pool: ProgramPool, **kw):
        self._fleet_name = fleet_name
        self._pool = pool
        self.shared_from: Optional[str] = None
        super().__init__(**kw)
        # the FleetService-level watchdog supervises every member; a
        # per-member watchdog thread would be N redundant supervisors
        self._own_watchdog = False
        # chaos plans target one member's fault sites by name
        # (serving.device_dispatch#<member>) and health events carry it
        self.fault_scope = fleet_name
        if self._health is not None:
            self._health.member = fleet_name

    def _install(self, model, version_id: str, path: Optional[str] = None):
        scorer = model._ensure_compiled(quant=self.config.quantize)
        self.shared_from = self._pool.adopt_or_register(
            f"{self._fleet_name}:{version_id}", model, scorer)
        return super()._install(model, version_id, path=path)


@dataclass
class FleetConfig:
    """JSON-loadable fleet layout: named models, tenant policies, shared
    serving defaults. Example::

        {"models": {"churn": "models/churn",
                    "churn-eu": {"path": "models/churn_eu",
                                 "serving": {"max_batch": 32}}},
         "tenants": {"acme": {"rate": 200, "burst": 400, "priority": 1},
                     "trial": {"rate": 20, "priority": 0}},
         "serving": {"max_batch": 16},
         "compile_cache": true}
    """

    models: Dict[str, Any] = field(default_factory=dict)
    tenants: Dict[str, Any] = field(default_factory=dict)
    # policy for tenants not named above (None = admit unmetered at the
    # lowest priority, so configured tenants always outrank anonymous
    # traffic under pressure)
    default_tenant: Optional[Dict[str, Any]] = None
    shed_watermark: float = 0.5
    serving: Dict[str, Any] = field(default_factory=dict)
    compile_cache: Optional[bool] = None
    compile_cache_dir: Optional[str] = None
    # serving/resilience.ResilienceParams JSON: shared default for every
    # member's health machine / breaker / watchdog (a member spec's
    # serving overrides may still pin its own `resilience` block)
    resilience: Optional[Dict[str, Any]] = None
    # obs/slo.SLOParams JSON evaluated over the FLEET registry: per-
    # tenant/per-model availability + latency objectives judged from
    # the labeled fleet_* series (member-level SLOs go in a member's
    # own serving config instead)
    slo: Optional[Dict[str, Any]] = None
    # shared state plane (store/): the artifact-store root this replica
    # shares with its peers, this replica's name, and whether tenant
    # quotas meter against the CAS-guarded fleet-wide balance instead of
    # a private per-replica bucket (the K-replica tenant invariant)
    store_dir: Optional[str] = None
    replica: str = "r0"
    shared_quota: bool = False
    # serving/autopilot.AutopilotParams JSON: the SLO-burn-driven
    # supervisor (rebucket re-arm, fidelity route flips, predictive
    # admission, warm-spare activation). None = no controller; requires
    # an `slo` block (the burn signal it closes the loop on)
    autopilot: Optional[Dict[str, Any]] = None
    # obs/federate.FleetObs knobs (requires store_dir): trace-shard
    # publishing, metrics snapshots, incident correlation. Keys:
    # enabled (default True when store_dir is set), metrics_period_s,
    # capture_window_s
    obs: Optional[Dict[str, Any]] = None

    _FIELDS = ("models", "tenants", "default_tenant", "shed_watermark",
               "serving", "compile_cache", "compile_cache_dir",
               "resilience", "slo", "store_dir", "replica",
               "shared_quota", "autopilot", "obs")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "FleetConfig":
        return FleetConfig(**{k: d[k] for k in FleetConfig._FIELDS
                              if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}

    @staticmethod
    def load(path: str) -> "FleetConfig":
        with open(path) as fh:
            return FleetConfig.from_json(json.load(fh))


def _model_spec(spec: Any) -> Tuple[str, Dict[str, Any]]:
    if isinstance(spec, str):
        return spec, {}
    if isinstance(spec, dict) and spec.get("path"):
        return str(spec["path"]), dict(spec.get("serving") or {})
    raise ValueError(f"fleet model spec must be a path or "
                     f'{{"path": ...}}: {spec!r}')


class FleetService:
    """N named models, one process. See module docstring.

    Usage::

        fleet = FleetService(FleetConfig(
            models={"a": "dir_a", "b": "dir_b"},
            tenants={"acme": {"rate": 100, "priority": 1}}))
        fleet.start()
        fleet.score("a", rows, tenant="acme")
        fleet.reload_model("b", "dir_b_v2")   # others undisturbed
        fleet.stop()
    """

    def __init__(self, config: Optional[FleetConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.config = config or FleetConfig()
        self.registry = registry or MetricsRegistry()
        self.pool = ProgramPool()
        self.shared_quota = None
        if self.config.shared_quota and self.config.store_dir:
            from transmogrifai_tpu.store import SharedQuota
            self.shared_quota = SharedQuota(
                self.config.store_dir, replica=self.config.replica,
                registry=self.registry)
        self.router = Router(
            tenants={name: TenantPolicy.from_json(p)
                     for name, p in (self.config.tenants or {}).items()},
            default=(TenantPolicy.from_json(self.config.default_tenant)
                     if self.config.default_tenant else None),
            shed_watermark=self.config.shed_watermark,
            registry=self.registry,
            shared=self.shared_quota)
        self._lock = threading.Lock()
        self._services: Dict[str, FleetMemberService] = {}
        self._started = False
        self.started_at = time.time()
        # fleet-level hang watchdog: ONE supervisor heartbeats every
        # member's scoring loop (serving/resilience.Watchdog); members
        # skip their own per-service watchdog threads
        from transmogrifai_tpu.serving.resilience import (
            ResilienceParams, Watchdog)
        self._resilience = ResilienceParams.from_json(
            self.config.resilience
            or (self.config.serving or {}).get("resilience"))
        self.watchdog: Optional[Watchdog] = None
        if self._resilience.enabled:
            self.watchdog = Watchdog(
                self._live_services,
                period_s=self._resilience.watchdog_period_s,
                name="fleet-watchdog")
        self._m_models = self.registry.gauge(
            "fleet_models", "models currently hosted by this process")
        self._m_shared = self.registry.gauge(
            "fleet_shared_signatures",
            "distinct compiled bucket-program sets across all models")
        # fleet-level request tracing: admission (router) spans + the
        # sampler that judges admission-shed traces (requests that never
        # reach a member service); members sample their own
        self.tracing = TracingParams.from_json(
            (self.config.serving or {}).get("tracing"))
        self.sampler: Optional[TailSampler] = (
            TailSampler(self.tracing, registry=self.registry)
            if self.tracing.enabled else None)
        # fleet-level SLO engine over the labeled fleet_* series
        self.slo_engine = None
        if self.config.slo and dict(self.config.slo).get("enabled", True):
            self._build_slo_engine()
        # fidelity route flips (autopilot-owned): requests for a key
        # model resolve to the mapped resident sibling (e.g. its
        # int8-calibrated build) until cleared — a table write, no
        # compile, no drop (the quant sibling is a separate member
        # whose programs never adopt the f32 member's)
        self._fidelity_routes: Dict[str, str] = {}  # guarded-by: self._lock
        # SLO-burn autopilot (opt-in via config.autopilot; needs slo)
        self.autopilot = None
        if self.config.autopilot and \
                dict(self.config.autopilot).get("enabled", True):
            from transmogrifai_tpu.serving.autopilot import (
                Autopilot, AutopilotParams)
            self.autopilot = Autopilot(
                self, AutopilotParams.from_json(self.config.autopilot))
        # fleet observability federation (trace shards + metrics
        # snapshots + incident correlation) over the shared store
        self.fleetobs = None
        obs_cfg = dict(self.config.obs or {})
        if self.config.store_dir and obs_cfg.get("enabled", True):
            try:
                from transmogrifai_tpu.obs.federate import FleetObs
                self.fleetobs = FleetObs(
                    self.config.store_dir, self.config.replica,
                    snapshot_fn=self._obs_snapshot,
                    metrics_period_s=float(
                        obs_cfg.get("metrics_period_s", 1.0)),
                    capture_window_s=float(
                        obs_cfg.get("capture_window_s", 10.0)))
            except Exception:
                log.warning("fleet: observability federation disabled "
                            "(setup failed)", exc_info=True)
        for name, spec in (self.config.models or {}).items():
            path, overrides = _model_spec(spec)
            self.add_model(name, path, overrides)

    def _build_slo_engine(self) -> None:
        """Per-tenant/per-model SLOs judged from the fleet registry's
        labeled series: availability from fleet_requests/errors/shed,
        latency from the per-tenant latency histogram, staleness from
        the continual freshness gauge on the process registry."""
        from transmogrifai_tpu.obs.metrics import get_registry
        from transmogrifai_tpu.obs.slo import (
            SLOEngine, SLOParams, availability_source, latency_source,
            staleness_source)
        params = SLOParams.from_json(self.config.slo)
        engine = SLOEngine(params, registry=self.registry)
        for slo in engine.slos():
            if slo.kind == "availability":
                # the error/shed families carry a tenant label but no
                # model label, so availability SLOs scope by TENANT
                # (a model-scoped availability needs per-member SLOs
                # on that member's own serving config).
                # fleet_requests_total ticks in Router.note_success —
                # SUCCESSES only — so the source must build the
                # denominator as successes+errors+sheds, or a total
                # outage (no successes) would zero the window and
                # never fire
                scope = {"tenant": slo.tenant} if slo.tenant else {}
                engine.set_source(slo.name, availability_source(
                    self.registry, "fleet_requests_total",
                    error_families=("fleet_errors_total",),
                    shed_families=("fleet_shed_total",),
                    requests_count="successes", **scope))
            elif slo.kind == "latency":
                engine.set_source(slo.name, latency_source(
                    self.registry, "fleet_request_latency_seconds",
                    slo.threshold_s,
                    **({"tenant": slo.tenant} if slo.tenant else {})))
            elif slo.kind == "staleness":
                engine.set_source(slo.name, staleness_source(
                    get_registry(), "continual_staleness_current_seconds",
                    slo.threshold_s))
        if self.config.store_dir:
            # a configured store IS the fleet: share burn state (and the
            # fleet alert latch) through it directly — the env-var path
            # stays for processes without a FleetConfig
            try:
                engine.attach_fleet(self.config.store_dir,
                                    self.config.replica)
            except Exception:
                log.debug("fleet: slo fleet attach failed", exc_info=True)
        else:
            from transmogrifai_tpu.obs.slo import maybe_attach_fleet
            maybe_attach_fleet(engine)
        self.slo_engine = engine

    # -- membership -------------------------------------------------------- #

    def _live_services(self) -> Dict[str, FleetMemberService]:
        with self._lock:
            return {k: v for k, v in self._services.items()
                    if v is not None}

    def _serving_config(self, overrides: Dict[str, Any]) -> ServingConfig:
        base = dict(self.config.serving or {})
        base.update(overrides or {})
        if self.config.resilience is not None:
            base.setdefault("resilience", self.config.resilience)
        if self.config.compile_cache is not None:
            base.setdefault("compile_cache", self.config.compile_cache)
        if self.config.compile_cache_dir is not None:
            base.setdefault("compile_cache_dir",
                            self.config.compile_cache_dir)
        known = {f for f in ServingConfig.__dataclass_fields__}
        unknown = set(base) - known
        if unknown:
            raise ValueError(
                f"unknown serving config keys: {sorted(unknown)}")
        return ServingConfig(**base)

    def add_model(self, name: str, path: str,
                  overrides: Optional[Dict[str, Any]] = None
                  ) -> FleetMemberService:
        """Load + warm a model under `name` (programs shared through the
        pool where signatures agree) and start serving it if the fleet
        is running. Rejects duplicate names."""
        from transmogrifai_tpu.workflow.serialization import (
            load_model, model_fingerprint)
        cfg = self._serving_config(overrides or {})
        # reserve the name UNDER the lock before the slow load/warm: a
        # concurrent duplicate add_model must fail fast, not overwrite a
        # member whose scoring thread would then leak for the process
        # lifetime
        with self._lock:
            if name in self._services:
                raise ScoreError("bad_request",
                                 f"model {name!r} already hosted")
            self._services[name] = None  # reservation
        try:
            model = load_model(path)
            svc = FleetMemberService(
                name, self.pool, model=model,
                version_id=model_fingerprint(path), config=cfg)
        except BaseException:
            with self._lock:
                if self._services.get(name) is None:
                    self._services.pop(name, None)
            raise
        with self._lock:
            if name not in self._services:
                # removed (or the whole fleet reconfigured) mid-load:
                # don't resurrect a member nobody tracks
                removed = True
            else:
                removed = False
                self._services[name] = svc
                if self._started:
                    svc.start()
        if removed:
            svc.stop()
            raise ScoreError("bad_request",
                             f"model {name!r} was removed while loading")
        self._note_membership()
        return svc

    def remove_model(self, name: str) -> None:
        with self._lock:
            if name not in self._services:
                raise ScoreError("not_found", f"no model named {name!r}")
            svc = self._services.pop(name)
        if svc is not None:  # None = reservation of an in-flight add
            svc.stop()
        self._note_membership()

    def _note_membership(self) -> None:
        with self._lock:
            n = sum(1 for s in self._services.values() if s is not None)
        self._m_models.set(n)
        self._m_shared.set(len(self.pool.report()))

    def _service(self, name: str) -> FleetMemberService:
        with self._lock:
            svc = self._services.get(name)
        if svc is None:
            # absent, or a reservation whose load/warm is still running
            raise ScoreError("not_found",
                             f"no model named {name!r} (or still loading)")
        return svc

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "FleetService":
        with self._lock:
            self._started = True
            services = [s for s in self._services.values()
                        if s is not None]
        for svc in services:
            svc.start()
        if self.watchdog is not None:
            self.watchdog.start()
        if self.slo_engine is not None:
            # alert events attach to the caller's span (chaos/bench run
            # roots): the engine thread has no ambient span of its own
            self.slo_engine.span = TRACER.current()
            self.slo_engine.start()
        if self.autopilot is not None:
            self.autopilot.start()
        if self.fleetobs is not None:
            self.fleetobs.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self.autopilot is not None:
            self.autopilot.stop()
        if self.slo_engine is not None:
            self.slo_engine.stop()
        if self.fleetobs is not None:
            self.fleetobs.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        with self._lock:
            self._started = False
            services = [s for s in self._services.values()
                        if s is not None]
        for svc in services:
            svc.stop(timeout=timeout)

    def __enter__(self) -> "FleetService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- scoring ----------------------------------------------------------- #

    def score(self, model: str, rows: List[Dict[str, Any]],
              tenant: Optional[str] = None,
              deadline_ms: Optional[float] = None,
              trace: Optional[TraceContext] = None):
        """Route one row-wire request: resolve the model, pass tenant
        admission (token-bucket quota + priority shedding against the
        target model's queue pressure), then score through that model's
        own micro-batcher. Per-tenant accounting happens here so every
        member's latency lands in the tenant's labeled series.

        The request trace OPENS here (not in the member), so router
        admission is its first phase child and an admission-shed
        request still leaves a kept trace (sheds are errors to the
        tail sampler)."""
        return self._score_routed(
            model, len(rows or ()), tenant, trace,
            lambda svc, tr: svc.score(rows, deadline_ms=deadline_ms,
                                      trace=tr))

    def score_columns(self, model: str, columns: Dict[str, List[Any]],
                      tenant: Optional[str] = None,
                      deadline_ms: Optional[float] = None,
                      trace: Optional[TraceContext] = None):
        """Columnar request wire through the same admission path as
        `score` (quota metering in rows, identical shedding/tracing):
        the member converts columns with no row pivot and its outputs
        are bit-identical to the row wire for the same data."""
        n_rows = 0
        if isinstance(columns, dict):
            for v in columns.values():
                n_rows = len(v) if hasattr(v, "__len__") else 0
                break
        return self._score_routed(
            model, n_rows, tenant, trace,
            lambda svc, tr: svc.score_columns(
                columns, deadline_ms=deadline_ms, trace=tr))

    def score_frame(self, frame: bytes,
                    trace: Optional[TraceContext] = None):
        """Binary columnar wire: decode one length-prefixed frame
        (serving/binwire.py) and route it exactly like `score_columns`.
        Any malformation raises bad_request BEFORE admission, so a
        client framing bug never charges a tenant's quota, the breaker,
        or the health window."""
        from transmogrifai_tpu.serving.binwire import decode_frame
        columns, meta = decode_frame(frame)
        model = meta.get("model")
        if not isinstance(model, str) or not model:
            raise ScoreError("bad_request",
                             "binary frame: missing model name")
        return self.score_columns(
            model, columns, tenant=meta.get("tenant"),
            deadline_ms=meta.get("deadline_ms"), trace=trace)

    def set_fidelity_route(self, model: str,
                           target: Optional[str] = None) -> Optional[str]:
        """Install (target given) or clear (target=None) the fidelity
        route flip for `model`. Autopilot-owned: callers must emit the
        actuation event that justified the flip (lint L022). Returns
        the previous target, None if there was none."""
        with self._lock:
            if target is None:
                return self._fidelity_routes.pop(model, None)
            if target not in self._services:
                raise ScoreError(
                    "not_found",
                    f"fidelity target {target!r} is not a hosted member")
            prev = self._fidelity_routes.get(model)
            self._fidelity_routes[model] = str(target)
            return prev

    def resolve_model(self, model: str) -> str:
        """The member name requests for `model` actually score on."""
        with self._lock:
            return self._fidelity_routes.get(model, model)

    def _score_routed(self, model: str, n_rows: int,
                      tenant: Optional[str],
                      trace: Optional[TraceContext], member_call):
        # predictive pressure is keyed by the REQUESTED model (the
        # logical route key the autopilot writes against); the fidelity
        # flip only changes which member's queue serves that traffic
        requested = model
        model = self.resolve_model(model)
        svc = self._service(model)
        rt: Optional[RequestTrace] = None
        if self.sampler is not None and svc.sampler is not None:
            rt = RequestTrace(ctx=trace, rows=n_rows,
                              tenant=tenant or "default", model=model)
        t0 = time.monotonic()
        try:
            admission = (rt.child("serving:admission", model=model)
                         if rt is not None else contextlib.nullcontext())
            with admission:
                queue_frac = svc._batcher.depth() / max(
                    1, svc.config.max_queue)
                drain_s = None
                if max(queue_frac, self.router.pressure(requested)) >= \
                        self.router.shed_watermark:
                    # only when a shed is plausible: the model predict
                    # is cheap but not free, and the happy path pays
                    # nothing for the proportional backoff hint
                    drain_s = svc.predicted_drain_s()
                tname = self.router.admit(tenant, n_rows,
                                          queue_frac, model=requested,
                                          drain_s=drain_s)
        except ScoreError as e:
            # admission shed: the member never saw this request, so the
            # fleet finishes + samples the trace itself (always kept)
            if rt is not None:
                rt.finish(e.code)
                self.sampler.observe(rt, time.monotonic() - t0,
                                     error=True)
            raise
        with TRACER.span("fleet:score", category="serving",
                         tenant=tname, model=model):
            try:
                # the member's scoring owns the trace from here: phase
                # children, finish, tail sampling, exemplars
                result = member_call(svc, rt if rt is not None else trace)
            except ScoreError as e:
                self.router.note_error(tname, model, e.code)
                raise
        self.router.note_success(tname, model, n_rows,
                                 time.monotonic() - t0)
        return result

    # -- rolling swap ------------------------------------------------------ #

    def reload_model(self, name: str, model_location: str
                     ) -> Dict[str, Any]:
        """Rolling swap of ONE member under traffic: the candidate is
        loaded, pool-adopted (a same-shaped candidate warms with zero
        new compiles), warmed OFF the serving path, then atomically
        flipped — in-flight requests on every OTHER model never touch
        this path at all, and this model's in-flight batches finish on
        the version they were dispatched with. Emits a `fleet_swap`
        goodput event carrying the per-tenant traffic served DURING the
        swap window (the goodput report's rolling-swap accounting)."""
        svc = self._service(name)
        before = self.router.snapshot()
        t0 = time.monotonic()
        status = "failed"
        try:
            result = svc.reload(model_location)
            status = result.get("status", "swapped")
        finally:
            wall = time.monotonic() - t0
            during = self.router.delta(before)
            try:
                from transmogrifai_tpu.obs.export import record_event
                record_event(
                    "fleet_swap", model=name, wall_s=round(wall, 6),
                    status=status,
                    requests_during_swap=sum(
                        d.get("requests", 0) for d in during.values()),
                    shed_during_swap=sum(
                        d.get("shed", 0) for d in during.values()),
                    per_tenant=during)
            except Exception:
                log.debug("fleet_swap event emission failed",
                          exc_info=True)
        if status == "swapped":
            self.registry.counter(
                "fleet_swaps_total", "rolling model swaps applied",
                model=name).inc()
        self._note_membership()
        return result

    def rollback_model(self, name: str) -> Dict[str, Any]:
        return self._service(name).rollback()

    # -- introspection ----------------------------------------------------- #

    def models(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            services = {k: v for k, v in self._services.items()
                        if v is not None}
        out: Dict[str, Dict[str, Any]] = {}
        for name, svc in services.items():
            health = svc.health()
            health["shared_from"] = svc.shared_from
            out[name] = health
        return out

    def health(self) -> Dict[str, Any]:
        models = self.models()
        statuses = [m["status"] for m in models.values()]
        if not self._started or not models or \
                not any(s == "ok" for s in statuses):
            status = "down"
        elif all(s == "ok" for s in statuses):
            status = "ok"
        else:
            # one member quarantined/down must NOT 503 the whole fleet:
            # the healthy members keep taking traffic, balancers see
            # "degraded" with the per-member breakdown
            status = "degraded"
        out = {
            "status": status,
            "replica": self.config.replica,
            "models": models,
            "tenants": self.router.snapshot(),
            "shared_programs": self.pool.report(),
        }
        with self._lock:
            if self._fidelity_routes:
                out["fidelity_routes"] = dict(self._fidelity_routes)
        if self.autopilot is not None:
            out["autopilot"] = self.autopilot.status()
        if status == "down":
            hints = [float(m.get("retry_after_s") or 0.0)
                     for m in models.values()]
            hints.append(self._resilience.watchdog_period_s)
            out["retry_after_s"] = round(max(hints), 3)
        return out

    def metrics_json(self) -> Dict[str, Any]:
        with self._lock:
            services = {k: v for k, v in self._services.items()
                        if v is not None}
        return {"fleet": self.registry.to_json(),
                "models": {name: svc.registry.to_json()
                           for name, svc in services.items()}}

    def _obs_snapshot(self):
        """What this replica publishes to the metrics federation: the
        fleet registry (tenant/model-labeled series) plus every
        member's serving_* registry labeled by model. Runs on the
        publisher thread — reads only, never blocks a scoring path."""
        snap = MetricsRegistry()
        snap.merge(self.registry)
        for name, svc in self._live_services().items():
            snap.merge(svc.registry, model=name)
        return snap

    def fleet_metrics_json(self) -> Dict[str, Any]:
        """The aggregated `/metrics/fleet` payload: every replica's
        last-published snapshot merged (counters summed, histograms
        bucket-merged, gauges replica-labeled), with per-replica
        publish timestamps as provenance. Requires a store_dir."""
        if not self.config.store_dir:
            raise ScoreError(
                "not_found",
                "no store_dir configured: metrics federation is off")
        from transmogrifai_tpu.obs.federate import aggregate_fleet_metrics
        merged, info = aggregate_fleet_metrics(self.config.store_dir)
        return {"replica": self.config.replica,
                "replicas": info,
                "fleet": merged.to_json()}
