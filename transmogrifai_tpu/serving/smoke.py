"""Serving smoke check: train tiny -> save -> serve -> score -> scrape.

`make serve-smoke` runs this module. It must prove, in one process and
under a minute on CPU, the full production path: a model trains and
saves, the service loads + AOT-warms it, the HTTP frontend binds a
RANDOM free port, a real `/score` POST returns a scored row with a
model version, `/healthz` reports ok, `/metrics` exposes non-zero
latency data in both formats, `/reload` of the same dir is a detected
no-op, and shutdown is clean. Exit 0 on success, 1 with a reason
otherwise.

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.serving.smoke``
"""

from __future__ import annotations

import json
import sys
import tempfile
import urllib.request


def _train_tiny_model(path: str) -> None:
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(7)
    n = 120
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (x1 + 0.5 * x2 + rng.normal(0, 0.3, n) > 0).astype(np.float64)
    ds = Dataset({"x1": x1, "x2": x2, "y": y},
                 {"x1": t.Real, "x2": t.Real, "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    pred = OpLogisticRegression(max_iter=40).set_input(
        label, vec).get_output()
    model = Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()
    model.save(path)


def _post(url: str, payload: dict) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read()


def main() -> int:
    from transmogrifai_tpu.serving.http import serve
    from transmogrifai_tpu.serving.service import ScoringService, ServingConfig

    with tempfile.TemporaryDirectory(prefix="serve-smoke-") as tmp:
        model_dir = f"{tmp}/model"
        _train_tiny_model(model_dir)

        service = ScoringService.from_path(
            model_dir, config=ServingConfig(max_batch=8))
        service.start()
        server, _ = serve(service, port=0, block=False)
        base = f"http://127.0.0.1:{server.port}"
        try:
            health = json.loads(_get(f"{base}/healthz"))
            assert health["status"] == "ok", health

            scored = _post(f"{base}/score",
                           {"rows": [{"x1": 1.2, "x2": -0.3}]})
            assert scored["model_version"], scored
            (row,) = scored["scores"]
            pred = next(v for v in row.values()
                        if isinstance(v, dict) and "prediction" in v)
            assert pred["prediction"] in (0.0, 1.0), scored

            reload_resp = _post(f"{base}/reload",
                                {"model_location": model_dir})
            assert reload_resp["status"] == "unchanged", reload_resp

            prom = _get(f"{base}/metrics").decode()
            assert "serving_request_latency_seconds_count" in prom, prom
            assert "serving_requests_total 1" in prom, prom
            mjson = json.loads(_get(f"{base}/metrics?format=json"))
            lat = mjson["serving_request_latency_seconds"]["series"][0]
            assert lat["count"] >= 1 and lat["p50"] is not None, lat
        except Exception as e:
            print(f"serve-smoke FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        finally:
            server.shutdown()
            server.server_close()
            service.stop()
    print("serve-smoke OK: boot, /score, /healthz, /metrics (prom+json), "
          "/reload no-op, clean shutdown")
    return 0


if __name__ == "__main__":
    sys.exit(main())
