"""Compatibility re-export: the metrics registry moved to `obs/metrics.py`.

Serving grew the Counter/Gauge/Histogram registry first; once train-time
ingest, retries, and fit counters wanted the same `/metrics` surface it
was promoted to the cross-cutting `obs/` package (single process-wide
`REGISTRY`, Prometheus label escaping). Import from
`transmogrifai_tpu.obs.metrics` in new code; this module keeps every
existing `serving.metrics` import path working.
"""

from transmogrifai_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS, REGISTRY, Counter, Gauge, Histogram,
    MetricsRegistry, get_registry, _escape_help, _escape_label_value,
    _fmt_labels, _label_key)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS", "REGISTRY", "get_registry"]
