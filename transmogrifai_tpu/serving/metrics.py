"""DEPRECATED compatibility re-export: the registry lives in
`obs/metrics.py`.

Serving grew the Counter/Gauge/Histogram registry first; once train-time
ingest, retries, and fit counters wanted the same `/metrics` surface it
was promoted to the cross-cutting `obs/` package (single process-wide
`REGISTRY`, Prometheus label escaping, trace-id exemplars). Every
in-repo importer has been migrated to `transmogrifai_tpu.obs.metrics`;
this shim remains for external callers and now says so out loud — a
`DeprecationWarning` on import (one shim test pins the contract:
identical objects, warning emitted)."""

import warnings

from transmogrifai_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS, REGISTRY, Counter, Gauge, Histogram,
    MetricsRegistry, get_registry, _escape_help, _escape_label_value,
    _fmt_labels, _label_key)

warnings.warn(
    "transmogrifai_tpu.serving.metrics is deprecated; import from "
    "transmogrifai_tpu.obs.metrics instead",
    DeprecationWarning, stacklevel=2)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS", "REGISTRY", "get_registry"]
