"""Serving-plane resilience: health states, circuit breakers, watchdog.

Everything fault-tolerant built so far (`runtime/faults`, `RetryPolicy`,
journal resume) protects *training*; this module is the serving plane's
defense. A fleet that melts down on one bad member is not goodput
(arxiv 2502.06982), and the multi-model servable lifecycle mirrored
from TF-Serving (arxiv 1605.08695) assumes exactly this health-state +
supervision layer. Three mechanisms, one per failure mode:

**Per-member health state machine** (`MemberHealth`). Each
`ScoringService` rolls its recent request outcomes + latencies through
a bounded window and walks HEALTHY → DEGRADED → QUARANTINED:

- DEGRADED: rolling error rate past ``degraded_error_rate`` (with at
  least ``min_window`` samples) — the member serves but is flagged;
- QUARANTINED: error rate past ``quarantine_error_rate``, OR the
  circuit breaker is open, OR the watchdog found the scoring loop
  wedged. New requests to a quarantined member with no fallback
  version FAST-FAIL with a structured ``circuit_open`` error (plus a
  retry-after hint) instead of queueing into a dead batcher;
- recovery is half-open: every ``half_open_after_s`` one probe batch is
  dispatched on the primary path; ``probe_successes`` consecutive probe
  wins close the breaker, clear the window, and restore HEALTHY.
  Transitions are recorded (bounded history + ``health_transition``
  events) with the measured outage duration on recovery — the MTTR the
  goodput report and the chaos bench roll up.

**Circuit breaker + degraded fallback.** ``breaker_failures``
CONSECUTIVE device-dispatch failures open the member's breaker. While
open, if a resident previous version exists (the hot-swap rollback
chain), batches auto-fall-back to scoring on it — the member degrades
to known-good answers (`serving_degraded_fallback_total`, a
``degraded_fallback`` goodput event) instead of going dark; with no
fallback the member fast-fails as above. Only PRIMARY-path dispatch
outcomes feed the breaker: batch-assembly errors and fallback results
count toward the health window but never toward the breaker.

**Hang watchdog** (`Watchdog`). A fleet-level supervisor thread
heartbeats every member's scoring loop via its per-batch liveness
timestamp. A loop wedged past ``watchdog_stall_s`` (or a scoring
thread killed outright — an `InjectedKill` or real fatal error sails
through the loop's ``except Exception``) gets its in-flight batch
quarantined per-request (structured ``watchdog_restart`` errors — no
client ever hangs forever on a wedged jit dispatch), the scoring
thread restarted under a fresh generation, and the event recorded
(`serving_watchdog_restarts_total` + ``watchdog_restart`` event).

All knobs live in `ResilienceParams`, JSON-threaded through
``ServingConfig.resilience`` / ``ServingParams.resilience`` / cli
``serve``. The deterministic exercise machinery is `runtime/faults`
(sites ``serving.device_dispatch`` / ``serving.batch_assemble`` /
``serving.reload_load``) and the chaos harness (`serving/chaos.py`,
``make chaos-smoke``, ``python bench.py chaos``).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

log = logging.getLogger(__name__)

__all__ = ["HEALTHY", "DEGRADED", "QUARANTINED", "ResilienceParams",
           "MemberHealth", "Watchdog"]

HEALTHY = "healthy"
DEGRADED = "degraded"
QUARANTINED = "quarantined"


@dataclass
class ResilienceParams:
    """Knobs for the serving resilience layer (JSON-loadable via
    ``ServingConfig.resilience`` / ``ServingParams.resilience``)."""

    enabled: bool = True
    # rolling request-outcome window (count-based; per member)
    window: int = 64
    min_window: int = 16           # floor before error-rate judgments
    degraded_error_rate: float = 0.25
    quarantine_error_rate: float = 0.6
    # consecutive PRIMARY device-dispatch failures that open the breaker
    breaker_failures: int = 5
    # open -> half-open probe cadence, and probes needed to close
    half_open_after_s: float = 1.0
    probe_successes: int = 2
    # hang watchdog: supervisor poll period and per-batch stall budget
    watchdog_period_s: float = 0.25
    watchdog_stall_s: float = 30.0

    _FIELDS = ("enabled", "window", "min_window", "degraded_error_rate",
               "quarantine_error_rate", "breaker_failures",
               "half_open_after_s", "probe_successes",
               "watchdog_period_s", "watchdog_stall_s")

    def __post_init__(self):
        if self.window < 1 or self.min_window < 1:
            raise ValueError("window sizes must be >= 1")
        if self.min_window > self.window:
            # the deque caps at `window` samples, so a larger floor
            # could never be reached — the error-rate machine would be
            # silently inert under a 100% error rate
            raise ValueError(
                f"min_window ({self.min_window}) must be <= window "
                f"({self.window})")
        if not (0.0 < self.degraded_error_rate
                <= self.quarantine_error_rate <= 1.0):
            raise ValueError(
                "need 0 < degraded_error_rate <= quarantine_error_rate "
                f"<= 1, got {self.degraded_error_rate} / "
                f"{self.quarantine_error_rate}")
        if self.breaker_failures < 1:
            raise ValueError("breaker_failures must be >= 1")
        if self.half_open_after_s <= 0 or self.watchdog_period_s <= 0 \
                or self.watchdog_stall_s <= 0:
            raise ValueError("resilience periods must be > 0")

    @staticmethod
    def from_json(d: Optional[Dict[str, Any]]) -> "ResilienceParams":
        d = d or {}
        return ResilienceParams(**{k: d[k] for k in ResilienceParams._FIELDS
                                   if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}


def _record_event(name: str, **attrs: Any) -> None:
    """Best-effort goodput event (the health path must never raise)."""
    try:
        from transmogrifai_tpu.obs.export import record_event
        record_event(name, **attrs)
    except Exception:
        log.debug("resilience event %s emission failed", name,
                  exc_info=True)


def _flight_dump(reason: str) -> None:
    """Best-effort crash-flight-recorder dump on an incident transition
    (breaker open, quarantine entry): the ring holds the failing
    dispatch spans that caused it — capture them before they scroll
    out. Debounced inside the recorder, never raises."""
    try:
        from transmogrifai_tpu.obs import flight
        flight.request_dump(reason)
    except Exception:
        log.debug("flight dump (%s) failed", reason, exc_info=True)


class MemberHealth:
    """One member's health state machine + circuit breaker. Thread-safe:
    noted from the scoring thread, read from caller threads and the
    watchdog. See module docstring for the state semantics."""

    def __init__(self, params: ResilienceParams, member: str = "",
                 registry=None):
        self.params = params
        self.member = member
        self.registry = registry
        self._lock = threading.RLock()
        self.state = HEALTHY
        self._window: deque = deque(maxlen=params.window)   # ok bools
        self._latencies: deque = deque(maxlen=params.window)
        self._consecutive = 0          # primary dispatch failures in a row
        self._breaker_open = False
        self._probe_streak = 0
        self._probe_anchor = 0.0       # last open/probe tick (monotonic)
        self._stalled = False
        self._down_since: Optional[float] = None  # outage start (monotonic)
        # incident dumps queued by the state machine under the lock,
        # written AFTER it is released (flight dumps are disk I/O)
        self._pending_dumps: list = []
        self.breaker_opens = 0
        self.breaker_closes = 0
        self.recoveries: list = []     # measured MTTR seconds, bounded
        self.transitions: deque = deque(maxlen=64)

    # -- introspection ------------------------------------------------------ #

    @property
    def breaker_open(self) -> bool:
        with self._lock:
            return self._breaker_open

    def error_rate(self) -> float:
        with self._lock:
            if not self._window:
                return 0.0
            return 1.0 - sum(self._window) / len(self._window)

    def retry_after_s(self) -> float:
        """Seconds until the next half-open probe slot — the backoff a
        fast-failed client should honor (HTTP ``Retry-After``)."""
        with self._lock:
            if self.state != QUARANTINED:
                return 0.0
            return max(0.0, self.params.half_open_after_s
                       - (time.monotonic() - self._probe_anchor))

    def _latency_quantile(self, q: float) -> float:
        vals = sorted(self._latencies)
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self.state,
                "error_rate": round(self.error_rate(), 4),
                "window_n": len(self._window),
                # rolling latency over the same window the error rate
                # judges — the /healthz-visible half of the
                # "error-rate/latency window"
                "latency_p50_ms": round(
                    self._latency_quantile(0.5) * 1e3, 3),
                "latency_p99_ms": round(
                    self._latency_quantile(0.99) * 1e3, 3),
                "breaker_open": self._breaker_open,
                "breaker_opens": self.breaker_opens,
                "breaker_closes": self.breaker_closes,
                "consecutive_failures": self._consecutive,
                "stalled": self._stalled,
                "recoveries": [round(r, 4) for r in self.recoveries[-8:]],
                "transitions": [dict(t) for t in self.transitions],
            }

    # -- admission ---------------------------------------------------------- #

    def admit(self, has_fallback: bool) -> Optional[float]:
        """None = admit. A float = FAST-FAIL with that retry-after: the
        member is quarantined and has no resident fallback to degrade
        onto, so queueing the request would just park it in a dead (or
        known-broken) batcher. Probe slots are admitted so recovery can
        actually be observed."""
        with self._lock:
            if self.state != QUARANTINED or has_fallback:
                return None
            # leave the probe slot to the scoring loop's own dispatch
            # plan; admit one request per probe window so the probe has
            # something to score
            remaining = self.params.half_open_after_s - (
                time.monotonic() - self._probe_anchor)
            if remaining <= 0:
                return None
            return remaining

    def probe_due(self) -> bool:
        """While open/quarantined: claim the half-open probe slot (one
        per ``half_open_after_s``). Mutating on purpose — exactly one
        batch per window becomes the probe."""
        with self._lock:
            if not (self._breaker_open or self.state == QUARANTINED):
                return False
            now = time.monotonic()
            if now - self._probe_anchor >= self.params.half_open_after_s:
                self._probe_anchor = now
                return True
            return False

    # -- notes from the scoring path ---------------------------------------- #

    def note_request(self, ok: bool, latency_s: float = 0.0) -> None:
        """One request outcome into the rolling window (every resolved
        or failed scoring request, fallback included)."""
        with self._lock:
            self._window.append(bool(ok))
            self._latencies.append(float(latency_s))
            self._recompute("error_rate")
        self._flush_flight_dumps()

    def note_dispatch(self, ok: bool, probe: bool = False) -> None:
        """One PRIMARY-path device dispatch outcome (per batch, or per
        quarantined single). Feeds the breaker; fallback dispatches
        must NOT be noted here (they prove nothing about the primary)."""
        with self._lock:
            if ok:
                self._consecutive = 0
                if self._breaker_open and probe:
                    self._probe_streak += 1
                    if self._probe_streak >= self.params.probe_successes:
                        self._close_breaker()
            else:
                self._consecutive += 1
                if self._breaker_open:
                    if probe:
                        # failed probe: re-arm the open window
                        self._probe_streak = 0
                        self._probe_anchor = time.monotonic()
                elif self._consecutive >= self.params.breaker_failures:
                    self._open_breaker()
        self._flush_flight_dumps()

    def note_stall(self, since: Optional[float] = None) -> None:
        """The watchdog found the scoring loop wedged/dead: quarantine
        until the restart's probes prove recovery. `since` (monotonic)
        backdates the outage to when the batch actually stalled so the
        recorded MTTR measures the real client-visible gap."""
        with self._lock:
            self._stalled = True
            if self._down_since is None:
                self._down_since = since if since is not None \
                    else time.monotonic()
            self._probe_anchor = time.monotonic()
            self._recompute("stall")
        self._flush_flight_dumps()

    def clear_stall(self) -> None:
        """Scoring thread restarted: the stall itself is over; state
        recomputes from the window/breaker (errors the stall caused may
        keep the member DEGRADED until traffic washes them out)."""
        with self._lock:
            self._stalled = False
            self._recompute("stall_recovered")
        self._flush_flight_dumps()

    def _flush_flight_dumps(self) -> None:
        """Write incident dumps the state machine queued, AFTER the
        lock is released. A flight dump is disk I/O (trace + event
        artifacts); holding the health lock across it would stall every
        thread noting or admitting requests behind one slow disk —
        exactly the blocking-under-lock pattern C003 flags."""
        with self._lock:
            if not self._pending_dumps:
                return
            reasons, self._pending_dumps = self._pending_dumps, []
        for reason in reasons:
            _flight_dump(reason)
        with self._lock:
            # every queued reason marks an incident ENTRY, and the dump
            # above is disk I/O of unbounded duration — if it ran past
            # half_open_after_s the backoff would already be spent and
            # the first post-incident request would sail through admit()
            # as an unthrottled probe; the half-open window measures
            # time serving while broken, so start it now
            if self._breaker_open or self.state == QUARANTINED:
                self._probe_anchor = time.monotonic()

    # -- internals (lock held) ---------------------------------------------- #

    def _open_breaker(self) -> None:  # guarded-by: _lock
        self._breaker_open = True
        self._probe_streak = 0
        self._probe_anchor = time.monotonic()
        if self._down_since is None:
            self._down_since = time.monotonic()
        self.breaker_opens += 1
        self._counter("serving_breaker_opens_total",
                      "circuit breakers tripped open").inc()
        _record_event("breaker_open", member=self.member,
                      consecutive_failures=self._consecutive)
        # queued, not written here: the caller holds self._lock
        self._pending_dumps.append("breaker_open")
        log.warning("serving%s: circuit breaker OPEN after %d consecutive "
                    "dispatch failures",
                    f"[{self.member}]" if self.member else "",
                    self._consecutive)
        self._recompute("breaker_open")

    def _close_breaker(self) -> None:  # guarded-by: _lock
        self._breaker_open = False
        self._consecutive = 0
        self._probe_streak = 0
        self.breaker_closes += 1
        # the quarantine-era errors in the window are the breaker's own
        # history, not fresh evidence — recovery must not instantly
        # re-degrade on them
        self._window.clear()
        self._latencies.clear()
        self._counter("serving_breaker_closes_total",
                      "circuit breakers closed by probe recovery").inc()
        _record_event("breaker_close", member=self.member)
        log.info("serving%s: circuit breaker closed (probe recovery)",
                 f"[{self.member}]" if self.member else "")
        self._recompute("breaker_close")

    def _counter(self, name: str, help_text: str):
        if self.registry is not None:
            return self.registry.counter(name, help_text)

        class _Null:
            def inc(self, *_: Any) -> None:
                pass
        return _Null()

    def _target_state(self) -> str:  # guarded-by: _lock
        if self._breaker_open or self._stalled:
            return QUARANTINED
        n = len(self._window)
        if n >= self.params.min_window:
            rate = 1.0 - sum(self._window) / n
            if rate >= self.params.quarantine_error_rate:
                return QUARANTINED
            if rate >= self.params.degraded_error_rate:
                return DEGRADED
        return HEALTHY

    def _recompute(self, reason: str) -> None:  # guarded-by: _lock
        target = self._target_state()
        if target == self.state:
            return
        prev, self.state = self.state, target
        entry: Dict[str, Any] = {
            "at": time.time(), "from": prev, "to": target,
            "reason": reason}
        if target == QUARANTINED:
            if self._down_since is None:
                self._down_since = time.monotonic()
            self._probe_anchor = time.monotonic()
        elif prev == QUARANTINED and self._down_since is not None:
            mttr = time.monotonic() - self._down_since
            self._down_since = None
            entry["recovery_s"] = round(mttr, 6)
            self.recoveries.append(mttr)
            del self.recoveries[:-64]
        self.transitions.append(entry)
        self._counter(
            "serving_health_transitions_total",
            "health state-machine transitions").inc()
        _record_event("health_transition", member=self.member,
                      **{k: v for k, v in entry.items() if k != "at"})
        if target == QUARANTINED:
            # queued, not written here: the caller holds self._lock
            self._pending_dumps.append("quarantine")
        log.log(logging.WARNING if target == QUARANTINED else logging.INFO,
                "serving%s: health %s -> %s (%s)",
                f"[{self.member}]" if self.member else "", prev, target,
                reason)


class Watchdog:
    """Fleet-level hang supervisor: heartbeats every member's scoring
    loop and recovers wedged/dead ones. ``members`` is a zero-arg
    callable returning the CURRENT name -> service map (fleet
    membership is dynamic); each service exposes ``check_liveness()``
    (None | "dead" | "stalled") and ``recover_scoring_thread(reason)``.
    The watchdog itself must never die: each sweep is exception-
    isolated per member."""

    def __init__(self, members: Callable[[], Dict[str, Any]],
                 period_s: float = 0.25, name: str = "serving-watchdog"):
        self._members = members
        self.period_s = float(period_s)
        self.name = name
        self._halt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.restarts = 0

    def start(self) -> "Watchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._halt.clear()
        self._thread = threading.Thread(target=self._run, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def sweep(self) -> int:
        """One supervision pass (also callable synchronously in tests):
        recover every member whose scoring loop is dead or stalled.
        Returns the number of restarts performed."""
        n = 0
        try:
            members = dict(self._members() or {})
        except Exception:
            log.exception("watchdog: membership enumeration failed")
            return 0
        for name, svc in members.items():
            if svc is None:
                continue
            try:
                reason = svc.check_liveness()
                if reason is not None:
                    svc.recover_scoring_thread(reason)
                    self.restarts += 1
                    n += 1
            except Exception:
                log.exception("watchdog: recovery of member %r failed",
                              name)
        return n

    def _run(self) -> None:
        while not self._halt.wait(timeout=self.period_s):
            self.sweep()
