"""Reusable batch staging: coalesce + pad as WRITES, not allocations.

Before this module, every device batch the scoring thread assembled
paid ``Dataset.concat`` (one fresh ``np.concatenate`` per column) plus
a fresh pad allocation inside ``score_padded`` (``pad_dataset`` builds
a repeat-index array and concatenates again). Per dispatch that is
2×n_columns fresh arrays whose sizes are ALWAYS one of the ladder's
bucket sizes — the textbook case for resident staging buffers.

``StagingPool`` owns one preallocated buffer set per (bucket, column
layout): batch assembly writes each request's columns into slices of
the resident block, the pad tail is a broadcast write repeating the
last valid row (the same pad-row discipline ``pad_dataset`` documents —
pad rows take the exact host-encode path valid rows take and never
widen a quantized batch's value range), and the Dataset handed to the
compiled scorer wraps the resident buffers directly, already at bucket
size — ``score_padded`` sees ``len(ds) == pad_to`` and its own concat +
pad path becomes a no-op. The donated device write then reads straight
off the staging block.

Ownership and fencing: the pool is owned by the SINGLE scoring thread —
assembly never locks. Hot-swaps, rollbacks, and ladder rebuckets call
``invalidate()`` (any thread): the generation counter bumps and the
buffer map clears, so the next assemble reallocates against the new
schema/ladder while a batch mid-flight keeps the references it already
holds (buffers are never mutated by anyone but the scoring thread, and
the scoring thread finishes its dispatch before assembling the next
batch).

``allocations`` counts buffer (re)allocations — the steady-state proof
``make parse-smoke`` asserts: after warmup, scoring traffic performs
ZERO fresh batch-block allocations. ``fallbacks`` counts batches the
pool refused (mixed column layouts, exact-int object columns where the
resident buffer is float64) — those take the legacy concat path so
correctness never depends on the buffers fitting.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from transmogrifai_tpu.data.dataset import Dataset, _dataset_unchecked

__all__ = ["StagingPool"]


def _layout(ds: Dataset) -> Tuple:
    """Column layout signature of one request dataset: names in order,
    storage dtype kind, and schema ftype per column. Two requests stage
    into the same buffers only when their layouts are IDENTICAL —
    same-named columns with different ftypes or storage classes must
    not silently share a batch (Dataset.concat's validation, enforced
    structurally here)."""
    return tuple(
        (name, arr.dtype, ds.schema.get(name))
        for name, arr in ds.columns.items())


class StagingPool:
    """Per-bucket resident staging buffers for the scoring thread."""

    def __init__(self):
        self._buffers: Dict[Tuple[int, Tuple], Dict[str, np.ndarray]] = {}
        self._gen_lock = threading.Lock()
        self.generation = 0      # bumped by invalidate()
        self.allocations = 0     # buffer sets (re)allocated
        self.fallbacks = 0       # batches refused (legacy concat path)
        self.assembled = 0       # batches staged through the pool

    def invalidate(self) -> None:
        """Drop every resident buffer (hot-swap / rollback / rebucket:
        the column layout or the bucket ladder changed). Safe from any
        thread — the scoring thread re-allocates lazily on its next
        assemble and never writes a dropped buffer again (it fetches
        buffers fresh per batch)."""
        with self._gen_lock:
            self.generation += 1
            self._buffers = {}

    # -- assembly (scoring thread only) ------------------------------------ #

    def assemble(self, parts: List[Dataset], n_valid: int,
                 bucket: int) -> Optional[Dataset]:
        """Write `parts` (total `n_valid` rows) into the resident block
        for `bucket` and pad the tail by repeating the last valid row.
        Returns a bucket-sized Dataset over the resident buffers, or
        None when the batch cannot stage (mixed layouts / dtype drift)
        — the caller then takes the legacy concat path.

        Raises ValueError on an EMPTY parts list (a batch always has
        requests). A mixed-ftype batch returns None rather than raising
        so the caller's per-request quarantine semantics stay exactly
        as they were."""
        if not parts:
            raise ValueError("assemble: empty batch")
        gen = self.generation
        first = parts[0]
        layout = _layout(first)
        for p in parts[1:]:
            if _layout(p) != layout:
                self.fallbacks += 1
                return None
        key = (bucket, layout)
        bufs = self._buffers.get(key)
        if bufs is None:
            # buffers mirror the request columns' exact storage dtypes:
            # the staged block must be bit-identical to what the legacy
            # concat path would have produced
            bufs = {name: np.empty(bucket, dtype=dtype)
                    for name, dtype, _ in layout}
            with self._gen_lock:
                if self.generation != gen:
                    # a watchdog restart fenced us off mid-assemble: a
                    # STALE loop must not install buffers into the map
                    # the restarted loop now owns (two writers on one
                    # block); take the allocation-free fallback instead
                    self.fallbacks += 1
                    return None
                self._buffers[key] = bufs
            self.allocations += 1
        off = 0
        for p in parts:
            n = len(p)
            for name, arr in p.columns.items():
                bufs[name][off:off + n] = arr
            off += n
        if off != n_valid or off == 0 or off > bucket:
            # row accounting drifted (caller bug) — refuse rather than
            # ship a half-written block
            self.fallbacks += 1
            return None
        if off < bucket:
            for name, _, _ in layout:
                buf = bufs[name]
                if buf.dtype == object:
                    # fill(), not slice-assign: a sequence-valued cell
                    # (list/map column) must repeat as ONE object, not
                    # broadcast its elements
                    buf[off:bucket].fill(buf[off - 1])
                else:
                    buf[off:bucket] = buf[off - 1]  # repeat last valid row
        self.assembled += 1
        # schema dict is shared with the first request's dataset —
        # Dataset transforms copy-on-write it, nothing mutates in place
        return _dataset_unchecked(
            {name: bufs[name] for name, _, _ in layout}, first.schema)
