"""Device mesh helpers: the sweep × data grid over TPU chips.

Reference parity: this replaces the reference's two parallelism mechanisms —
Spark row-partitioning (data axis) and the driver thread-pool dispatching
model×grid×fold fits (`OpValidator.scala:299-358`, the "sweep axis") — with
one `jax.sharding.Mesh`:

- `"sweep"` axis: independent fold×grid programs spread across chips
- `"data"`  axis: rows of the feature matrix sharded; stats/fit reductions
  become `psum`s over ICI

Multi-host scaling is the same mesh over more devices (DCN between slices);
no separate communication backend is needed — XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SWEEP_AXIS = "sweep"
DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              sweep: Optional[int] = None,
              axis_names: Tuple[str, str] = (SWEEP_AXIS, DATA_AXIS)) -> Mesh:
    """Build a 2-D (sweep, data) mesh over the first `n_devices` devices.

    `sweep` fixes the sweep-axis size (defaults to every device on sweep,
    data=1 — the AutoML workload is usually sweep-bound).
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices, have {len(devices)}")
    s = sweep if sweep is not None else n
    if n % s != 0:
        raise ValueError(f"sweep={s} must divide n_devices={n}")
    grid = np.array(devices[:n]).reshape(s, n // s)
    return Mesh(grid, axis_names)


def sweep_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (grid×fold) axis over the sweep dimension."""
    return NamedSharding(mesh, P(SWEEP_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (row) axis over the data dimension."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
