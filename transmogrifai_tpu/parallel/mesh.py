"""Device mesh helpers: the sweep × data grid over TPU chips.

Reference parity: this replaces the reference's two parallelism mechanisms —
Spark row-partitioning (data axis) and the driver thread-pool dispatching
model×grid×fold fits (`OpValidator.scala:299-358`, the "sweep axis") — with
one `jax.sharding.Mesh`:

- `"sweep"` axis: independent fold×grid programs spread across chips
- `"data"`  axis: rows of the feature matrix sharded; stats/fit reductions
  become `psum`s over ICI

Multi-host scaling is the same mesh over more devices (DCN between slices);
no separate communication backend is needed — XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SWEEP_AXIS = "sweep"
DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              sweep: Optional[int] = None,
              axis_names: Tuple[str, str] = (SWEEP_AXIS, DATA_AXIS)) -> Mesh:
    """Build a 2-D (sweep, data) mesh over the first `n_devices` devices.

    `sweep` fixes the sweep-axis size (defaults to every device on sweep,
    data=1 — the AutoML workload is usually sweep-bound).
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"Requested {n} devices, have {len(devices)}")
    s = sweep if sweep is not None else n
    if n % s != 0:
        raise ValueError(f"sweep={s} must divide n_devices={n}")
    grid = np.array(devices[:n]).reshape(s, n // s)
    return Mesh(grid, axis_names)


def make_multislice_mesh(n_slices: int,
                         devices_per_slice: Optional[int] = None,
                         data_per_slice: Optional[int] = None) -> Mesh:
    """(sweep, data) mesh laid out for a multi-slice pod (SURVEY.md §5.8).

    Slice boundaries land on the SWEEP axis: fold×grid programs are
    independent, so the only cross-slice (DCN) traffic is final metric
    gathers, while the data axis — whose `psum` reductions need bandwidth —
    stays inside a slice (ICI). Uses the runtime's slice topology when
    exposed (`device.slice_index`), otherwise falls back to contiguous
    grouping, which matches how hosts enumerate devices on real pods and
    on `--xla_force_host_platform_device_count` test meshes.

    `data_per_slice` splits each slice's devices further into a per-slice
    data axis (default: all of a slice's devices on data).
    """
    devices = jax.devices()
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", 0), []).append(d)
    if len(by_slice) >= n_slices > 1:
        groups = [by_slice[k] for k in sorted(by_slice)[:n_slices]]
        per = min(len(g) for g in groups)
        if devices_per_slice is not None:  # honored on real pods too
            if devices_per_slice > per:
                raise ValueError(
                    f"devices_per_slice={devices_per_slice} exceeds the "
                    f"smallest slice ({per} devices)")
            per = devices_per_slice
        groups = [g[:per] for g in groups]
    else:  # single real slice (or CPU test mesh): contiguous grouping
        if n_slices < 1:
            raise ValueError(f"n_slices={n_slices} must be >= 1")
        if devices_per_slice is None:
            # an implicit floor-divide would silently drop the remainder
            # devices (8 devices / 3 slices "worked" on 6) — demand an
            # explicit devices_per_slice instead of guessing
            if len(devices) % n_slices != 0:
                raise ValueError(
                    f"{len(devices)} devices do not divide into "
                    f"{n_slices} equal contiguous slices "
                    f"({len(devices)} % {n_slices} = "
                    f"{len(devices) % n_slices}); pass devices_per_slice "
                    "explicitly to use a subset")
            per = len(devices) // n_slices
        else:
            per = devices_per_slice
        if per < 1 or per * n_slices > len(devices):
            raise ValueError(
                f"need {max(per, 1) * n_slices} devices for {n_slices} "
                f"slices × {max(per, 1)}, have {len(devices)}")
        groups = [devices[i * per:(i + 1) * per] for i in range(n_slices)]
    dps = data_per_slice or per
    if per % dps != 0:
        raise ValueError(f"data_per_slice={dps} must divide {per}")
    # grid: (n_slices * per//dps, dps) — slice-major on the sweep axis
    rows = []
    for g in groups:
        for s in range(per // dps):
            rows.append(g[s * dps:(s + 1) * dps])
    return Mesh(np.array(rows), (SWEEP_AXIS, DATA_AXIS))


def sweep_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (grid×fold) axis over the sweep dimension."""
    return NamedSharding(mesh, P(SWEEP_AXIS))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (row) axis over the data dimension."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
