"""pod-smoke: multi-HOST sweep execution over the shared lease table.

The CI gate for the pod tier (`make pod-smoke`) — and the measured half
of `python bench.py pod`. Where `parallel/smoke.py` proves the
single-host work-stealing scheduler on one process's forced host mesh,
this module launches REAL separate scheduler processes (one per "host")
against one shared store dir and proves the cross-host contracts:

1. **bit-identical winner**: a 2-host pod sweep (each host a fresh
   process on a forced >1-slice `make_multislice_mesh` host mesh,
   claim-racing blocks through the `store.state.LeaseTable`) must
   reproduce the single-host scheduled sweep's metric matrix exactly
   (JSON-string equality) on EVERY host — each host's own rows plus the
   other host's rows merged from the host-qualified journal shards;
2. **kill-one-host TTL reclaim**: a host killed (InjectedKill) while
   holding a block lease stops renewing; the survivor process observes
   the TTL expiry, takes the block over, and finishes the sweep with
   EXACTLY the dead host's in-flight block re-run — asserted from the
   per-host journal shard record counts AND the lease table's per-block
   attempt counters;
3. **measurement**: single-host vs 2-host wall clock (speedup) + the
   fleet-wide ``mesh_utilization_frac`` from rolling each host's
   `GoodputReport.mesh` through `obs.goodput.fleet_mesh_rollup`.

The parent process never initializes JAX — it orchestrates child
processes (``--child``), reads their JSON payloads, and inspects the
shared store. Run: ``python -m transmogrifai_tpu.parallel.pod_smoke``.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

# smoke-scale workload: 4 LR max_iter groups + 1 SVC group = 5 blocks
# of 2 configs each (see parallel/smoke.py `_selector`)
SMOKE_MAX_ITERS = (8, 4, 6, 3)
SMOKE_ROWS = 240
SMOKE_WORKERS = int(os.environ.get("TRANSMOGRIFAI_POD_SMOKE_WORKERS", "2"))
KILL_TTL_S = 2.0


def _barrier(path: str, host: str, n: int, timeout_s: float = 180.0) -> None:
    """File-based start barrier: every host touches its marker, then
    polls (capped exponential backoff, deadline-bounded) until all `n`
    markers exist — so the speedup measurement times hosts that really
    ran concurrently, not a staggered pipeline."""
    os.makedirs(path, exist_ok=True)
    open(os.path.join(path, f"{host}.ready"), "w").close()
    deadline = time.monotonic() + timeout_s
    delay = 0.01
    while time.monotonic() < deadline:
        if len(glob.glob(os.path.join(path, "*.ready"))) >= n:
            return
        time.sleep(delay)
        delay = min(delay * 1.5, 0.25)
    raise TimeoutError(f"pod barrier: {host} waited {timeout_s}s for "
                       f"{n} hosts at {path}")


def _shard_records(ckpt_dir: str, host: Optional[str] = None) -> int:
    """Journal records across the shared store's shard files — scoped to
    one host's ``-w<host>_<lane>.jsonl`` shards when `host` is given."""
    pat = f"*.journal-w{host}_*.jsonl" if host else "*.journal-w*.jsonl"
    n = 0
    for p in glob.glob(os.path.join(ckpt_dir, pat)):
        with open(p) as fh:
            n += max(0, sum(1 for _ in fh) - 1)  # minus header
    return n


# -- child process ------------------------------------------------------------ #

def _child(cfg: Dict[str, Any]) -> int:
    """One pod host: forced host devices, a >1-slice mesh, and the
    env-gated `HostScheduler` path through the selector. Prints one
    JSON payload line."""
    from transmogrifai_tpu.parallel.smoke import ensure_host_devices
    ensure_host_devices(8)

    os.environ["TRANSMOGRIFAI_POD_STORE"] = cfg["store"]
    os.environ["TRANSMOGRIFAI_POD_HOST"] = cfg["host"]
    os.environ["TRANSMOGRIFAI_POD_SWEEP"] = cfg["sweep"]
    os.environ["TRANSMOGRIFAI_POD_WORKERS"] = str(cfg["workers"])
    os.environ["TRANSMOGRIFAI_POD_TTL_S"] = str(cfg["ttl_s"])

    from transmogrifai_tpu.obs import goodput as obs_goodput
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.parallel.mesh import make_multislice_mesh
    from transmogrifai_tpu.parallel.smoke import _cols, _fit, _rows, _selector

    max_iters = tuple(cfg["max_iters"])
    n_rows = int(cfg["n_rows"])
    cols = _cols(n_rows)
    # slice boundaries on the sweep axis: lanes are whole slices' rows,
    # so block execution stays inside a slice (ICI) and only the lease
    # table + journal shards cross hosts (DCN)
    mesh = make_multislice_mesh(2, data_per_slice=1)
    n_slices = 2

    # warm this process's compile caches off the clock (throwaway
    # trace: its mesh_utilization event must not leak into the measured
    # rollup), without touching the shared store
    with TRACER.span("run:pod-warmup", category="run", new_trace=True):
        _fit(_selector(max_iters=max_iters), cols, n_rows)

    if cfg.get("kill_at"):
        from transmogrifai_tpu.runtime.faults import (
            SITE_WORKER_BLOCK, FaultPlan, FaultSpec, InjectedKill)
        plan = FaultPlan([FaultSpec(SITE_WORKER_BLOCK,
                                    at=int(cfg["kill_at"]), kind="kill")])
        killed = False
        try:
            with plan.active():
                _fit(_selector(cfg["ckpt"], max_iters=max_iters),
                     cols, n_rows, mesh=mesh)
        except InjectedKill:
            killed = True
        print(json.dumps({"host": cfg["host"], "killed": killed}))
        return 0

    if cfg.get("barrier"):
        _barrier(cfg["barrier"], cfg["host"], int(cfg["hosts"]))
    with TRACER.span("run:pod-bench", category="run",
                     new_trace=True) as root:
        t0 = time.perf_counter()
        sweep = _rows(_fit(_selector(cfg["ckpt"], max_iters=max_iters),
                           cols, n_rows, mesh=mesh))
        t_fit = time.perf_counter() - t0
    report = obs_goodput.build_report(
        root, TRACER.trace_spans(root.trace_id))
    print(json.dumps({
        "host": cfg["host"], "t_fit_s": round(t_fit, 3),
        "n_slices": n_slices, "workers": int(cfg["workers"]),
        "n_results": len(sweep["rows"]),
        "winner": json.dumps(sweep, sort_keys=True),
        "mesh": report.mesh,
    }))
    return 0


def _spawn(cfg: Dict[str, Any], extra_env: Dict[str, str]):
    env = dict(os.environ)
    env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "transmogrifai_tpu.parallel.pod_smoke",
         "--child", json.dumps(cfg)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _finish(proc) -> Dict[str, Any]:
    out, err = proc.communicate(timeout=900)
    payload = None
    for line in out.splitlines():
        if line.startswith("{"):
            payload = json.loads(line)
    if proc.returncode != 0 or payload is None:
        raise RuntimeError(
            f"pod child failed (rc={proc.returncode}):\n{out}\n{err}")
    return payload


# -- parent: measured run ----------------------------------------------------- #

def run_pod(n_hosts: int = 2, workers: int = SMOKE_WORKERS,
            max_iters=SMOKE_MAX_ITERS, n_rows: int = SMOKE_ROWS,
            ttl_s: float = 30.0) -> Dict[str, Any]:
    """Single-host baseline vs `n_hosts` concurrent host processes on
    one shared store: winner parity + measured speedup + the fleet
    mesh-utilization rollup. Shared by the smoke gate and `bench.py
    pod` (which passes more blocks so the packing measurement is not
    dominated by per-process startup)."""
    from transmogrifai_tpu.obs.goodput import fleet_mesh_rollup

    with tempfile.TemporaryDirectory(prefix="pod-smoke-") as tmp:
        store = os.path.join(tmp, "store")
        corpus = os.path.join(tmp, "corpus")
        base_cfg = {"workers": workers, "max_iters": list(max_iters),
                    "n_rows": n_rows, "ttl_s": ttl_s}

        # baseline: ONE host process over its own store — same lane
        # count, same scheduler, so the speedup isolates what the extra
        # hosts add (the fleet perf corpus stays shared: per-replica
        # shards, merged reads)
        single = _finish(_spawn(
            {**base_cfg, "host": "base", "store": store, "sweep": "base",
             "ckpt": os.path.join(tmp, "ckpt-base")},
            {"TRANSMOGRIFAI_PERF_CORPUS_DIR": corpus,
             "TRANSMOGRIFAI_PERF_REPLICA": "base",
             "TRANSMOGRIFAI_PERF_MODEL": "1"}))
        n_cfgs = single["n_results"]
        assert n_cfgs == 2 * (len(max_iters) + 1), single

        ckpt = os.path.join(tmp, "ckpt-pod")
        barrier = os.path.join(tmp, "barrier")
        procs = [_spawn(
            {**base_cfg, "host": f"h{i}", "store": store, "sweep": "pod",
             "ckpt": ckpt, "barrier": barrier, "hosts": n_hosts},
            {"TRANSMOGRIFAI_PERF_CORPUS_DIR": corpus,
             "TRANSMOGRIFAI_PERF_REPLICA": f"h{i}",
             "TRANSMOGRIFAI_PERF_MODEL": "1"})
            for i in range(n_hosts)]
        hosts = [_finish(p) for p in procs]

        for h in hosts:
            assert h["n_results"] == n_cfgs, h
            assert h["winner"] == single["winner"], (
                f"host {h['host']} winner diverged from single-host")
        fleet = fleet_mesh_rollup([h["mesh"] for h in hosts])
        t_single = float(single["t_fit_s"])
        t_pod = max(float(h["t_fit_s"]) for h in hosts)
        blocks = int(fleet.get("blocks", 0))
        # host_cpus contextualizes the MEASURED speedup: n_hosts fresh
        # interpreters time-slicing fewer cores than hosts cannot beat
        # one process, so a sub-1 number on a starved box is the honest
        # reading, not a scheduler defect (winner parity + lease
        # arithmetic above are the correctness gates either way).
        return {
            "n_hosts": n_hosts, "workers_per_host": workers,
            "n_slices_per_host": hosts[0]["n_slices"],
            "blocks": blocks,
            "host_cpus": os.cpu_count() or 1,
            "sweep_single_host_measured_s": round(t_single, 3),
            f"sweep_pod{n_hosts}_measured_s": round(t_pod, 3),
            "pod_speedup": round(t_single / max(t_pod, 1e-9), 3),
            "fleet_mesh_utilization_frac":
                fleet["mesh_utilization_frac"],
            "fleet_mesh": fleet,
            "winner_exact": True,
        }


def _smoke_kill_host(payload: Dict[str, Any]) -> None:
    """Kill host `killer` (1 lane) at its SECOND block claim: block 1 is
    journaled + done, block 2 dies leased. The survivor — a fresh
    process started after the death — must see the lease TTL-expire,
    take over, and finish with exactly that one block re-run."""
    from transmogrifai_tpu.store.state import LeaseTable

    n_blocks, cfg_per_block = 5, 2
    total_cfgs = n_blocks * cfg_per_block
    with tempfile.TemporaryDirectory(prefix="pod-kill-") as tmp:
        store = os.path.join(tmp, "store")
        ckpt = os.path.join(tmp, "ckpt")
        base_cfg = {"store": store, "sweep": "kill", "ckpt": ckpt,
                    "max_iters": list(SMOKE_MAX_ITERS),
                    "n_rows": SMOKE_ROWS, "ttl_s": KILL_TTL_S}
        # cold cost model on BOTH hosts: the block arithmetic below
        # assumes count-LPT plans (no model-driven splits)
        env = {"TRANSMOGRIFAI_PERF_MODEL": "0"}

        killer = _finish(_spawn(
            {**base_cfg, "host": "killer", "workers": 1, "kill_at": 2},
            env))
        assert killer["killed"], "fault plan failed to kill the host"
        at_kill = _shard_records(ckpt)
        assert at_kill == cfg_per_block, (
            f"killed host should have journaled exactly its first "
            f"block: {at_kill}/{total_cfgs} configs")

        survivor = _finish(_spawn(
            {**base_cfg, "host": "survivor", "workers": SMOKE_WORKERS},
            env))
        assert survivor["n_results"] == total_cfgs, survivor
        rerun = _shard_records(ckpt) - at_kill
        assert rerun == total_cfgs - cfg_per_block, (
            f"survivor re-ran {rerun} configs, expected exactly the "
            f"{total_cfgs - cfg_per_block} the dead host never "
            "journaled (its in-flight block + its queue)")
        # lease-table forensics: exactly ONE block needed a second
        # attempt (the TTL takeover of the dead host's in-flight lease)
        snap = LeaseTable(store, "kill", owner="audit").snapshot()
        assert len(snap) == n_blocks, snap
        attempts = sorted(b["attempts"] for b in snap.values())
        assert attempts == [1] * (n_blocks - 1) + [2], attempts
        taken = [k for k, b in snap.items() if b["attempts"] == 2]
        assert all(b["state"] == "done" for b in snap.values()), snap
        payload.update(
            kill_ttl_reclaim="ok", blocks_journaled_at_kill=1,
            blocks_taken_over=len(taken),
            lease_ttl_s=KILL_TTL_S)


def _smoke() -> int:
    payload: Dict[str, Any] = {}
    payload.update(run_pod())
    _smoke_kill_host(payload)
    print(json.dumps({"pod_smoke": "ok", **payload}))
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(_child(json.loads(sys.argv[sys.argv.index("--child") + 1])))
    sys.exit(_smoke())
