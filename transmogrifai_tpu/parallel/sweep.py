"""The sweep engine: folds × models × grids as batched XLA programs.

Reference parity: `OpValidator.getSummary` / `OpCrossValidation.validate`
(`core/.../tuning/OpValidator.scala:299-358`, `OpCrossValidation.scala:87-147`)
— the reference dispatches each model×grid×fold fit as a Future running
Spark jobs; here EVERY model family (logistic, linear, GLM, SVC, NB, MLP,
random forest / decision tree, GBT / XGBoost) compiles its whole grid×fold
block into ONE XLA program: fit → predict → masked device metric
(`evaluators/device_metrics.py`), no host round-trips inside the sweep.

Static-shape strategy per family:
- linear-like: grids share one compile per distinct `max_iter`; the
  regularization axis is a traced vector, vmapped.
- trees: `max_depth` grids are PADDED to the group's largest depth and
  grown with a traced `active_depth` (models/trees.py), so a
  {3, 6, 12} depth grid is one compile; `min_child_weight`,
  `learning_rate`, `reg_lambda` are traced vectors.
- grid axis execution: `vmap` (parallel) when the sweep axis is sharded
  over a mesh or the family is cheap; `lax.scan`-based `lax.map`
  (sequential, single compile) for deep trees on a single device to bound
  the histogram working set.

Fault tolerance mirrors `OpValidator.scala:324-353`: a failing model family
is dropped with a warning; only all-families-failing raises.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators.device_metrics import make_device_metric
from transmogrifai_tpu.models.base import infer_n_classes
from transmogrifai_tpu.models.glm import (
    OpGeneralizedLinearRegression, fit_glm, predict_glm)
from transmogrifai_tpu.models.linear import (
    OpLinearRegression, fit_linreg, predict_linreg)
from transmogrifai_tpu.models.linear_svc import (
    OpLinearSVC, fit_linear_svc, predict_linear_svc)
from transmogrifai_tpu.models.logistic import (
    OpLogisticRegression, fit_logreg, predict_logreg)
from transmogrifai_tpu.models.mlp import (
    OpMultilayerPerceptronClassifier, fit_mlp, predict_mlp)
from transmogrifai_tpu.models.naive_bayes import (
    OpNaiveBayes, fit_naive_bayes, predict_naive_bayes)
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier, OpDecisionTreeRegressor, OpGBTClassifier,
    OpGBTRegressor, OpRandomForestClassifier, OpRandomForestRegressor,
    OpXGBoostClassifier, OpXGBoostRegressor,
    bin_features, fit_forest, fit_gbt, fit_gbt_multiclass,
    forest_classification_pred, forest_regression_pred,
    gbt_multiclass_pred_from_margin, gbt_pred_from_margin,
    quantile_bin_edges)

log = logging.getLogger(__name__)


# --------------------------------------------------------------------------- #
# host-path fallback (LambdaEvaluator / unknown model classes)                #
# --------------------------------------------------------------------------- #

def _metric(evaluator, y: np.ndarray, pred: Dict[str, np.ndarray],
            val_mask: np.ndarray) -> float:
    idx = val_mask > 0.5
    label = Column(T.RealNN, {
        "value": y[idx], "mask": np.ones(int(idx.sum()), dtype=bool)})
    pcol = Column(T.Prediction, {k: np.asarray(v)[idx] for k, v in pred.items()})
    return evaluator.metric_value(label, pcol)


def _sweep_generic(est, grids: List[Dict], X, y, folds, evaluator,
                   ctx) -> List[List[float]]:
    """Fallback: python loop over grids × folds (host metric path)."""
    from transmogrifai_tpu.models.trees import _TreeEstimatorBase
    out = []
    y_np = np.asarray(y)
    bin_cache: Dict = {}  # shared across the family: bin X once per max_bins
    for grid in grids:
        clone = type(est)(**{**{k: v for k, v in est.params.items()
                                if k != "uid"}, **grid})
        if isinstance(clone, _TreeEstimatorBase):
            clone._bin_cache = bin_cache
        row = []
        for tr, va in folds:
            model = clone.fit_arrays(X, y, jnp.asarray(tr), ctx)
            pred = model.predict_arrays(X)
            row.append(_metric(evaluator, y_np,
                               {k: np.asarray(v) for k, v in pred.items()}, va))
        out.append(row)
    return out


# --------------------------------------------------------------------------- #
# batched execution scaffold                                                  #
# --------------------------------------------------------------------------- #

def _grid_param(est, grid: Dict, name: str) -> Any:
    return grid.get(name, getattr(est, name, est.params.get(name)))


class HostMetricFallback:
    """Marker metric_fn: run the batched fit+predict XLA program, but score
    with a host evaluator (LambdaEvaluator / metrics with no device kernel).
    """

    def __init__(self, evaluator):
        self.evaluator = evaluator


def _shard_dyn(dyn: Dict[str, jnp.ndarray],
               sharding) -> Tuple[Dict[str, jnp.ndarray], int]:
    """Place the grid axis on the mesh's sweep axis; PAD a non-divisible
    group to the next multiple by repeating the last config (padded rows
    compute real but discarded fits — cheaper than replicating the whole
    group on every shard). Returns (dyn, original_g)."""
    if sharding is None:
        return dyn, next(iter(dyn.values())).shape[0]
    g = next(iter(dyn.values())).shape[0]
    n_shards = sharding.mesh.shape[sharding.spec[0]] if sharding.spec else 1
    if n_shards > 1 and g % n_shards != 0:
        pad = n_shards - g % n_shards
        dyn = {k: jnp.concatenate([v, jnp.repeat(v[-1:], pad, 0)])
               for k, v in dyn.items()}
    return {k: jax.device_put(v, sharding) for k, v in dyn.items()}, g


def _run_block(one_cfg: Callable, dyn: Dict[str, jnp.ndarray], sharding,
               grid_vmap: bool):
    """Execute one grid block: one_cfg(dyn_slice) over the grid axis.

    vmap → parallel over grids (sharded across the mesh's sweep axis when
    `sharding` is set); lax.map → sequential single compile (bounds the peak
    memory of deep-tree histogram building on one chip). Returns the raw
    jax output (a (g, k) metric array, or a prediction pytree with leading
    (g, k) axes on the host-metric fallback path).
    """
    dyn, g = _shard_dyn(dyn, sharding)
    if grid_vmap or sharding is not None:
        prog = jax.jit(jax.vmap(one_cfg))
    else:
        prog = jax.jit(lambda d: jax.lax.map(one_cfg, d))
    out = jax.block_until_ready(prog(dyn))
    return jax.tree_util.tree_map(lambda a: a[:g], out)  # drop pad rows


def _sweep_blocks(grids: List[Dict], y, W, V, metric_fn, sharding,
                  static_of: Callable[[Dict], Tuple],
                  dyn_of: Callable[[Dict], Dict[str, Any]],
                  build: Callable[[Tuple, List[int]], Callable],
                  grid_vmap: Callable[[Tuple, List[int]], bool] = lambda s, i: True,
                  host_dispatch: bool = False,
                  pair_width: Callable[[Tuple, List[int], int], int]
                  = lambda s, i, k: 1,
                  ) -> List[List[float]]:
    """Shared scaffold: group grids by static params; per group, stack the
    dynamic params into traced vectors and run fit→predict→metric as one
    program. `build(static, idxs)` returns `fit_predict(dyn_slice, w) -> pred`.

    A `HostMetricFallback` metric_fn (custom/LambdaEvaluator metrics with no
    device kernel) keeps the batched fit+predict program but evaluates the
    wrapped evaluator over the materialized (g, k, n, …) prediction pytree
    on host — fits stay one XLA program per group either way.

    `host_dispatch` (tree families, single device only): compile ONE
    fit→predict→metric program per static group and dispatch it per
    grid×fold pair from the host instead of folding the whole group into a
    single giant execution. Compile count is unchanged; per-dispatch device
    time stays seconds even for 20-tree depth-12 forests on 100k rows —
    monolithic sweep executions past ~60s get killed by serving
    infrastructure (and a host loop also bounds peak HBM). With a mesh
    (`sharding`), the batched path runs so the grid axis shards.
    """
    groups: Dict[Tuple, List[int]] = {}
    for i, g in enumerate(grids):
        groups.setdefault(static_of(g), []).append(i)
    metrics: List[Optional[List[float]]] = [None] * len(grids)
    host = isinstance(metric_fn, HostMetricFallback)
    y_np = np.asarray(y) if host else None
    V_np = np.asarray(V) if host else None
    for static, idxs in groups.items():
        dyn_dicts = [dyn_of(grids[i]) for i in idxs]
        dyn = {k: jnp.asarray([d[k] for d in dyn_dicts],
                              jnp.int32 if isinstance(dyn_dicts[0][k], int)
                              else jnp.float32)
               for k in dyn_dicts[0]}
        fit_predict = build(static, idxs)

        if host_dispatch and sharding is None:
            def one_pair(d, w, v, fit_predict=fit_predict):
                pred = fit_predict(d, w)
                return pred if host else metric_fn(y, pred, v)

            n_folds = int(np.asarray(W).shape[0])
            n_pairs = len(idxs) * n_folds
            width = max(1, min(n_pairs,
                               pair_width(static, idxs, n_folds)))
            # flat pair index p ↔ (grid row, fold) = divmod(p, n_folds);
            # pad the final chunk by repeating the last pair (computed,
            # discarded). Dispatching `width` vmapped pairs at a time
            # keeps per-dispatch exec under the serving ceiling while the
            # per-call RPC overhead amortizes over `width` fits. Each
            # chunk is scored/materialized before the next dispatch, so
            # peak HBM is one chunk, not the whole group.
            prog = jax.jit(jax.vmap(one_pair))
            for s in range(0, n_pairs, width):
                ps = [min(s + t, n_pairs - 1) for t in range(width)]
                gs = [p // n_folds for p in ps]
                fs = [p % n_folds for p in ps]
                dchunk = {k: v[jnp.asarray(gs)] for k, v in dyn.items()}
                out = jax.block_until_ready(
                    prog(dchunk, W[jnp.asarray(fs)], V[jnp.asarray(fs)]))
                out_np = jax.tree_util.tree_map(np.asarray, out)
                for t in range(min(width, n_pairs - s)):
                    row_i, j = divmod(s + t, n_folds)
                    if metrics[idxs[row_i]] is None:
                        metrics[idxs[row_i]] = [None] * n_folds  # type: ignore
                    if host:
                        metrics[idxs[row_i]][j] = _metric(  # type: ignore
                            metric_fn.evaluator, y_np,
                            jax.tree_util.tree_map(
                                lambda a, t=t: a[t], out_np), V_np[j])
                    else:
                        metrics[idxs[row_i]][j] = \
                            float(out_np[t])  # type: ignore
            continue

        def one_cfg(d, fit_predict=fit_predict):
            def one_fold(w, v):
                pred = fit_predict(d, w)
                return pred if host else metric_fn(y, pred, v)
            return jax.vmap(one_fold)(W, V)

        gk = _run_block(one_cfg, dyn, sharding, grid_vmap(static, idxs))
        if host:
            pred_np = jax.tree_util.tree_map(np.asarray, gk)
            for row_i, grid_i in enumerate(idxs):
                metrics[grid_i] = [
                    _metric(metric_fn.evaluator, y_np,
                            {k: v[row_i, fold_j] for k, v in pred_np.items()},
                            V_np[fold_j])
                    for fold_j in range(V_np.shape[0])]
        else:
            gk = np.asarray(gk)
            for row_i, grid_i in enumerate(idxs):
                metrics[grid_i] = [float(m) for m in gk[row_i]]
    return metrics  # type: ignore[return-value]


# --------------------------------------------------------------------------- #
# family handlers                                                             #
# --------------------------------------------------------------------------- #

def _sweep_logistic(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    n_classes = est.n_classes or infer_n_classes(np.asarray(y))
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (int(_grid_param(est, g, "max_iter")),),
        dyn_of=lambda g: {"reg": float(_grid_param(est, g, "reg_param"))},
        build=lambda st, idxs: lambda d, w: predict_logreg(
            fit_logreg(X, y, w, d["reg"], n_classes, st[0]), X))


def _sweep_linreg(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (),
        dyn_of=lambda g: {"reg": float(_grid_param(est, g, "reg_param"))},
        build=lambda st, idxs: lambda d, w: predict_linreg(
            fit_linreg(X, y, w, d["reg"]), X))


def _sweep_svc(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (int(_grid_param(est, g, "max_iter")),),
        dyn_of=lambda g: {"reg": float(_grid_param(est, g, "reg_param"))},
        build=lambda st, idxs: lambda d, w: predict_linear_svc(
            fit_linear_svc(X, y, w, d["reg"], st[0]), X))


def _sweep_glm(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    def build(st, idxs):
        family, max_iter, var_power = st
        return lambda d, w: predict_glm(
            fit_glm(X, y, w, d["reg"], family, max_iter, var_power), X, family)
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (str(_grid_param(est, g, "family")),
                             int(_grid_param(est, g, "max_iter")),
                             float(_grid_param(est, g, "var_power"))),
        dyn_of=lambda g: {"reg": float(_grid_param(est, g, "reg_param"))},
        build=build)


def _sweep_nb(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    if bool(jnp.any(X < 0)):  # Spark parity: family fails, selector drops it
        raise ValueError(
            "NaiveBayes requires non-negative features (Spark parity)")
    n_classes = est.n_classes or infer_n_classes(np.asarray(y))
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (),
        dyn_of=lambda g: {"smoothing": float(_grid_param(est, g, "smoothing"))},
        build=lambda st, idxs: lambda d, w: predict_naive_bayes(
            fit_naive_bayes(X, y, w, d["smoothing"], n_classes), X))


def _sweep_mlp(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    n_classes = est.n_classes or infer_n_classes(np.asarray(y))
    seed = ctx.seed if ctx is not None else 0

    def build(st, idxs):
        hidden, max_iter = st
        layers = (int(X.shape[1]),) + tuple(hidden) + (n_classes,)
        return lambda d, w: predict_mlp(
            fit_mlp(X, y, w, layers, max_iter, d["lr"], seed), X)
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (tuple(_grid_param(est, g, "hidden_layers")),
                             int(_grid_param(est, g, "max_iter"))),
        dyn_of=lambda g: {"lr": float(_grid_param(est, g, "learning_rate"))},
        build=build)


# --------------------------------------------------------------------------- #
# tree families: padded-depth trick, one compile per (bins, trees) group      #
# --------------------------------------------------------------------------- #

# host-dispatch batching model: how many grid×fold pairs fit in one
# dispatch. The work unit is learners × rows × nodes × features × bins —
# the histogram-matmul FLOP shape — with per-family constants fit from
# measured v5e exec (~0.9s for a 20-tree depth-12 forest pair and ~0.55s
# for a 50-round depth-6 GBT pair, both on 90k×55×32-bin). The exec
# target keeps a >2x margin under the ~60s serving ceiling, and the
# memory bound caps the simultaneous (n, 2^depth) routing one-hots.
_PAIR_EXEC_TARGET_S = 25.0
_PAIR_MEM_BYTES = 4 << 30
# measured fits are 6.9e-14 (forest: 0.9s / 20·90000·2^12·55·32) and
# 1.1e-12 (gbt: 0.55s / 50·90000·2^6·55·32); the constants carry a
# deliberate 2-4x safety margin so tunnel exec variance cannot push a
# dispatch over the serving ceiling
_SEC_PER_UNIT_FOREST = 2.8e-13
_SEC_PER_UNIT_GBT = 2.3e-12


def _tree_pair_width(n: int, d: int, n_bins: int, learners: int,
                     sec_per_unit: float, pad_depth: int) -> int:
    nodes = 2 ** min(pad_depth, 14)
    est_s = max(0.05, float(learners) * n * nodes * d * n_bins
                * sec_per_unit)
    mem_per_pair = n * (d + nodes) * 2  # bf16 bytes
    w_exec = int(_PAIR_EXEC_TARGET_S / est_s)
    w_mem = int(_PAIR_MEM_BYTES // max(mem_per_pair, 1))
    return max(1, min(w_exec, w_mem))

def _binned_cache(est, grids, X, ctx) -> Dict[int, jnp.ndarray]:
    """Bin X once per distinct max_bins ACROSS tree families in a sweep:
    the cache lives on the FitContext, so RF and XGB in the same selector
    share the quantile binning of the identical training matrix. (The eager
    fallback path has its own per-estimator `_bin_cache`.)

    Quantile edges come from the UNPADDED rows (`ctx._sweep_n_rows`): mesh
    padding appends zero-weight rows which must not shift bin edges, or
    sharded sweeps would silently deviate from unsharded ones."""
    out = getattr(ctx, "_sweep_bin_cache", None) if ctx is not None else None
    if out is None:
        out = {}
        if ctx is not None:
            ctx._sweep_bin_cache = out
    n = getattr(ctx, "_sweep_n_rows", None) if ctx is not None else None
    X_edges = None  # device→host gather only on a cache miss
    for g in grids:
        mb = int(_grid_param(est, g, "max_bins"))
        if mb not in out:
            if X_edges is None:
                X_host = np.asarray(X)
                X_edges = X_host if n is None else X_host[:n]
            edges = quantile_bin_edges(X_edges, mb)
            out[mb] = bin_features(jnp.asarray(X), jnp.asarray(edges))
    return out


def _pad_depth_of(est, grids, idxs) -> int:
    return max(int(_grid_param(est, grids[i], "max_depth")) for i in idxs)


def _sweep_forest(est, grids, X, y, W, V, metric_fn, ctx, sharding,
                  regression: bool):
    xb_by_bins = _binned_cache(est, grids, X, ctx)
    if regression:
        Y = jnp.asarray(y)[:, None]
        n_out = 1
    else:
        k = est.n_classes or infer_n_classes(np.asarray(y))
        Y = jax.nn.one_hot(jnp.asarray(y).astype(jnp.int32), k)
        n_out = k
    seed = ctx.seed if ctx is not None else 0
    pred_fn = forest_regression_pred if regression else forest_classification_pred
    # single deterministic tree for DT estimators (no Poisson bootstrap), so
    # sweep metrics describe exactly what the refit fit_arrays produces
    bootstrap = not isinstance(
        est, (OpDecisionTreeClassifier, OpDecisionTreeRegressor))

    n_folds = int(np.asarray(W).shape[0]) if hasattr(W, "shape") else len(W)
    n_rows = int(np.asarray(y).shape[0])

    def width_of(st, idxs):
        n_trees, max_bins, _ = st[:3]
        pad_depth = _pad_depth_of(est, grids, idxs)
        # real dispatch width never exceeds the pair count — keep the
        # fit_forest chunk budget in step with actual live instances
        return min(len(idxs) * n_folds,
                   _tree_pair_width(n_rows, int(X.shape[1]), max_bins,
                                    n_trees, _SEC_PER_UNIT_FOREST,
                                    pad_depth))

    def build(st, idxs):
        n_trees, max_bins, subsample = st[:3]
        Xb = xb_by_bins[max_bins]
        pad_depth = _pad_depth_of(est, grids, idxs)
        # unsharded → host dispatch of `width` vmapped pairs at a time;
        # sharded → the whole grid×fold block is vmapped. Either way the
        # tree-chunking inside fit_forest budgets for every simultaneous
        # instance.
        divisor = (width_of(st, idxs) if sharding is None
                   else max(1, len(idxs) * n_folds))

        def fit_predict(d, w):
            trees = fit_forest(Xb, Y, w, n_trees, pad_depth, max_bins,
                               n_out, seed, subsample, d["mcw"],
                               active_depth=d["depth"], bootstrap=bootstrap,
                               tree_budget_divisor=divisor)
            return pred_fn(trees, Xb)
        return fit_predict

    # one PADDED compile per family group (traced active_depth masks the
    # unused levels): sweep wall-clock on a fresh process is dominated by
    # the remote AOT compiles (~15-50s each), not the sub-second padded
    # executions, so fewer compiles beats depth-exact programs
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (int(_grid_param(est, g, "n_trees")),
                             int(_grid_param(est, g, "max_bins")),
                             bool(_grid_param(est, g, "subsample_features"))),
        dyn_of=lambda g: {
            "depth": int(_grid_param(est, g, "max_depth")),
            "mcw": float(_grid_param(est, g, "min_child_weight"))},
        build=build,
        grid_vmap=lambda st, idxs: _pad_depth_of(est, grids, idxs) <= 6,
        host_dispatch=True,
        pair_width=lambda st, idxs, k: width_of(st, idxs))


def _sweep_gbt(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    xb_by_bins = _binned_cache(est, grids, X, ctx)
    objective = est._objective
    n_classes = 2
    if objective == "logistic":
        n_classes = getattr(est, "n_classes", None) or \
            infer_n_classes(np.asarray(y))
    seed = ctx.seed if ctx is not None else 0

    def lr_of(grid) -> float:
        v = grid.get("eta", grid.get("learning_rate"))
        if v is None:
            v = est.params.get("eta", getattr(est, "learning_rate", 0.1))
        return float(v)

    n_rows = int(np.asarray(y).shape[0])

    n_folds_g = int(np.asarray(W).shape[0]) if hasattr(W, "shape") else len(W)

    def width_of(st, idxs):
        n_estimators, max_bins = st[:2]
        pad_depth = _pad_depth_of(est, grids, idxs)
        return min(len(idxs) * n_folds_g,
                   _tree_pair_width(n_rows, int(X.shape[1]), max_bins,
                                    n_estimators, _SEC_PER_UNIT_GBT,
                                    pad_depth))

    def build(st, idxs):
        n_estimators, max_bins = st[:2]
        Xb = xb_by_bins[max_bins]
        pad_depth = _pad_depth_of(est, grids, idxs)

        def fit_predict(d, w):
            common = dict(min_child_weight=d["mcw"], active_depth=d["depth"],
                          gamma=d["gamma"], alpha=d["alpha"],
                          subsample=d["subsample"], colsample=d["colsample"],
                          seed=seed)
            if objective == "logistic" and n_classes > 2:
                _, margin = fit_gbt_multiclass(
                    Xb, y, w, n_estimators, pad_depth, max_bins, n_classes,
                    d["lr"], d["lam"], **common)
                return gbt_multiclass_pred_from_margin(margin)
            # the scan carry is the final training-matrix margin — no
            # post-fit forest re-walk needed
            _, margin = fit_gbt(Xb, y, w, n_estimators, pad_depth, max_bins,
                                d["lr"], d["lam"], objective, **common)
            return gbt_pred_from_margin(margin, objective)
        return fit_predict

    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: (int(_grid_param(est, g, "n_estimators")),
                             int(_grid_param(est, g, "max_bins"))),
        dyn_of=lambda g: {
            "depth": int(_grid_param(est, g, "max_depth")),
            "lr": lr_of(g),
            "lam": float(_grid_param(est, g, "reg_lambda")),
            "mcw": float(_grid_param(est, g, "min_child_weight")),
            "gamma": float(_grid_param(est, g, "gamma") or 0.0),
            "alpha": float(_grid_param(est, g, "alpha") or 0.0),
            "subsample": float(_grid_param(est, g, "subsample") or 1.0),
            "colsample": float(
                _grid_param(est, g, "colsample_bytree") or 1.0)},
        build=build,
        grid_vmap=lambda st, idxs: _pad_depth_of(est, grids, idxs) <= 6,
        host_dispatch=True,
        pair_width=lambda st, idxs, k: width_of(st, idxs))


# --------------------------------------------------------------------------- #
# dispatch                                                                    #
# --------------------------------------------------------------------------- #

def _dispatch(est) -> Optional[Callable]:
    # order matters: subclasses before parents
    if isinstance(est, (OpXGBoostClassifier, OpXGBoostRegressor,
                        OpGBTClassifier, OpGBTRegressor)):
        return _sweep_gbt
    if isinstance(est, (OpRandomForestRegressor, OpDecisionTreeRegressor)):
        return lambda *a: _sweep_forest(*a, regression=True)
    if isinstance(est, (OpRandomForestClassifier, OpDecisionTreeClassifier)):
        return lambda *a: _sweep_forest(*a, regression=False)
    if isinstance(est, OpLogisticRegression):
        return _sweep_logistic
    if isinstance(est, OpLinearRegression):
        return _sweep_linreg
    if isinstance(est, OpLinearSVC):
        return _sweep_svc
    if isinstance(est, OpGeneralizedLinearRegression):
        return _sweep_glm
    if isinstance(est, OpNaiveBayes):
        return _sweep_nb
    if isinstance(est, OpMultilayerPerceptronClassifier):
        return _sweep_mlp
    return None


def run_sweep(est, grids: List[Dict], X, y, folds, evaluator, ctx,
              sharding=None) -> List[List[float]]:
    """Metric matrix [grid][fold] for one model family."""
    handler = _dispatch(est)
    if handler is None:
        return _sweep_generic(est, grids, X, y, folds, evaluator, ctx)
    try:
        n_classes = getattr(est, "n_classes", None) or \
            infer_n_classes(np.asarray(y))
    except Exception:
        n_classes = None
    # no device kernel for this evaluator → batched fits, host metrics
    metric_fn = (make_device_metric(evaluator, n_classes=n_classes)
                 or HostMetricFallback(evaluator))
    # the cache entry RETAINS the keying objects so `is` comparisons are
    # safe (an id()-only key could false-hit after GC address reuse): a
    # FitContext reused with different X/y/folds (public run_sweep callers)
    # must not silently get the first call's arrays back
    def _same_data(key_objs) -> bool:
        kX, ky, kfolds = key_objs
        return (kX is X and ky is y and len(kfolds) == len(folds)
                and all(a is c and b is d
                        for (a, b), (c, d) in zip(kfolds, folds)))

    cached = getattr(ctx, "_sweep_data_cache", None) if ctx is not None else None
    if cached is not None and _same_data(cached[0]):
        _, X, y, W, V = cached  # same selector fit: reuse padded/sharded set
    else:
        key_objs = (X, y, list(folds))
        W = jnp.asarray(np.stack([tr for tr, _ in folds]))
        V = jnp.asarray(np.stack([va for _, va in folds]))
        if ctx is not None and ctx.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from transmogrifai_tpu.parallel.mesh import DATA_AXIS
            data_size = ctx.mesh.shape.get(DATA_AXIS, 1)
            n = int(np.asarray(y).shape[0])
            if data_size > 1:
                # every fit/metric is weight-masked, so rows pad with zero
                # weight in ALL folds — sharding never silently degrades to
                # replication on uneven row counts. Tree binning must ignore
                # the pad rows (see _binned_cache); bootstrap streams are
                # prefix-stable across the padded shape.
                ctx._sweep_n_rows = n
                pad = (-n) % data_size
                if pad:
                    X = jnp.concatenate(
                        [X, jnp.zeros((pad, X.shape[1]), X.dtype)])
                    y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
                    W = jnp.concatenate(
                        [W, jnp.zeros((W.shape[0], pad), W.dtype)], axis=1)
                    V = jnp.concatenate(
                        [V, jnp.zeros((V.shape[0], pad), V.dtype)], axis=1)
                mesh = ctx.mesh
                X = jax.device_put(X, NamedSharding(mesh, P(DATA_AXIS, None)))
                y = jax.device_put(y, NamedSharding(mesh, P(DATA_AXIS)))
                W = jax.device_put(W, NamedSharding(mesh, P(None, DATA_AXIS)))
                V = jax.device_put(V, NamedSharding(mesh, P(None, DATA_AXIS)))
        if ctx is not None:
            ctx._sweep_data_cache = (key_objs, X, y, W, V)
            ctx._sweep_bin_cache = {}  # binned-X cache is per-data too
    return handler(est, grids, X, y, W, V, metric_fn, ctx, sharding)
