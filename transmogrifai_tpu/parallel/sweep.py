"""The sweep engine: folds × models × grids as batched XLA programs.

Reference parity: `OpValidator.getSummary` / `OpCrossValidation.validate`
(`core/.../tuning/OpValidator.scala:299-358`, `OpCrossValidation.scala:87-147`)
— the reference dispatches each model×grid×fold fit as a Future running
Spark jobs; here EVERY model family (logistic, linear, GLM, SVC, NB, MLP,
random forest / decision tree, GBT / XGBoost) compiles its whole grid×fold
block into ONE XLA program: fit → predict → masked device metric
(`evaluators/device_metrics.py`), no host round-trips inside the sweep.

Static-shape strategy per family:
- linear-like: grids share one compile per distinct `max_iter`; the
  regularization axis is a traced vector, vmapped.
- trees: `max_depth` grids are PADDED to the group's largest depth and
  grown with a traced `active_depth` (models/trees.py), so a
  {3, 6, 12} depth grid is one compile; `min_child_weight`,
  `learning_rate`, `reg_lambda` are traced vectors.
- grid axis execution: `vmap` (parallel) when the sweep axis is sharded
  over a mesh or the family is cheap; `lax.scan`-based `lax.map`
  (sequential, single compile) for deep trees on a single device to bound
  the histogram working set.

Fault tolerance mirrors `OpValidator.scala:324-353`: a failing model family
is dropped with a warning; only all-families-failing raises.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.obs import export as obs_export
from transmogrifai_tpu.obs.trace import TRACER
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators.device_metrics import make_device_metric
from transmogrifai_tpu.models.base import infer_n_classes
from transmogrifai_tpu.models.glm import (
    OpGeneralizedLinearRegression, fit_glm, predict_glm)
from transmogrifai_tpu.models.linear import (
    OpLinearRegression, fit_linreg, fit_linreg_enet, predict_linreg)
from transmogrifai_tpu.models.linear_svc import (
    OpLinearSVC, fit_linear_svc, predict_linear_svc)
from transmogrifai_tpu.models.logistic import (
    OpLogisticRegression, enet_iters, fit_logreg, fit_logreg_enet,
    predict_logreg)
from transmogrifai_tpu.models.mlp import (
    OpMultilayerPerceptronClassifier, fit_mlp, predict_mlp)
from transmogrifai_tpu.models.naive_bayes import (
    OpNaiveBayes, fit_naive_bayes, predict_naive_bayes)
from transmogrifai_tpu.models.trees import (
    OpDecisionTreeClassifier, OpDecisionTreeRegressor, OpGBTClassifier,
    OpGBTRegressor, OpRandomForestClassifier, OpRandomForestRegressor,
    OpXGBoostClassifier, OpXGBoostRegressor,
    bin_features, fit_forest, fit_gbt, fit_gbt_multiclass,
    forest_classification_pred, forest_regression_pred,
    gbt_multiclass_pred_from_margin, gbt_pred_from_margin,
    quantile_bin_edges)
from transmogrifai_tpu.runtime.faults import (
    SITE_RUN_BLOCK, fault_point, is_oom_error)

log = logging.getLogger(__name__)


# --------------------------------------------------------------------------- #
# block journaling + fault-resilient group execution                          #
# --------------------------------------------------------------------------- #

# Per-family sweep state set by run_sweep for the duration of one family's
# handler call. Thread-local on purpose: families sweep concurrently on the
# selector's thread pool, each with its OWN journal file.
_SWEEP_TL = threading.local()


def _active_journal():
    return getattr(_SWEEP_TL, "journal", None)


class _BestTracker:
    """Running best-so-far (mean metric + grid) recorded into each journal
    entry, so a resumed operator can see where an interrupted sweep stood."""

    def __init__(self, larger_is_better: bool):
        self.sign = 1.0 if larger_is_better else -1.0
        self.best: Optional[Dict[str, Any]] = None

    def note(self, grid: Dict, row: List[float]) -> Optional[Dict[str, Any]]:
        mean = float(np.mean(row)) if row else float("nan")
        if np.isfinite(mean) and (
                self.best is None
                or self.sign * mean > self.sign * self.best["mean"]):
            self.best = {"mean": mean, "grid": grid}
        return self.best


def journal_prefill(journal, grids: List[Dict],
                    metrics: List[Optional[List[float]]],
                    event: str = "journal_resume") -> int:
    """Fill journaled rows into `metrics`; returns how many were skipped.
    Journal floats round-trip JSON exactly, so a resumed sweep's metric
    matrix is bit-identical to an uninterrupted run's. The ONE resume-
    skip implementation: the in-family path below, the distributed
    scheduler's per-job resume, and the pod scheduler's cross-host
    merge all route through it. `event` names the timeline event: a
    resume credits the journal with blocks it AVOIDED re-running
    ("journal_resume" savings in the goodput report), while a pod
    host merging shards for blocks other hosts ran THIS run records
    "pod_merge" — fleet work, not savings."""
    if journal is None:
        return 0
    hits = 0
    saved_s = 0.0
    for i, g in enumerate(grids):
        if metrics[i] is not None:
            continue
        row = journal.lookup(g)
        if row is not None:
            metrics[i] = row
            saved_s += journal.duration_of(g)
            hits += 1
    if hits:
        if event == "journal_resume":
            log.info("sweep journal: resuming past %d/%d completed blocks",
                     hits, len(grids))
            # resume-skip savings into the unified timeline + event log:
            # the goodput report credits the journal with the blocks it
            # avoided
            obs_export.record_event("journal_resume", blocks=hits,
                                    total=len(grids),
                                    saved_s=round(saved_s, 6))
        else:
            log.info("sweep journal: merged %d/%d foreign blocks (%s)",
                     hits, len(grids), event)
            obs_export.record_event(event, blocks=hits,
                                    total=len(grids),
                                    foreign_s=round(saved_s, 6))
    return hits


def _journal_prefill(grids: List[Dict],
                     metrics: List[Optional[List[float]]]) -> int:
    return journal_prefill(_active_journal(), grids, metrics)


def _journal_commit(grids: List[Dict],
                    metrics: List[Optional[List[float]]],
                    idxs: List[int],
                    block_s: Optional[float] = None,
                    facts: Optional[Dict] = None) -> None:
    journal = _active_journal()
    if journal is None:
        return
    best = getattr(_SWEEP_TL, "best", None)
    # the block ran its configs as one program: attribute wall time evenly
    per_cfg = (block_s / len(idxs)) if (block_s and idxs) else None
    block_facts = None
    if facts is not None and block_s is not None:
        # static-signature facts + the block's wall cost, stamped on
        # every record of the block under one block_key so a resumed
        # run's journal contributes training rows to the cost-model
        # corpus (perf/corpus.harvest_journal dedupes per block)
        block_facts = dict(facts)
        block_facts["block_s"] = round(float(block_s), 6)
        block_facts["block_key"] = _block_key_fn(grids)(idxs)
    for i in idxs:
        row = metrics[i]
        if row is None or any(m is None for m in row):
            continue
        journal.append(grids[i], row,
                       best=best.note(grids[i], row) if best else None,
                       duration_s=per_cfg, facts=block_facts)


def _run_groups_resilient(groups: Dict[Tuple, List[int]], run_one,
                          commit, family: str, facts=None,
                          block_key=None) -> None:
    """Execute grid-block groups with the fault-tolerance contract:

    - `fault_point(SITE_RUN_BLOCK)` fires before every block, so a chaos
      plan can kill/fail the sweep at any block boundary;
    - with a warm cost model (`perf/`), a block whose PREDICTED HBM
      footprint exceeds the budget is pre-shrunk into narrower parts
      BEFORE dispatch — the ``oom_redo`` badput the halving path would
      have paid is never spent (an ``hbm_preshrink`` event marks the
      decision); the halving path below stays as the fallback, and
      every OOM observed becomes a negative training example;
    - a device-OOM failure HALVES the block width and retries each half
      before surfacing (narrower blocks fit where wide ones did not —
      the compiled program per half persists in the compile cache); the
      failed wide attempt's wall time is recorded as an ``oom_redo``
      badput event on the enclosing span;
    - `commit(idxs, block_s, facts)` journals a block only after it
      fully completes, stamped with its wall cost + static-signature
      facts (resume-skip accounting and cost-model training rows).

    `facts(static, idxs)` returns the block's cost-model feature dict
    (`perf/features.block_features`); when provided, every executed
    block records its measured wall time (and predicted-vs-measured
    residual, when the model was warm) into the perf corpus — cold
    start changes NOTHING about execution, it only collects rows.
    `block_key(idxs)` stamps each row with the block's content key
    (same formula as the journal's `facts["block_key"]`) so a later
    `harvest_journal` of this run's journal recognizes the block as
    already recorded instead of duplicating it.
    """
    model = None
    budget = 0.0
    if facts is not None:
        try:
            from transmogrifai_tpu import perf as _perf
            model = _perf.get_model()
            budget = _perf.hbm_budget_bytes()
        except Exception:
            model = None

    def _note(target, feats, predicted, measured, **extra):
        try:
            from transmogrifai_tpu import perf as _perf
            _perf.note(target, feats, predicted, measured, **extra)
        except Exception:
            log.debug("perf recording failed", exc_info=True)

    def run(static, idxs):
        feats = facts(static, idxs) if facts is not None else None
        pred = (model.predict("block_runtime", feats)
                if model is not None and feats is not None else None)
        t0 = time.perf_counter()
        try:
            with TRACER.span("sweep:block", category="sweep",
                             family=family, static=repr(static),
                             configs=len(idxs)):
                fault_point(SITE_RUN_BLOCK)
                run_one(static, idxs)
        except Exception as e:
            if len(idxs) <= 1 or not is_oom_error(e):
                raise
            wasted = time.perf_counter() - t0
            obs_export.record_event("oom_redo", family=family,
                                    configs=len(idxs),
                                    wasted_s=round(wasted, 6))
            if feats is not None:
                # negative training example: this block's footprint
                # exceeded the device — teach the HBM target that shapes
                # like it sit past the budget, so the NEXT run's gate
                # pre-shrinks instead of paying this redo again
                from transmogrifai_tpu.perf.features import \
                    hbm_proxy_bytes
                proxy = hbm_proxy_bytes(feats)
                _note("hbm", feats, None,
                      max(proxy, budget or proxy) * 1.25, oom=True)
            mid = (len(idxs) + 1) // 2
            log.warning(
                "sweep %s block %r: device OOM with %d configs (%s) — "
                "halving block width and retrying", family, static,
                len(idxs), e)
            run(static, idxs[:mid])
            run(static, idxs[mid:])
            return
        block_s = time.perf_counter() - t0
        if feats is not None:
            from transmogrifai_tpu.perf.features import hbm_proxy_bytes
            extra = ({"block_key": block_key(idxs)}
                     if block_key is not None else {})
            _note("block_runtime", feats, pred, block_s, **extra)
            _note("hbm", feats, None, hbm_proxy_bytes(feats))
        commit(idxs, block_s, feats)

    for static, idxs in groups.items():
        parts = [idxs]
        if model is not None and facts is not None and budget > 0 \
                and len(idxs) > 1:
            hp = model.predict("hbm", facts(static, idxs))
            if hp is not None and hp.value > budget:
                import math as _math
                k = min(len(idxs), int(_math.ceil(hp.value / budget)))
                if k > 1:
                    step = -(-len(idxs) // k)
                    parts = [idxs[i:i + step]
                             for i in range(0, len(idxs), step)]
                    obs_export.record_event(
                        "hbm_preshrink", family=family,
                        configs=len(idxs), parts=len(parts),
                        predicted_bytes=round(hp.value),
                        budget_bytes=round(budget))
                    log.info(
                        "sweep %s block %r: predicted HBM %.2f GB over "
                        "the %.2f GB budget — pre-shrinking %d configs "
                        "into %d parts (no OOM redo)", family, static,
                        hp.value / 2**30, budget / 2**30, len(idxs),
                        len(parts))
        for part in parts:
            run(static, part)


# --------------------------------------------------------------------------- #
# host-path fallback (LambdaEvaluator / unknown model classes)                #
# --------------------------------------------------------------------------- #

def _metric(evaluator, y: np.ndarray, pred: Dict[str, np.ndarray],
            val_mask: np.ndarray) -> float:
    idx = val_mask > 0.5
    label = Column(T.RealNN, {
        "value": y[idx], "mask": np.ones(int(idx.sum()), dtype=bool)})
    pcol = Column(T.Prediction, {k: np.asarray(v)[idx] for k, v in pred.items()})
    return evaluator.metric_value(label, pcol)


def _sweep_generic(est, grids: List[Dict], X, y, folds, evaluator,
                   ctx) -> List[List[float]]:
    """Fallback: python loop over grids × folds (host metric path). A
    grid config is the journaling block here: journaled configs are
    skipped, completed configs append as soon as their folds finish."""
    from transmogrifai_tpu.models.trees import _TreeEstimatorBase
    out: List[List[float]] = []
    y_np = np.asarray(y)
    journal = _active_journal()
    best = getattr(_SWEEP_TL, "best", None)
    bin_cache: Dict = {}  # shared across the family: bin X once per max_bins
    hits, saved_s = 0, 0.0
    for grid in grids:
        cached = journal.lookup(grid) if journal is not None else None
        if cached is not None:
            out.append(cached)
            if best is not None:
                best.note(grid, cached)
            hits += 1
            saved_s += journal.duration_of(grid)
            continue
        t0 = time.perf_counter()
        with TRACER.span("sweep:block", category="sweep",
                         family=type(est).__name__, configs=1):
            fault_point(SITE_RUN_BLOCK)
            clone = type(est)(**{**{k: v for k, v in est.params.items()
                                    if k != "uid"}, **grid})
            if isinstance(clone, _TreeEstimatorBase):
                clone._bin_cache = bin_cache
            row = []
            for tr, va in folds:
                with _DispatchSpan():  # visible to tree-family calib timing
                    model = clone.fit_arrays(X, y, jnp.asarray(tr), ctx)
                    pred = model.predict_arrays(X)
                row.append(_metric(
                    evaluator, y_np,
                    {k: np.asarray(v) for k, v in pred.items()}, va))
        out.append(row)
        if journal is not None:
            journal.append(grid, row,
                           best=best.note(grid, row) if best else None,
                           duration_s=time.perf_counter() - t0)
    if hits:
        obs_export.record_event("journal_resume", blocks=hits,
                                total=len(grids),
                                saved_s=round(saved_s, 6))
    return out


# --------------------------------------------------------------------------- #
# batched execution scaffold                                                  #
# --------------------------------------------------------------------------- #

def _grid_param(est, grid: Dict, name: str) -> Any:
    return grid.get(name, getattr(est, name, est.params.get(name)))


class HostMetricFallback:
    """Marker metric_fn: run the batched fit+predict XLA program, but score
    with a host evaluator (LambdaEvaluator / metrics with no device kernel).
    """

    def __init__(self, evaluator):
        self.evaluator = evaluator


def _shard_dyn(dyn: Dict[str, jnp.ndarray],
               sharding) -> Tuple[Dict[str, jnp.ndarray], int]:
    """Place the grid axis on the mesh's sweep axis; PAD a non-divisible
    group to the next multiple by repeating the last config (padded rows
    compute real but discarded fits — cheaper than replicating the whole
    group on every shard). Returns (dyn, original_g)."""
    if sharding is None:
        return dyn, next(iter(dyn.values())).shape[0]
    g = next(iter(dyn.values())).shape[0]
    n_shards = sharding.mesh.shape[sharding.spec[0]] if sharding.spec else 1
    if n_shards > 1 and g % n_shards != 0:
        pad = n_shards - g % n_shards
        dyn = {k: jnp.concatenate([v, jnp.repeat(v[-1:], pad, 0)])
               for k, v in dyn.items()}
    return {k: jax.device_put(v, sharding) for k, v in dyn.items()}, g


def _run_block(one_cfg: Callable, dyn: Dict[str, jnp.ndarray], sharding,
               grid_vmap: bool, label: str = "sweep:block"):
    """Execute one grid block: one_cfg(dyn_slice) over the grid axis.

    vmap → parallel over grids (sharded across the mesh's sweep axis when
    `sharding` is set); lax.map → sequential single compile (bounds the peak
    memory of deep-tree histogram building on one chip). Returns the raw
    jax output (a (g, k) metric array, or a prediction pytree with leading
    (g, k) axes on the host-metric fallback path).
    """
    from transmogrifai_tpu.analysis.retrace import instrumented_jit
    dyn, g = _shard_dyn(dyn, sharding)
    if grid_vmap or sharding is not None:
        prog = instrumented_jit(jax.vmap(one_cfg), label=label)
    else:
        prog = instrumented_jit(lambda d: jax.lax.map(one_cfg, d),
                                label=label)
    # span-wrapped (even though THIS site never feeds calibration) so a
    # tree family timing a dispatch on another thread sees the overlap —
    # a linear-family execution queues tree dispatches just the same
    with _DispatchSpan():
        out = jax.block_until_ready(prog(dyn))
    return jax.tree_util.tree_map(lambda a: a[:g], out)  # drop pad rows


def _sweep_blocks(grids: List[Dict], y, W, V, metric_fn, sharding,
                  static_of: Callable[[Dict], Tuple],
                  dyn_of: Callable[[Dict], Dict[str, Any]],
                  build: Callable[[Tuple, List[int]], Callable],
                  grid_vmap: Callable[[Tuple, List[int]], bool] = lambda s, i: True,
                  host_dispatch: bool = False,
                  pair_width: Callable[[Tuple, List[int], int], int]
                  = lambda s, i, k: 1,
                  calibrate: Optional[Callable[[Tuple, List[int], float, int,
                                                int, bool], int]] = None,
                  fit_takes_val: bool = False,
                  family: str = "generic",
                  x_info: Optional[Tuple[int, int]] = None,
                  ) -> List[List[float]]:
    """Shared scaffold: group grids by static params; per group, stack the
    dynamic params into traced vectors and run fit→predict→metric as one
    program. `build(static, idxs)` returns `fit_predict(dyn_slice, w) -> pred`.

    A `HostMetricFallback` metric_fn (custom/LambdaEvaluator metrics with no
    device kernel) keeps the batched fit+predict program but evaluates the
    wrapped evaluator over the materialized (g, k, n, …) prediction pytree
    on host — fits stay one XLA program per group either way.

    `host_dispatch` (tree families, single device only): compile ONE
    fit→predict→metric program per static group and dispatch it per
    grid×fold pair from the host instead of folding the whole group into a
    single giant execution. Compile count is unchanged; per-dispatch device
    time stays seconds even for 20-tree depth-12 forests on 100k rows —
    monolithic sweep executions past ~60s get killed by serving
    infrastructure (and a host loop also bounds peak HBM). With a mesh
    (`sharding`), the batched path runs so the grid axis shards.

    `x_info` = (n_features, wire dtype bytes) of the training matrix —
    the handlers pass it so every executed block can be described to
    the cost model (`perf/features.block_features`) without this
    scaffold touching X itself.
    """
    metrics: List[Optional[List[float]]] = [None] * len(grids)
    _journal_prefill(grids, metrics)  # resume: skip completed blocks
    groups: Dict[Tuple, List[int]] = {}
    for i, g in enumerate(grids):
        if metrics[i] is None:
            groups.setdefault(static_of(g), []).append(i)
    host = isinstance(metric_fn, HostMetricFallback)
    y_np = np.asarray(y) if host else None
    V_np = np.asarray(V) if host else None
    def _run_group(static, idxs):
        dyn_dicts = [dyn_of(grids[i]) for i in idxs]
        dyn = {k: jnp.asarray([d[k] for d in dyn_dicts],
                              jnp.int32 if isinstance(dyn_dicts[0][k], int)
                              else jnp.float32)
               for k in dyn_dicts[0]}
        fit_predict = build(static, idxs)

        if host_dispatch and sharding is None:
            def one_pair(d, w, v, fit_predict=fit_predict):
                pred = (fit_predict(d, w, v) if fit_takes_val
                        else fit_predict(d, w))
                return pred if host else metric_fn(y, pred, v)

            n_folds = int(np.asarray(W).shape[0])
            n_pairs = len(idxs) * n_folds
            width = max(1, min(n_pairs,
                               pair_width(static, idxs, n_folds)))
            # flat pair index p ↔ (grid row, fold) = divmod(p, n_folds);
            # pad the final chunk by repeating the last pair (computed,
            # discarded). Dispatching `width` vmapped pairs at a time
            # keeps per-dispatch exec under the serving ceiling while the
            # per-call RPC overhead amortizes over `width` fits. Each
            # chunk is scored/materialized before the next dispatch, so
            # peak HBM is one chunk, not the whole group. `calibrate`
            # may resize `width` between dispatches from measured wall
            # time (a resize recompiles, so it only fires when the
            # remaining work amortizes the new compile).
            import time as _time

            from transmogrifai_tpu.analysis.retrace import instrumented_jit
            prog = instrumented_jit(
                jax.vmap(one_pair),
                label=f"sweep:{family}:{static!r}:pairs")
            s = 0
            # device-metric path: every chunk's output is a tiny (width,)
            # metric vector, but each np.asarray costs a ~0.7s tunnel
            # fetch RPC regardless of size (r4 measurement) — so chunks
            # accumulate as DEVICE arrays and materialize in ONE fetch
            # after the loop (r5, the sweep analogue of
            # score_stream(fetch_group)). The host-metric fallback still
            # fetches per chunk: it needs the full prediction pytree and
            # bounding peak HBM to one chunk matters there.
            pend: List[Tuple[int, int, Any]] = []  # (s, width, device out)
            while s < n_pairs:
                ps = [min(s + t, n_pairs - 1) for t in range(width)]
                gs = [p // n_folds for p in ps]
                fs = [p % n_folds for p in ps]
                dchunk = {k: v[jnp.asarray(gs)] for k, v in dyn.items()}
                with _DispatchSpan() as span:
                    t0 = _time.perf_counter()
                    out = jax.block_until_ready(
                        prog(dchunk, W[jnp.asarray(fs)], V[jnp.asarray(fs)]))
                    dt = _time.perf_counter() - t0
                SWEEP_STATS.record((id(prog), static, width), dt,
                                   clean=span.clean)
                if host:
                    out_np = jax.tree_util.tree_map(np.asarray, out)
                    for t in range(min(width, n_pairs - s)):
                        row_i, j = divmod(s + t, n_folds)
                        if metrics[idxs[row_i]] is None:
                            metrics[idxs[row_i]] = [None] * n_folds  # type: ignore
                        metrics[idxs[row_i]][j] = _metric(  # type: ignore
                            metric_fn.evaluator, y_np,
                            jax.tree_util.tree_map(
                                lambda a, t=t: a[t], out_np), V_np[j])
                else:
                    pend.append((s, width, out))
                s += width
                if calibrate is not None and s < n_pairs:
                    new_w = max(1, min(calibrate(static, idxs, dt, width,
                                                 n_pairs - s, span.clean),
                                       n_pairs - s))
                    if new_w != width:
                        # same jitted fn — the new chunk shape compiles on
                        # first use and persists in the compile cache
                        log.info("sweep dispatch width recalibrated "
                                 "%d -> %d (measured %.1fs)", width, new_w, dt)
                        width = new_w
            if pend:
                flat = np.asarray(jnp.concatenate(
                    [jnp.asarray(o, jnp.float32) for _, _, o in pend]))
                off = 0
                for s0, w0, _ in pend:
                    for t in range(min(w0, n_pairs - s0)):
                        row_i, j = divmod(s0 + t, n_folds)
                        if metrics[idxs[row_i]] is None:
                            metrics[idxs[row_i]] = [None] * n_folds  # type: ignore
                        metrics[idxs[row_i]][j] = float(flat[off + t])  # type: ignore
                    off += w0
            return

        def one_cfg(d, fit_predict=fit_predict):
            def one_fold(w, v):
                pred = (fit_predict(d, w, v) if fit_takes_val
                        else fit_predict(d, w))
                return pred if host else metric_fn(y, pred, v)
            return jax.vmap(one_fold)(W, V)

        gk = _run_block(one_cfg, dyn, sharding, grid_vmap(static, idxs),
                        label=f"sweep:{family}:{static!r}")
        if host:
            pred_np = jax.tree_util.tree_map(np.asarray, gk)
            for row_i, grid_i in enumerate(idxs):
                metrics[grid_i] = [
                    _metric(metric_fn.evaluator, y_np,
                            {k: v[row_i, fold_j] for k, v in pred_np.items()},
                            V_np[fold_j])
                    for fold_j in range(V_np.shape[0])]
        else:
            gk = np.asarray(gk)
            for row_i, grid_i in enumerate(idxs):
                metrics[grid_i] = [float(m) for m in gk[row_i]]

    # groups run SEQUENTIALLY on purpose (families already overlap on
    # the selector's thread pool): fanning groups out as well would (a)
    # multiply concurrently-live dispatch chunks past the per-dispatch
    # _PAIR_MEM_BYTES budget (device OOM faults poison the process on
    # this serving stack), (b) poison the persisted width calibration
    # with queue-contention time — and width feeds compiled dispatch
    # shapes, defeating the stable-shape/persistent-cache strategy, and
    # (c) let later groups reuse calibration learned by earlier ones.
    _run_groups_resilient(
        groups, _run_group,
        commit=lambda idxs, block_s=None, facts=None: _journal_commit(
            grids, metrics, idxs, block_s, facts),
        family=family,
        facts=_block_facts_fn(family, y, W, x_info),
        block_key=_block_key_fn(grids))
    return metrics  # type: ignore[return-value]


def _block_key_fn(grids: List[Dict]):
    """Content key of a block (the grids it ran), matching
    `_journal_commit`'s `facts["block_key"]` — one identity shared by
    live corpus rows and journal records so harvests never duplicate a
    block this process already recorded."""
    from transmogrifai_tpu.runtime.journal import SweepJournal

    def key(idxs: List[int]) -> str:
        return SweepJournal.key_of({"block": [grids[i] for i in idxs]})
    return key


def _x_info(X) -> Tuple[int, int]:
    """(n_features, wire dtype bytes) of a training matrix — the shape
    facts the cost model keys block features on."""
    try:
        return int(X.shape[1]), int(np.dtype(X.dtype).itemsize)
    except (AttributeError, IndexError, TypeError):
        return 0, 4


def _block_facts_fn(family: str, y, W, x_info: Optional[Tuple[int, int]]):
    """The `facts(static, idxs)` callback `_run_groups_resilient` feeds
    the cost model; None (no x_info) keeps the group runner silent."""
    if x_info is None:
        return None
    n_cols, dtype_bytes = x_info
    n_rows = int(np.shape(y)[0])
    n_folds = int(np.shape(W)[0]) if hasattr(W, "shape") else len(W)

    def facts(static, idxs):
        from transmogrifai_tpu.perf.features import block_features
        return block_features(family, static, len(idxs), n_rows, n_cols,
                              n_folds, dtype_bytes)
    return facts


# --------------------------------------------------------------------------- #
# family handlers                                                             #
# --------------------------------------------------------------------------- #

def _enet_of(est, g) -> float:
    return float(_grid_param(est, g, "elastic_net_param") or 0.0)


def _l1_l2_of(est, g) -> Dict[str, float]:
    """Spark penalty split: reg·α → L1, reg·(1−α) → L2
    (`DefaultSelectorParams.scala:48` ElasticNet {0.1, 0.5})."""
    reg = float(_grid_param(est, g, "reg_param"))
    alpha = _enet_of(est, g)
    return {"l1": reg * alpha, "l2": reg * (1.0 - alpha)}


# -- per-family static grouping keys ---------------------------------------- #
# Module-level (not closures inside the handlers) on purpose: the
# distributed scheduler (parallel/scheduler.py) partitions a family's
# grids into work blocks along EXACTLY these boundaries, so a scheduled
# block regroups into one compiled program on its worker — the same
# static-shape strategy as the single-device sweep, just spread over the
# mesh. The handlers below pass these same functions to `_sweep_blocks`.

def _static_logistic(est, g) -> Tuple:
    return (int(_grid_param(est, g, "max_iter")), _enet_of(est, g) > 0.0)


def _static_linreg(est, g) -> Tuple:
    return (_enet_of(est, g) > 0.0,)


def _static_svc(est, g) -> Tuple:
    return (int(_grid_param(est, g, "max_iter")),)


def _static_glm(est, g) -> Tuple:
    ln = _grid_param(est, g, "link")
    return (str(_grid_param(est, g, "family")),
            int(_grid_param(est, g, "max_iter")),
            float(_grid_param(est, g, "var_power")),
            str(ln) if ln is not None else None)


def _static_nb(est, g) -> Tuple:
    return ()


def _static_mlp(est, g) -> Tuple:
    return (tuple(_grid_param(est, g, "hidden_layers")),
            int(_grid_param(est, g, "max_iter")))


def _static_forest(est, g) -> Tuple:
    return (int(_grid_param(est, g, "n_trees")),
            int(_grid_param(est, g, "max_bins")),
            bool(_grid_param(est, g, "subsample_features")),
            _depth_bucket(int(_grid_param(est, g, "max_depth"))))


def _static_gbt(est, g) -> Tuple:
    return (int(_grid_param(est, g, "n_estimators")),
            int(_grid_param(est, g, "max_bins")),
            int(_grid_param(est, g, "early_stopping_rounds") or 0),
            _depth_bucket(int(_grid_param(est, g, "max_depth"))))


def static_signature(est, grid: Dict) -> Tuple:
    """The (family, static-group) key a grid config compiles under.

    Two grids with equal signatures share one batched XLA program in the
    family handlers; the distributed scheduler uses this to cut a
    family's grid list into blocks that never split a compiled group
    (a split group would compile twice at different dyn-vector shapes).
    Unknown estimator classes fall back to per-config blocks (they run
    the eager `_sweep_generic` path, where a config IS the unit)."""
    if isinstance(est, (OpXGBoostClassifier, OpXGBoostRegressor,
                        OpGBTClassifier, OpGBTRegressor)):
        return ("gbt", _static_gbt(est, grid))
    if isinstance(est, (OpRandomForestRegressor, OpDecisionTreeRegressor,
                        OpRandomForestClassifier, OpDecisionTreeClassifier)):
        return ("forest", _static_forest(est, grid))
    if isinstance(est, OpLogisticRegression):
        return ("logistic", _static_logistic(est, grid))
    if isinstance(est, OpLinearRegression):
        return ("linreg", _static_linreg(est, grid))
    if isinstance(est, OpLinearSVC):
        return ("svc", _static_svc(est, grid))
    if isinstance(est, OpGeneralizedLinearRegression):
        return ("glm", _static_glm(est, grid))
    if isinstance(est, OpNaiveBayes):
        return ("naive_bayes", _static_nb(est, grid))
    if isinstance(est, OpMultilayerPerceptronClassifier):
        return ("mlp", _static_mlp(est, grid))
    from transmogrifai_tpu.runtime.journal import SweepJournal
    return ("generic", SweepJournal.key_of(grid))


def _sweep_logistic(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    n_classes = est.n_classes or infer_n_classes(np.asarray(y))

    def build(st, idxs):
        max_iter, enet = st
        if enet:  # FISTA path — one compile covers the whole (l1, l2) grid
            iters = enet_iters(max_iter)
            return lambda d, w: predict_logreg(
                fit_logreg_enet(X, y, w, d["l1"], d["l2"], n_classes,
                                iters), X)
        return lambda d, w: predict_logreg(
            fit_logreg(X, y, w, d["l2"], n_classes, max_iter), X)

    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_logistic(est, g),
        dyn_of=lambda g: _l1_l2_of(est, g),
        build=build, family="logistic", x_info=_x_info(X))


def _sweep_linreg(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    def build(st, idxs):
        if st[0]:  # any L1 in the group → FISTA elastic net
            return lambda d, w: predict_linreg(
                fit_linreg_enet(X, y, w, d["l1"], d["l2"]), X)
        return lambda d, w: predict_linreg(fit_linreg(X, y, w, d["l2"]), X)

    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_linreg(est, g),
        dyn_of=lambda g: _l1_l2_of(est, g),
        build=build, family="linreg", x_info=_x_info(X))


def _sweep_svc(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_svc(est, g),
        dyn_of=lambda g: {"reg": float(_grid_param(est, g, "reg_param"))},
        build=lambda st, idxs: lambda d, w: predict_linear_svc(
            fit_linear_svc(X, y, w, d["reg"], st[0]), X),
        family="svc", x_info=_x_info(X))


def _sweep_glm(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    def build(st, idxs):
        family, max_iter, var_power, link = st
        return lambda d, w: predict_glm(
            fit_glm(X, y, w, d["reg"], family, max_iter, var_power, link),
            X, family, link, var_power)

    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_glm(est, g),
        dyn_of=lambda g: {"reg": float(_grid_param(est, g, "reg_param"))},
        build=build, family="glm", x_info=_x_info(X))


def _sweep_nb(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    # Spark parity: family fails on negative features, selector drops it.
    # The host read is a blocking device sync (~1s through the tunnel), so
    # the verdict is cached per training matrix on the FitContext — one
    # sync per selector fit, not one per NB sweep/fold.
    cache = getattr(ctx, "_nb_nonneg_cache", None) if ctx is not None else None
    if cache is None or cache[0] is not X:
        cache = (X, bool(jnp.any(X < 0)))
        if ctx is not None:
            ctx._nb_nonneg_cache = cache
    if cache[1]:
        raise ValueError(
            "NaiveBayes requires non-negative features (Spark parity)")
    n_classes = est.n_classes or infer_n_classes(np.asarray(y))
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_nb(est, g),
        dyn_of=lambda g: {"smoothing": float(_grid_param(est, g, "smoothing"))},
        build=lambda st, idxs: lambda d, w: predict_naive_bayes(
            fit_naive_bayes(X, y, w, d["smoothing"], n_classes), X),
        family="naive_bayes", x_info=_x_info(X))


def _sweep_mlp(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    n_classes = est.n_classes or infer_n_classes(np.asarray(y))
    seed = ctx.seed if ctx is not None else 0

    def build(st, idxs):
        hidden, max_iter = st
        layers = (int(X.shape[1]),) + tuple(hidden) + (n_classes,)
        return lambda d, w: predict_mlp(
            fit_mlp(X, y, w, layers, max_iter, d["lr"], seed), X)
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_mlp(est, g),
        dyn_of=lambda g: {"lr": float(_grid_param(est, g, "learning_rate"))},
        build=build, family="mlp", x_info=_x_info(X))


# --------------------------------------------------------------------------- #
# tree families: padded-depth trick, one compile per (bins, trees) group      #
# --------------------------------------------------------------------------- #

# host-dispatch batching model: how many grid×fold pairs fit in one
# dispatch. The work unit is learners × rows × nodes × features × bins —
# the histogram-matmul FLOP shape. The INITIAL per-family constants were
# fit on one v5e at 90k×55×32-bin; every real dispatch is then timed and
# the measured sec/unit (EMA, RPC overhead subtracted) replaces the guess
# for the rest of the process — a different TPU generation or feature
# width recalibrates itself after one dispatch instead of over/under-
# shooting the ~60s serving ceiling. The exec target keeps a >2x margin
# under that ceiling; the memory bound caps the simultaneous bin one-hots
# (n·d·bins bf16) plus deepest-level routing one-hots (n·2^depth bf16).
_PAIR_EXEC_TARGET_S = 25.0
_PAIR_MEM_BYTES = 4 << 30
_DISPATCH_OVERHEAD_S = 0.7  # tunnel RPC per dispatch, excluded from calib
# initial guesses (r2-measured with a 2-4x safety margin): forest 0.9s /
# 20·90000·2^12·55·32, gbt 0.55s / 50·90000·2^6·55·32
_CALIB_INIT = {"forest": 2.8e-13, "gbt": 2.3e-12}
_CALIB: Dict[str, float] = {}
_CALIB_LOADED = False


class SweepStats:
    """Per-process dispatch accounting (SURVEY §5.1 'measure instead'):
    how much of a sweep's wall-clock the device dispatch loop actually
    occupies, and how much went to first-execution (compile) overhead.
    `bench.py` resets before a sweep and reports the fractions."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self.dispatch_s = 0.0
            self.dispatches = 0
            # CLEAN = no other dispatch overlapped the measurement; only
            # clean numbers feed the warm-mean/compile estimate — an
            # overlapped wall-clock includes another family's queue time
            # (r4 advisor, medium)
            self.clean_s = 0.0
            self.cleans = 0
            self.first_s = 0.0   # first CLEAN execution of a program shape
            self.firsts = 0
            self._seen: set = set()

    def record(self, key, seconds: float, clean: bool = True) -> None:
        with self._lock:
            self.dispatch_s += seconds
            self.dispatches += 1
            if not clean:
                # mark seen so a later clean run of the same program is
                # not miscounted as a first, but keep the contaminated
                # seconds out of both the first and the warm pools
                self._seen.add(key)
                return
            self.clean_s += seconds
            self.cleans += 1
            if key not in self._seen:
                self._seen.add(key)
                self.first_s += seconds
                self.firsts += 1

    def compile_estimate_s(self) -> float:
        """First-execution seconds minus what those executions would cost
        warm (estimated from the observed clean warm mean) ≈ compile +
        cache-lookup overhead. Uses only clean dispatches on both sides."""
        warm_n = self.cleans - self.firsts
        if warm_n <= 0:
            return self.first_s
        warm_mean = (self.clean_s - self.first_s) / warm_n
        return max(0.0, self.first_s - warm_mean * self.firsts)


SWEEP_STATS = SweepStats()


# Concurrent-dispatch detection: families sweep on the selector's thread
# pool, so one family's `block_until_ready` wall-clock can include time
# queued behind ANOTHER family's device execution. Feeding that inflated
# measurement into `_record_calib` persists a too-slow sec/unit (the EMA
# leans 0.7 toward slower), which shrinks dispatch widths and forces
# fresh compiled shapes mid-sweep — exactly the instabilities the
# sequential-groups comment in `_sweep_blocks` guards against (r4
# advisor, medium). Every timed device dispatch wraps itself in
# `_DispatchSpan`; a measurement is CLEAN only if no other span was live
# at entry and none started before it exited.
_SPAN_LOCK = threading.Lock()
_SPAN_ACTIVE = 0
_SPAN_STARTS = 0


class _DispatchSpan:
    """Context manager around one timed device dispatch; `.clean` (valid
    after exit) is True iff no other dispatch overlapped it."""

    def __enter__(self):
        global _SPAN_ACTIVE, _SPAN_STARTS
        with _SPAN_LOCK:
            _SPAN_ACTIVE += 1
            _SPAN_STARTS += 1
            self._epoch = _SPAN_STARTS
            self.clean = _SPAN_ACTIVE == 1
        return self

    def __exit__(self, *exc):
        global _SPAN_ACTIVE
        with _SPAN_LOCK:
            _SPAN_ACTIVE -= 1
            if _SPAN_STARTS != self._epoch:  # someone started during us
                self.clean = False
        return False


def _calib_path() -> str:
    import os
    from transmogrifai_tpu.store.config import cache_root
    return os.path.join(cache_root(), "sweep_calib.json")


def _load_calib() -> None:
    """Measured sec/unit persists beside the XLA compile cache so a NEW
    process starts from the previous run's measurements — widths converge
    to the same values run over run, which also keeps dispatch shapes
    stable for the persistent compile cache."""
    global _CALIB_LOADED
    if _CALIB_LOADED:
        return
    _CALIB_LOADED = True
    import json as _json
    import os
    try:
        if os.path.exists(_calib_path()):
            with open(_calib_path()) as f:
                _CALIB.update({k: float(v) for k, v in _json.load(f).items()})
    except (OSError, ValueError, TypeError):
        log.debug("sweep calibration file unreadable; using initial "
                  "estimates", exc_info=True)


def _save_calib() -> None:
    import json as _json
    import os
    try:
        os.makedirs(os.path.dirname(_calib_path()), exist_ok=True)
        tmp = _calib_path() + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(_CALIB, f)
        os.replace(tmp, _calib_path())
    except OSError:
        pass


def _sec_per_unit(kind: str) -> float:
    _load_calib()
    return _CALIB.get(kind, _CALIB_INIT[kind])


_CALIB_LOCK = threading.Lock()


def _record_calib(kind: str, seconds: float, units: float) -> float:
    """Fold one measured dispatch into the family's sec/unit estimate.
    Conservative EMA: jumps fast on slower-than-expected, slow on faster
    (serving-kill risk is asymmetric). Locked: families sweep on a thread
    pool, and a racy read-modify-write (or two writers interleaving the
    same .tmp file) would corrupt the persisted calibration the stable-
    shape strategy depends on."""
    if units <= 0:
        return _sec_per_unit(kind)
    with _CALIB_LOCK:
        measured = max(seconds - _DISPATCH_OVERHEAD_S, 0.02) / units
        # the lock intentionally covers the read-modify-write AND the
        # persisted .tmp/replace below: two interleaved writers would
        # corrupt the calibration file (see docstring)
        # conc-ok: C003 (calibration RMW + persist must be atomic)
        prev = _sec_per_unit(kind) if kind in _CALIB else None
        if prev is None:
            new = measured
        elif measured > prev:
            new = 0.3 * prev + 0.7 * measured
        else:
            new = 0.7 * prev + 0.3 * measured
        _CALIB[kind] = new
        # conc-ok: C003 (calibration RMW + persist must be atomic)
        _save_calib()
        return new


def _pow2_floor(x: int) -> int:
    return 1 << max(0, int(x).bit_length() - 1)


def _tree_pair_width(n: int, d: int, n_bins: int, learners: int,
                     sec_per_unit: float, pad_depth: int) -> int:
    nodes = 2 ** min(pad_depth, 14)
    est_s = max(0.05, float(learners) * n * nodes * d * n_bins
                * sec_per_unit)
    mem_per_pair = n * (d * n_bins + nodes) * 2  # bf16 bytes
    w_exec = int(_PAIR_EXEC_TARGET_S / est_s)
    w_mem = int(_PAIR_MEM_BYTES // max(mem_per_pair, 1))
    # power-of-2 width: small calibration drift between runs must not
    # change the dispatch shape (every distinct width is a fresh remote
    # AOT compile that misses the persistent cache)
    return _pow2_floor(max(1, min(w_exec, w_mem)))

def _binned_cache(est, grids, X, ctx) -> Dict[int, jnp.ndarray]:
    """Bin X once per distinct max_bins ACROSS tree families in a sweep:
    the cache lives on the FitContext, so RF and XGB in the same selector
    share the quantile binning of the identical training matrix. (The eager
    fallback path has its own per-estimator `_bin_cache`.)

    Quantile edges come from the UNPADDED rows (`ctx._sweep_n_rows`): mesh
    padding appends zero-weight rows which must not shift bin edges, or
    sharded sweeps would silently deviate from unsharded ones.

    Guarded by a lock: tree families now sweep on a thread pool, and two
    families hitting the same max_bins must not double-build the (n, d)
    binned matrix."""
    with _BIN_CACHE_LOCK:
        out = (getattr(ctx, "_sweep_bin_cache", None)
               if ctx is not None else None)
        if out is None:
            out = {}
            if ctx is not None:
                ctx._sweep_bin_cache = out
        n = getattr(ctx, "_sweep_n_rows", None) if ctx is not None else None
        X_edges = None  # device→host gather only on a cache miss
        for g in grids:
            mb = int(_grid_param(est, g, "max_bins"))
            if mb not in out:
                if X_edges is None:
                    X_host = np.asarray(X)
                    X_edges = X_host if n is None else X_host[:n]
                edges = quantile_bin_edges(X_edges, mb)
                out[mb] = bin_features(jnp.asarray(X), jnp.asarray(edges))
        return out


_BIN_CACHE_LOCK = threading.Lock()


_DEPTH_BUCKETS = (4, 6, 8, 10, 12, 14)


def _depth_bucket(depth: int) -> int:
    """Quantize a max_depth to a padding bucket. Two jobs (VERDICT r3 #2):
    grids in DIFFERENT buckets compile separately, so a depth-3 config no
    longer pays the 2^12-node histogram cost of sharing a depth-12
    program (level cost doubles per level — sharing one padded program
    across {3,6,12} made the shallow 2/3 of the reference RF grid ~50×
    more expensive than needed); and the padded shape depends only on the
    bucket, not on which exact depths co-occur in a grid, so compiled
    shapes stay stable across grid edits for the persistent cache."""
    for b in _DEPTH_BUCKETS:
        if depth <= b:
            return b
    return _DEPTH_BUCKETS[-1]


def _pad_depth_of(est, grids, idxs) -> int:
    return _depth_bucket(
        max(int(_grid_param(est, grids[i], "max_depth")) for i in idxs))


def _sweep_forest(est, grids, X, y, W, V, metric_fn, ctx, sharding,
                  regression: bool):
    xb_by_bins = _binned_cache(est, grids, X, ctx)
    if regression:
        Y = jnp.asarray(y)[:, None]
        n_out = 1
    else:
        k = est.n_classes or infer_n_classes(np.asarray(y))
        Y = jax.nn.one_hot(jnp.asarray(y).astype(jnp.int32), k)
        n_out = k
    seed = ctx.seed if ctx is not None else 0
    pred_fn = forest_regression_pred if regression else forest_classification_pred
    # single deterministic tree for DT estimators (no Poisson bootstrap), so
    # sweep metrics describe exactly what the refit fit_arrays produces
    bootstrap = not isinstance(
        est, (OpDecisionTreeClassifier, OpDecisionTreeRegressor))

    n_folds = int(np.asarray(W).shape[0]) if hasattr(W, "shape") else len(W)
    n_rows = int(np.asarray(y).shape[0])

    def width_of(st, idxs):
        n_trees, max_bins, _ = st[:3]
        pad_depth = _pad_depth_of(est, grids, idxs)
        # real dispatch width never exceeds the pair count — keep the
        # fit_forest chunk budget in step with actual live instances
        return min(len(idxs) * n_folds,
                   _tree_pair_width(n_rows, int(X.shape[1]), max_bins,
                                    n_trees, _sec_per_unit("forest"),
                                    pad_depth))

    def calibrate(st, idxs, seconds, width, remaining, clean):
        n_trees, max_bins, _ = st[:3]
        pad_depth = _pad_depth_of(est, grids, idxs)
        units = (float(width) * n_trees * n_rows
                 * (2 ** min(pad_depth, 14)) * int(X.shape[1]) * max_bins)
        # an overlapped wall-clock includes another family's queue time —
        # never let it reach the persisted calibration or GROW compiled
        # dispatch shapes (r4 advisor, medium)...
        spu = (_record_calib("forest", seconds, units) if clean
               else _sec_per_unit("forest"))
        # ...but the serving-kill halving fires regardless: overlap only
        # ever OVERSTATES device time, so halving on a contaminated >45s
        # reading is conservatively safe, while skipping it could let the
        # next dispatch cross the ~60s exec kill
        if seconds > 0.75 * 60.0:  # dangerously near the serving kill
            return max(1, width // 2)
        if not clean:
            return width
        ideal = _tree_pair_width(n_rows, int(X.shape[1]), max_bins,
                                 n_trees, spu, pad_depth)
        # a resize recompiles (remote AOT ~15-50s): grow only when the
        # dispatch badly underfills the exec target AND enough pairs
        # remain to amortize the new program
        if (ideal >= 2 * width and remaining >= 2 * width
                and seconds < 0.3 * _PAIR_EXEC_TARGET_S):
            return min(ideal, remaining)
        return width

    def build(st, idxs):
        n_trees, max_bins, subsample = st[:3]
        Xb = xb_by_bins[max_bins]
        pad_depth = _pad_depth_of(est, grids, idxs)
        # unsharded → host dispatch of `width` vmapped pairs at a time;
        # sharded → the whole grid×fold block is vmapped. Either way the
        # tree-chunking inside fit_forest budgets for every simultaneous
        # instance.
        divisor = (width_of(st, idxs) if sharding is None
                   else max(1, len(idxs) * n_folds))

        def fit_predict(d, w):
            trees = fit_forest(Xb, Y, w, n_trees, pad_depth, max_bins,
                               n_out, seed, subsample, d["mcw"],
                               active_depth=d["depth"], bootstrap=bootstrap,
                               tree_budget_divisor=divisor,
                               min_gain=d["min_gain"])
            # small predict chunk: the dispatch vmaps `divisor` pairs, so
            # the per-chunk (c, n, m->128) slab multiplies by the width
            return pred_fn(trees, Xb, chunk=8)
        return fit_predict

    def dyn_of(g):
        mcw = max(float(_grid_param(est, g, "min_child_weight") or 1.0),
                  float(_grid_param(est, g, "min_instances_per_node") or 1.0))
        return {"depth": int(_grid_param(est, g, "max_depth")),
                "mcw": mcw,
                "min_gain": float(_grid_param(est, g, "min_info_gain") or 0.0)}

    # one PADDED compile per (family group, depth bucket): traced
    # active_depth masks unused levels within a bucket, while the bucket
    # split keeps shallow configs off the deep configs' 2^depth node cost
    # (the persistent compile cache absorbs the extra program per bucket)
    return _sweep_blocks(
        grids, y, W, V, metric_fn, sharding,
        static_of=lambda g: _static_forest(est, g),
        dyn_of=dyn_of,
        build=build,
        grid_vmap=lambda st, idxs: _pad_depth_of(est, grids, idxs) <= 6,
        host_dispatch=True,
        pair_width=lambda st, idxs, k: width_of(st, idxs),
        calibrate=calibrate, family="forest",
        x_info=_x_info(X))


def _sweep_gbt(est, grids, X, y, W, V, metric_fn, ctx, sharding):
    from transmogrifai_tpu.models.trees import (
        _pick_rounds_per_dispatch, fit_gbt_chunk)
    xb_by_bins = _binned_cache(est, grids, X, ctx)
    objective = est._objective
    n_classes = 2
    if objective == "logistic":
        n_classes = getattr(est, "n_classes", None) or \
            infer_n_classes(np.asarray(y))
    seed = ctx.seed if ctx is not None else 0
    multiclass = objective == "logistic" and n_classes > 2

    def lr_of(grid) -> float:
        v = grid.get("eta", grid.get("learning_rate"))
        if v is None:
            v = est.params.get("eta", getattr(est, "learning_rate", 0.1))
        return float(v)

    n_rows = int(np.asarray(y).shape[0])
    d_feat = int(X.shape[1])
    n_folds = int(np.asarray(W).shape[0]) if hasattr(W, "shape") else len(W)

    eval_metric = str(getattr(est, "eval_metric", "logloss") or "logloss")

    def static_of(g):
        return _static_gbt(est, g)

    def dyn_of(g):
        mcw = max(float(_grid_param(est, g, "min_child_weight") or 1.0),
                  float(_grid_param(est, g, "min_instances_per_node") or 1.0))
        return {
            "depth": int(_grid_param(est, g, "max_depth")),
            "lr": lr_of(g),
            "lam": float(_grid_param(est, g, "reg_lambda")),
            "mcw": mcw,
            "gamma": float(_grid_param(est, g, "gamma") or 0.0),
            "alpha": float(_grid_param(est, g, "alpha") or 0.0),
            "subsample": float(_grid_param(est, g, "subsample") or 1.0),
            "colsample": float(
                _grid_param(est, g, "colsample_bytree") or 1.0),
            "min_gain_norm": float(
                _grid_param(est, g, "min_info_gain") or 0.0)}

    if sharding is not None or multiclass:
        # mesh-sharded grids (dryrun/pod shapes) and multiclass keep the
        # single-program path: the whole fit (with in-scan early-stop
        # masking for binary/squared — same key stream and state
        # transitions as the chunked loop, so metrics agree) vmaps over
        # the grid axis
        def build(st, idxs):
            n_estimators, max_bins, esr = st[:3]
            Xb = xb_by_bins[max_bins]
            pad_depth = _pad_depth_of(est, grids, idxs)

            def fit_predict(d, w, v):
                common = dict(min_child_weight=d["mcw"],
                              active_depth=d["depth"],
                              gamma=d["gamma"], alpha=d["alpha"],
                              subsample=d["subsample"],
                              colsample=d["colsample"], seed=seed)
                if multiclass:
                    _, margin = fit_gbt_multiclass(
                        Xb, y, w, n_estimators, pad_depth, max_bins,
                        n_classes, d["lr"], d["lam"],
                        min_gain_norm=d["min_gain_norm"], **common)
                    return gbt_multiclass_pred_from_margin(margin)
                # the scan carry is the final training-matrix margin — no
                # post-fit forest re-walk needed
                _, margin = fit_gbt(Xb, y, w, n_estimators, pad_depth,
                                    max_bins, d["lr"], d["lam"], objective,
                                    val_w=v, early_stopping_rounds=esr,
                                    min_gain_norm=d["min_gain_norm"],
                                    eval_metric=eval_metric, **common)
                return gbt_pred_from_margin(margin, objective)
            return fit_predict

        def width_of(st, idxs):
            n_estimators, max_bins = st[0], st[1]
            pad_depth = _pad_depth_of(est, grids, idxs)
            return min(len(idxs) * n_folds,
                       _tree_pair_width(n_rows, d_feat, max_bins,
                                        n_estimators, _sec_per_unit("gbt"),
                                        pad_depth))

        return _sweep_blocks(
            grids, y, W, V, metric_fn, sharding,
            static_of=static_of, dyn_of=dyn_of, build=build,
            grid_vmap=lambda st, idxs: _pad_depth_of(est, grids, idxs) <= 6,
            host_dispatch=sharding is None,
            pair_width=lambda st, idxs, k: width_of(st, idxs),
            fit_takes_val=True, family="gbt",
            x_info=_x_info(X))

    # ---- single-device binary/squared: ROUND-CHUNKED host dispatch ---- #
    # A 200-round depth-10 fit at 100k rows is a >60s single execution
    # (the serving infrastructure kills it); instead each dispatch runs
    # `rpd` boosting rounds for `width` vmapped grid×fold pairs, carrying
    # (margin, best_val, since) across dispatches, and once EVERY pair in
    # the chunk reports since >= early_stopping_rounds the remaining
    # rounds are skipped outright — the host-loop analogue of the
    # reference's numEarlyStoppingRounds (DefaultSelectorParams.scala:74).
    import time as _time
    metrics: List[Optional[List[float]]] = [None] * len(grids)
    _journal_prefill(grids, metrics)  # resume: skip completed blocks
    groups: Dict[Tuple, List[int]] = {}
    for i, g in enumerate(grids):
        if metrics[i] is None:
            groups.setdefault(static_of(g), []).append(i)
    host = isinstance(metric_fn, HostMetricFallback)
    y_np = np.asarray(y) if host else None
    V_np = np.asarray(V) if host else None

    def _run_gbt_group(static, idxs):
        n_est, max_bins, esr = static[:3]
        Xb = xb_by_bins[max_bins]
        pad_depth = _pad_depth_of(est, grids, idxs)
        dyn_dicts = [dyn_of(grids[i]) for i in idxs]
        dyn = {k: jnp.asarray([dd[k] for dd in dyn_dicts],
                              jnp.int32 if isinstance(dyn_dicts[0][k], int)
                              else jnp.float32)
               for k in dyn_dicts[0]}
        n_pairs = len(idxs) * n_folds
        nodes = 2 ** min(pad_depth, 14)
        upr = float(n_rows) * nodes * d_feat * max_bins  # units/round/pair
        mem_per_pair = n_rows * (d_feat * max_bins + nodes) * 2
        w_mem = max(1, int(_PAIR_MEM_BYTES // mem_per_pair))

        def chunk_pair(d, w, v, margin, best, since, ks):
            (m, b, s), _ = fit_gbt_chunk(
                Xb, y, w, v, margin, best, since, ks, int(ks.shape[0]),
                pad_depth, max_bins, d["lr"], d["lam"], objective,
                d["mcw"], d["depth"], d["gamma"], d["alpha"],
                d["subsample"], d["colsample"], esr, d["min_gain_norm"],
                eval_metric)
            return m, b, s

        from transmogrifai_tpu.analysis.retrace import instrumented_jit
        prog = instrumented_jit(
            jax.vmap(chunk_pair, in_axes=(0, 0, 0, 0, 0, 0, None)),
            label=f"sweep:gbt:{static!r}:rounds")
        if host:
            pred_prog = instrumented_jit(
                jax.vmap(lambda m: gbt_pred_from_margin(m, objective)),
                label=f"sweep:gbt:{static!r}:pred")
        else:
            metric_prog = instrumented_jit(
                jax.vmap(lambda m, v: metric_fn(
                    y, gbt_pred_from_margin(m, objective), v)),
                label=f"sweep:gbt:{static!r}:metric")
        keys_all = jax.random.split(jax.random.PRNGKey(seed), n_est)

        s = 0
        while s < n_pairs:
            spu = _sec_per_unit("gbt")
            # power-of-2 width + divisor-quantized rounds: calibration
            # drift between runs must not change compiled dispatch shapes.
            # NOT clamped to the remaining pair count — the pair-index
            # padding (`ps` repeats the last pair) keeps the tail chunk at
            # the same compiled shape instead of forcing a second compile
            width = _pow2_floor(max(1, min(
                n_pairs, w_mem, int(_PAIR_EXEC_TARGET_S
                                    / max(n_est * upr * spu, 1e-9)))))
            rpd = _pick_rounds_per_dispatch(
                n_est, _pow2_floor(max(1, int(
                    _PAIR_EXEC_TARGET_S / max(width * upr * spu, 1e-9)))))
            ps = [min(s + t, n_pairs - 1) for t in range(width)]
            gs = [p // n_folds for p in ps]
            fs = [p % n_folds for p in ps]
            dchunk = {k: v_[jnp.asarray(gs)] for k, v_ in dyn.items()}
            Wsel = W[jnp.asarray(fs)]
            Vsel = V[jnp.asarray(fs)]
            margin = jnp.zeros((width, n_rows), jnp.float32)
            best = jnp.full((width,), jnp.inf, jnp.float32)
            since = jnp.zeros((width,), jnp.int32)
            done = 0
            while done < n_est:
                ks = keys_all[done:done + rpd]
                with _DispatchSpan() as span:
                    t0 = _time.perf_counter()
                    margin, best, since = jax.block_until_ready(
                        prog(dchunk, Wsel, Vsel, margin, best, since, ks))
                    dt = _time.perf_counter() - t0
                SWEEP_STATS.record(
                    (id(prog), static, width, int(ks.shape[0])), dt,
                    clean=span.clean)
                done += int(ks.shape[0])
                if span.clean:  # overlapped wall-clock never enters calib
                    _record_calib(
                        "gbt", dt, float(width) * int(ks.shape[0]) * upr)
                if (esr > 0 and done < n_est
                        and bool(np.all(np.asarray(since) >= esr))):
                    log.info("gbt sweep: early stop after %d/%d rounds "
                             "(%d pairs)", done, n_est, width)
                    break
                # NOT gated on span.clean: overlap only ever OVERSTATES
                # device time, so halving on a contaminated >45s reading
                # is conservatively safe — while skipping it could let
                # the next dispatch cross the ~60s serving exec kill
                if done < n_est and dt > 0.75 * 60.0 and rpd > 1:
                    # measured too close to the serving kill: halve (the
                    # shorter chunk compiles once, then persists in cache)
                    new_rpd = _pick_rounds_per_dispatch(n_est, rpd // 2)
                    log.info("gbt sweep: rounds/dispatch recalibrated "
                             "%d -> %d (measured %.1fs)", rpd, new_rpd, dt)
                    rpd = new_rpd
            if host:
                pred_np = jax.tree_util.tree_map(np.asarray,
                                                 pred_prog(margin))
                row_metrics = [
                    _metric(metric_fn.evaluator, y_np,
                            jax.tree_util.tree_map(
                                lambda a, t=t: a[t], pred_np),
                            V_np[fs[t]])
                    for t in range(width)]
            else:
                row_metrics = [float(m) for m in
                               np.asarray(metric_prog(margin, Vsel))]
            for t in range(min(width, n_pairs - s)):
                row_i, j = divmod(s + t, n_folds)
                if metrics[idxs[row_i]] is None:
                    metrics[idxs[row_i]] = [None] * n_folds  # type: ignore
                metrics[idxs[row_i]][j] = row_metrics[t]  # type: ignore
            s += width

    _run_groups_resilient(
        groups, _run_gbt_group,
        commit=lambda idxs, block_s=None, facts=None: _journal_commit(
            grids, metrics, idxs, block_s, facts),
        family="gbt",
        facts=_block_facts_fn("gbt", y, W, _x_info(X)),
        block_key=_block_key_fn(grids))
    return metrics  # type: ignore[return-value]


# --------------------------------------------------------------------------- #
# dispatch                                                                    #
# --------------------------------------------------------------------------- #

def _dispatch(est) -> Optional[Callable]:
    # order matters: subclasses before parents
    if isinstance(est, (OpXGBoostClassifier, OpXGBoostRegressor,
                        OpGBTClassifier, OpGBTRegressor)):
        return _sweep_gbt
    if isinstance(est, (OpRandomForestRegressor, OpDecisionTreeRegressor)):
        return lambda *a: _sweep_forest(*a, regression=True)
    if isinstance(est, (OpRandomForestClassifier, OpDecisionTreeClassifier)):
        return lambda *a: _sweep_forest(*a, regression=False)
    if isinstance(est, OpLogisticRegression):
        return _sweep_logistic
    if isinstance(est, OpLinearRegression):
        return _sweep_linreg
    if isinstance(est, OpLinearSVC):
        return _sweep_svc
    if isinstance(est, OpGeneralizedLinearRegression):
        return _sweep_glm
    if isinstance(est, OpNaiveBayes):
        return _sweep_nb
    if isinstance(est, OpMultilayerPerceptronClassifier):
        return _sweep_mlp
    return None


def run_sweep(est, grids: List[Dict], X, y, folds, evaluator, ctx,
              sharding=None, journal=None) -> List[List[float]]:
    """Metric matrix [grid][fold] for one model family.

    `journal`: optional `runtime.journal.SweepJournal` — completed grid
    blocks append as soon as their fold metrics are final, and already-
    journaled configs are skipped, so a killed sweep resumed with the
    same journal re-runs only un-journaled blocks and reproduces the
    bit-identical metric matrix (journal floats round-trip exactly)."""
    _SWEEP_TL.journal = journal
    best = None
    if journal is not None:
        best = _BestTracker(getattr(evaluator, "is_larger_better", True))
        # seed from EVERY journaled row (not just this call's grids): a
        # post-resume record's `best` annotation must account for pre-
        # kill blocks, including — on the distributed scheduler path,
        # where each worker's run_sweep sees only its own block — the
        # grids other workers completed
        for g, row in journal.rows():
            best.note(g, row)
    _SWEEP_TL.best = best
    try:
        return _run_sweep(est, grids, X, y, folds, evaluator, ctx, sharding)
    finally:
        _SWEEP_TL.journal = None
        _SWEEP_TL.best = None


def _run_sweep(est, grids: List[Dict], X, y, folds, evaluator, ctx,
               sharding=None) -> List[List[float]]:
    handler = _dispatch(est)
    if handler is None:
        return _sweep_generic(est, grids, X, y, folds, evaluator, ctx)
    try:
        n_classes = getattr(est, "n_classes", None) or \
            infer_n_classes(np.asarray(y))
    except Exception:
        n_classes = None
    # no device kernel for this evaluator → batched fits, host metrics
    metric_fn = (make_device_metric(evaluator, n_classes=n_classes)
                 or HostMetricFallback(evaluator))
    # the cache entry RETAINS the keying objects so `is` comparisons are
    # safe (an id()-only key could false-hit after GC address reuse): a
    # FitContext reused with different X/y/folds (public run_sweep callers)
    # must not silently get the first call's arrays back
    def _same_data(key_objs) -> bool:
        kX, ky, kfolds = key_objs
        return (kX is X and ky is y and len(kfolds) == len(folds)
                and all(a is c and b is d
                        for (a, b), (c, d) in zip(kfolds, folds)))

    cached = getattr(ctx, "_sweep_data_cache", None) if ctx is not None else None
    if cached is not None and _same_data(cached[0]):
        _, X, y, W, V = cached  # same selector fit: reuse padded/sharded set
    else:
        key_objs = (X, y, list(folds))
        W = jnp.asarray(np.stack([tr for tr, _ in folds]))
        V = jnp.asarray(np.stack([va for _, va in folds]))
        if ctx is not None and ctx.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from transmogrifai_tpu.parallel.mesh import DATA_AXIS
            data_size = ctx.mesh.shape.get(DATA_AXIS, 1)
            n = int(np.asarray(y).shape[0])
            if data_size > 1:
                # every fit/metric is weight-masked, so rows pad with zero
                # weight in ALL folds — sharding never silently degrades to
                # replication on uneven row counts. Tree binning must ignore
                # the pad rows (see _binned_cache); bootstrap streams are
                # prefix-stable across the padded shape.
                ctx._sweep_n_rows = n
                pad = (-n) % data_size
                if pad:
                    X = jnp.concatenate(
                        [X, jnp.zeros((pad, X.shape[1]), X.dtype)])
                    y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
                    W = jnp.concatenate(
                        [W, jnp.zeros((W.shape[0], pad), W.dtype)], axis=1)
                    V = jnp.concatenate(
                        [V, jnp.zeros((V.shape[0], pad), V.dtype)], axis=1)
                mesh = ctx.mesh
                X = jax.device_put(X, NamedSharding(mesh, P(DATA_AXIS, None)))
                y = jax.device_put(y, NamedSharding(mesh, P(DATA_AXIS)))
                W = jax.device_put(W, NamedSharding(mesh, P(None, DATA_AXIS)))
                V = jax.device_put(V, NamedSharding(mesh, P(None, DATA_AXIS)))
        if ctx is not None:
            ctx._sweep_data_cache = (key_objs, X, y, W, V)
            ctx._sweep_bin_cache = {}  # binned-X cache is per-data too
    return handler(est, grids, X, y, W, V, metric_fn, ctx, sharding)
