"""The sweep engine: folds × grids as one batched XLA program.

Reference parity: `OpValidator.getSummary` / `OpCrossValidation.validate`
(`core/.../tuning/OpValidator.scala:299-358`, `OpCrossValidation.scala:87-147`)
— the reference dispatches each model×grid×fold fit as a Future running
Spark jobs; here the same sweep is `vmap(vmap(fit))` over stacked fold
masks and a dynamic hyperparameter vector, jitted once per static-parameter
group. On a mesh, sharding the grid axis with `sweep_sharding` spreads the
whole sweep across chips (SURVEY.md §3.3 north star); fold masks make every
fit shape-identical so XLA batches them without recompilation.

Fault tolerance mirrors `OpValidator.scala:324-353`: a failing model family
is dropped with a warning; only all-families-failing raises.
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.models.base import infer_n_classes
from transmogrifai_tpu.models.linear import OpLinearRegression, fit_linreg, predict_linreg
from transmogrifai_tpu.models.logistic import (
    OpLogisticRegression, fit_logreg, predict_logreg)

log = logging.getLogger(__name__)


def _metric(evaluator, y: np.ndarray, pred: Dict[str, np.ndarray],
            val_mask: np.ndarray) -> float:
    idx = val_mask > 0.5
    label = Column(T.RealNN, {
        "value": y[idx], "mask": np.ones(int(idx.sum()), dtype=bool)})
    pcol = Column(T.Prediction, {k: np.asarray(v)[idx] for k, v in pred.items()})
    return evaluator.metric_value(label, pcol)


def _eval_grid_fold(evaluator, y, preds_gk, val_masks) -> List[List[float]]:
    """preds_gk: dict of arrays with leading (g, k) axes → metric[g][k]."""
    g = np.asarray(preds_gk["prediction"]).shape[0]
    k = np.asarray(preds_gk["prediction"]).shape[1]
    out = []
    for gi in range(g):
        row = []
        for ki in range(k):
            pred = {key: np.asarray(v)[gi, ki] for key, v in preds_gk.items()}
            row.append(_metric(evaluator, y, pred, val_masks[ki]))
        out.append(row)
    return out


# --------------------------------------------------------------------------- #
# vmapped family sweeps                                                       #
# --------------------------------------------------------------------------- #

def _sweep_logistic(est: OpLogisticRegression, grids: List[Dict], X, y,
                    folds, evaluator, sharding=None) -> List[List[float]]:
    y_np = np.asarray(y)
    n_classes = est.n_classes or infer_n_classes(y_np)
    W_train = jnp.asarray(np.stack([tr for tr, _ in folds]))
    val_masks = [va for _, va in folds]

    # group grids sharing static params (max_iter) → one compile per group
    metrics: List[Optional[List[float]]] = [None] * len(grids)
    by_static: Dict[int, List[int]] = {}
    for i, grid in enumerate(grids):
        mi = int(grid.get("max_iter", est.max_iter))
        by_static.setdefault(mi, []).append(i)

    for max_iter, idxs in by_static.items():
        l2s = jnp.asarray(
            [float(grids[i].get("reg_param", est.reg_param)) for i in idxs],
            dtype=jnp.float32)
        if sharding is not None:
            l2s = jax.device_put(l2s, sharding)

        fit_one = lambda l2, w: fit_logreg(  # noqa: E731
            X, y, w, l2, n_classes, max_iter)
        fit_gk = jax.jit(jax.vmap(jax.vmap(fit_one, in_axes=(None, 0)),
                                  in_axes=(0, None)))
        params = fit_gk(l2s, W_train)  # pytree with leading (g, k)
        preds = jax.jit(jax.vmap(jax.vmap(
            lambda p: predict_logreg(p, X))))(params)
        grid_fold = _eval_grid_fold(evaluator, y_np, preds, val_masks)
        for row, i in zip(grid_fold, idxs):
            metrics[i] = row
    return metrics  # type: ignore[return-value]


def _sweep_linear(est: OpLinearRegression, grids: List[Dict], X, y,
                  folds, evaluator, sharding=None) -> List[List[float]]:
    y_np = np.asarray(y)
    W_train = jnp.asarray(np.stack([tr for tr, _ in folds]))
    val_masks = [va for _, va in folds]
    l2s = jnp.asarray(
        [float(g.get("reg_param", est.reg_param)) for g in grids],
        dtype=jnp.float32)
    if sharding is not None:
        l2s = jax.device_put(l2s, sharding)
    fit_gk = jax.jit(jax.vmap(jax.vmap(
        lambda l2, w: fit_linreg(X, y, w, l2), in_axes=(None, 0)),
        in_axes=(0, None)))
    params = fit_gk(l2s, W_train)
    preds = jax.jit(jax.vmap(jax.vmap(
        lambda p: predict_linreg(p, X))))(params)
    return _eval_grid_fold(evaluator, y_np, preds, val_masks)


def _sweep_generic(est, grids: List[Dict], X, y, folds, evaluator,
                   ctx) -> List[List[float]]:
    """Fallback: python loop over grids × folds (tree models etc.)."""
    from transmogrifai_tpu.models.trees import _TreeEstimatorBase
    out = []
    y_np = np.asarray(y)
    bin_cache: Dict = {}  # shared across the family: bin X once per max_bins
    for grid in grids:
        clone = type(est)(**{**{k: v for k, v in est.params.items()
                                if k != "uid"}, **grid})
        if isinstance(clone, _TreeEstimatorBase):
            clone._bin_cache = bin_cache
        row = []
        for tr, va in folds:
            model = clone.fit_arrays(X, y, jnp.asarray(tr), ctx)
            pred = model.predict_arrays(X)
            row.append(_metric(evaluator, y_np,
                               {k: np.asarray(v) for k, v in pred.items()}, va))
        out.append(row)
    return out


def run_sweep(est, grids: List[Dict], X, y, folds, evaluator, ctx,
              sharding=None) -> List[List[float]]:
    """Metric matrix [grid][fold] for one model family."""
    if isinstance(est, OpLogisticRegression):
        return _sweep_logistic(est, grids, X, y, folds, evaluator, sharding)
    if isinstance(est, OpLinearRegression):
        return _sweep_linear(est, grids, X, y, folds, evaluator, sharding)
    return _sweep_generic(est, grids, X, y, folds, evaluator, ctx)
