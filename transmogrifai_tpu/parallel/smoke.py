"""multichip-smoke: the distributed sweep scheduler on a forced host mesh.

The CI gate for `parallel/scheduler.py` (`make multichip-smoke`) — and
the measured half of bench's multichip story (`python bench.py
multichip` calls `run_measured` with bigger shapes). On 8 XLA
host-platform virtual devices (the reference's `local[2]` trick):

1. **exact-winner parity**: a 2-family grid sweep scheduled across an
   8-wide sweep mesh must reproduce the single-device sweep's metric
   matrix bit for bit (JSON-roundtrip exact) — per-worker blocks run
   the exact single-device programs, so distribution must not move a
   single ulp;
2. **kill-one-worker resume parity**: an `InjectedKill` at the LAST
   block claim (``scheduler.worker_block``) preempts the schedule; the
   surviving lanes drain + journal their in-flight blocks, so resuming
   re-runs ONLY the killed worker's in-flight block — asserted from
   the per-worker journal shard record counts;
3. **work stealing**: an injected worker-level *error* retires one
   lane mid-schedule; the survivors steal its block and the sweep
   completes with the same exact metrics (no resume needed);
4. **measurement**: single-device vs mesh wall clock + the goodput
   mesh-utilization rollup — the measured counterpart of the bench's
   divide-by-N pod extrapolation.

Run: ``python -m transmogrifai_tpu.parallel.smoke`` (fresh process: the
module forces the 8-device host platform before JAX initializes).
"""

from __future__ import annotations

import glob
import json
import os
import tempfile
import time
from typing import Any, Dict


def ensure_host_devices(n: int = 8) -> None:
    """Force `n` virtual CPU devices — must run before backend init."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def _cols(n: int, seed: int = 3):
    import numpy as np

    import transmogrifai_tpu.types as T
    from transmogrifai_tpu.data.columns import Column
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] + 0.6 * X[:, 1] + rng.normal(0, 0.5, n) > 0) \
        .astype(np.float64)
    return (Column(T.RealNN, {"value": y, "mask": np.ones(n, bool)}),
            Column(T.OPVector, X))


def _selector(ckpt=None, max_iters=(8, 4)):
    """Two families, every static group exactly 2 configs: LR grids over
    two max_iter groups + one SVC group = 3 scheduler blocks of 2, so
    the kill-one-block arithmetic below is exact."""
    from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
    from transmogrifai_tpu.models import OpLinearSVC, OpLogisticRegression
    from transmogrifai_tpu.selector import ModelSelector
    from transmogrifai_tpu.selector.validators import OpCrossValidation
    lr = [{"reg_param": r, "max_iter": it}
          for it in max_iters for r in (0.01, 0.1)]
    svc = [{"reg_param": r} for r in (0.01, 0.1)]
    return ModelSelector(
        models=[(OpLogisticRegression(), lr), (OpLinearSVC(max_iter=8), svc)],
        validator=OpCrossValidation(n_folds=2, seed=11),
        evaluator=BinaryClassificationEvaluator(),
        checkpoint_dir=ckpt)


def _fit(selector, cols, n, mesh=None):
    from transmogrifai_tpu.stages.base import FitContext
    return selector.fit_model(cols, FitContext(n_rows=n, seed=7, mesh=mesh))


def _rows(model) -> Dict[str, Any]:
    s = model.summary
    return {"best_grid": s.best_grid, "best_model": s.best_model,
            "rows": {f"{r.model}:{json.dumps(r.grid, sort_keys=True)}":
                     r.fold_metrics for r in s.validation_results}}


def _shard_records(ckpt_dir: str) -> int:
    n = 0
    for p in glob.glob(os.path.join(ckpt_dir, "*.journal-w*.jsonl")):
        with open(p) as fh:
            n += max(0, sum(1 for _ in fh) - 1)  # minus header
    return n


def run_measured(n_devices: int = 8, n_rows: int = 240,
                 max_iters=(8, 4)) -> Dict[str, Any]:
    """Single-device vs mesh-scheduled sweep: exact parity + measured
    speedup + the goodput mesh rollup. Shared by the smoke gate and
    `bench.py multichip` (which passes more/larger grid blocks so the
    packing measurement is not dominated by 3 tiny blocks)."""
    ensure_host_devices(n_devices)
    import jax

    from transmogrifai_tpu.obs import goodput as obs_goodput
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, have {len(jax.devices())}")
    mesh = make_mesh(n_devices, sweep=n_devices)
    cols = _cols(n_rows)

    def sel():
        return _selector(max_iters=max_iters)

    # warm both paths once (compiles must not contaminate the timing,
    # and the persistent compile cache makes warm the steady state) —
    # under a THROWAWAY trace, or the warm-up schedule's
    # mesh_utilization event lands in the caller's trace (bench.py's
    # root) and its goodput.mesh rollup reports warm-up packing instead
    # of the measured run's
    with TRACER.span("run:multichip-warmup", category="run",
                     new_trace=True):
        _fit(sel(), cols, n_rows)
        _fit(sel(), cols, n_rows, mesh=mesh)

    t0 = time.perf_counter()
    base = _rows(_fit(sel(), cols, n_rows))
    t_single = time.perf_counter() - t0

    with TRACER.span("run:multichip-bench", category="run",
                     new_trace=True) as root:
        t0 = time.perf_counter()
        sched = _rows(_fit(sel(), cols, n_rows, mesh=mesh))
        t_mesh = time.perf_counter() - t0
    report = obs_goodput.build_report(
        root, TRACER.trace_spans(root.trace_id))

    exact = (base["best_grid"] == sched["best_grid"]
             and set(base["rows"]) == set(sched["rows"])
             and all(json.dumps(base["rows"][k]) ==
                     json.dumps(sched["rows"][k]) for k in base["rows"]))
    assert exact, "mesh-scheduled sweep is not bit-identical to single-device"
    util = float(report.mesh.get("utilization_frac", 0.0))
    assert 0.0 < util <= 1.0, f"mesh utilization out of range: {report.mesh}"
    return {
        "n_devices": n_devices,
        "n_rows": n_rows,
        "winner_exact": exact,
        "sweep_single_measured_s": round(t_single, 3),
        f"sweep_mesh{n_devices}_measured_s": round(t_mesh, 3),
        "mesh_speedup": round(t_single / max(t_mesh, 1e-9), 3),
        "mesh_scaling_efficiency": round(
            t_single / max(t_mesh, 1e-9) / n_devices, 4),
        "mesh_utilization_frac": round(util, 4),
        "mesh": report.mesh,
    }


def _smoke_kill_resume(payload: Dict[str, Any], n_rows: int = 240) -> None:
    """Kill at the LAST block claim: the other blocks are already in
    flight and drain to their journals, so resume re-runs exactly one
    2-config block."""
    from transmogrifai_tpu.parallel.mesh import make_mesh
    from transmogrifai_tpu.runtime.faults import (
        SITE_WORKER_BLOCK, FaultPlan, FaultSpec, InjectedKill)

    mesh = make_mesh(8, sweep=8)
    cols = _cols(n_rows)
    clean = _rows(_fit(_selector(), cols, n_rows, mesh=mesh))
    n_blocks, cfg_per_block, total_cfgs = 3, 2, 6

    with tempfile.TemporaryDirectory(prefix="multichip-smoke-") as tmp:
        plan = FaultPlan(
            [FaultSpec(SITE_WORKER_BLOCK, at=n_blocks, kind="kill")])
        killed = False
        try:
            with plan.active():
                _fit(_selector(tmp), cols, n_rows, mesh=mesh)
        except InjectedKill:
            killed = True
        assert killed, "fault plan failed to preempt the schedule"
        journaled = _shard_records(tmp)
        assert journaled == total_cfgs - cfg_per_block, (
            f"drain should journal every block but the killed worker's "
            f"in-flight one: {journaled}/{total_cfgs} configs journaled")

        resumed = _rows(_fit(_selector(tmp), cols, n_rows, mesh=mesh))
        rerun = _shard_records(tmp) - journaled
        assert rerun == cfg_per_block, (
            f"resume re-ran {rerun} configs, expected exactly the "
            f"{cfg_per_block}-config in-flight block")
        assert resumed["best_grid"] == clean["best_grid"]
        assert all(json.dumps(resumed["rows"][k]) ==
                   json.dumps(clean["rows"][k]) for k in clean["rows"]), \
            "resumed metrics are not bit-identical"
        payload.update(kill_resume="ok",
                       blocks_journaled_at_kill=journaled // cfg_per_block,
                       blocks_rerun_on_resume=rerun // cfg_per_block)


def _smoke_steal(payload: Dict[str, Any], n_rows: int = 240) -> None:
    """A worker-level ERROR retires one lane; the survivors steal its
    in-flight block and the schedule completes exactly."""
    from transmogrifai_tpu.obs import goodput as obs_goodput
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.parallel.mesh import make_mesh
    from transmogrifai_tpu.runtime.faults import (
        SITE_WORKER_BLOCK, FaultPlan, FaultSpec)

    mesh = make_mesh(8, sweep=8)
    cols = _cols(n_rows)
    clean = _rows(_fit(_selector(), cols, n_rows, mesh=mesh))
    plan = FaultPlan([FaultSpec(SITE_WORKER_BLOCK, at=1, kind="error")])
    with TRACER.span("run:multichip-steal", category="run",
                     new_trace=True) as root:
        with plan.active():
            stolen = _rows(_fit(_selector(), cols, n_rows, mesh=mesh))
    report = obs_goodput.build_report(root, TRACER.trace_spans(root.trace_id))
    assert all(json.dumps(stolen["rows"][k]) ==
               json.dumps(clean["rows"][k]) for k in clean["rows"]), \
        "post-steal metrics are not bit-identical"
    assert report.counts.get("workers_retired", 0) == 1, report.counts
    assert report.mesh.get("requeues", 0) >= 1, report.mesh
    payload.update(steal_resilience="ok",
                   requeues=report.mesh.get("requeues"))


def _smoke() -> int:
    # fresh perf corpus: the kill/resume block arithmetic below assumes
    # count-LPT blocks (no model-driven splits) — a warm corpus from
    # earlier runs on this machine must not re-plan the schedule
    if "TRANSMOGRIFAI_PERF_CORPUS_DIR" not in os.environ:
        os.environ["TRANSMOGRIFAI_PERF_CORPUS_DIR"] = \
            tempfile.mkdtemp(prefix="perf-corpus-")
    payload: Dict[str, Any] = {}
    payload.update(run_measured())
    _smoke_kill_resume(payload)
    _smoke_steal(payload)
    print(json.dumps({"multichip_smoke": "ok", **payload}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_smoke())
