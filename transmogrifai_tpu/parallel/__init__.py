from transmogrifai_tpu.parallel.mesh import make_mesh, sweep_sharding, data_sharding
from transmogrifai_tpu.parallel.sweep import run_sweep

__all__ = ["make_mesh", "sweep_sharding", "data_sharding", "run_sweep"]
