from transmogrifai_tpu.parallel.mesh import (
    data_sharding, make_mesh, make_multislice_mesh, sweep_sharding)
from transmogrifai_tpu.parallel.scheduler import GridScheduler, SweepJob
from transmogrifai_tpu.parallel.sweep import run_sweep, static_signature

__all__ = ["data_sharding", "make_mesh", "make_multislice_mesh",
           "sweep_sharding", "run_sweep", "static_signature",
           "GridScheduler", "SweepJob"]
