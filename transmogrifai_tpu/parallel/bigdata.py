"""Out-of-core model fitting: device-resident compressed matrices fed by
row-chunk streaming from a `ColumnarStore`.

Reference parity: BASELINE target 4 (10M×500 CV sweep) — the workload the
reference runs as a Spark cluster job (`OpValidator.scala:299-358`
dispatching fits over executors). One TPU chip can't hold 10M×500 f32
(20 GB) plus working set, so this module:

- streams the memmapped store to the device ONCE per representation,
  through donated `dynamic_update_slice` writes into a persistent HBM
  buffer (no 2× copies);
- keeps TWO device representations, built per model family and freed
  after: bf16 (10 GB at 10M×500) for linear-family fits/scoring, and
  int8 quantile-binned (5 GB) for every tree family;
- grows trees with CHUNKED histogram matmuls: the (n, d·bins) bin
  one-hot — 320 GB at 10M×500×32, impossible to materialize — is built
  per row-chunk inside a `lax.scan` and contracted immediately, with the
  per-chunk A-side stacking ALL histogram values ([G·, H]) so each chunk
  is read once; gain/split selection reuses the in-core logic
  (`models/trees.py:split_from_histograms`).
- leaf sums use the same chunked matmul (TPU scatter-add serializes at
  10M rows);
- feeds every upload through the persistent content-addressed feature
  cache (`data/feature_cache.py`, ``cache=`` on the builders): repeat
  sweeps / resumed runs / serving warmups replay the wire tape from a
  verified artifact with ZERO store reads (bit-identical buffers), and
  cold misses can ship an int8/int4 quantized wire with dequant fused
  into the donated write (2–4× fewer bytes than f16).

Memory plan at 10M×500×32 bins (v5e 16 GB HBM):
    linear family : X bf16 10 GB + y/masks/logits ≈ 0.2 GB     → 10.2 GB
    tree families : Xb int8 5 GB + per-chunk one-hots ≈ 2.2 GB → 7.2 GB
    (families run sequentially; buffers freed between families)
"""

from __future__ import annotations

import logging
import os
import time
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from transmogrifai_tpu.data import feature_cache as fc
from transmogrifai_tpu.data.columnar_store import ColumnarStore
from transmogrifai_tpu.data.pipeline import IngestStats, run_chunk_pipeline
from transmogrifai_tpu.models.trees import split_from_histograms
from transmogrifai_tpu.obs.export import record_event

log = logging.getLogger(__name__)

UPLOAD_CHUNK_ROWS = 262_144   # ~256 MB f16 per upload dispatch at d=500
HIST_CHUNK_ROWS = 65_536      # bounds per-chunk one-hot to ~2 GB at d=500
UPLOAD_WORKERS = 2            # memmap read + cast threads (GIL-releasing)
UPLOAD_DEPTH = 4              # donated writes in flight (amortizes RPC RTT)


def _pad_rows(n: int, chunk: int) -> int:
    return -(-n // chunk) * chunk


@partial(jax.jit, donate_argnums=(0,))
def _write_cast_rows(buf, chunk, r0):
    """Donated row write; widens/narrows the wire chunk to the buffer
    dtype ON DEVICE (fused into the update), so the host ships the
    narrowest representation."""
    return jax.lax.dynamic_update_slice(
        buf, chunk.astype(buf.dtype), (r0, 0))


@partial(jax.jit, donate_argnums=(0,))
def _bin_write_rows(buf, chunk_f16, edges, r0):
    from transmogrifai_tpu.models.trees import bin_features
    binned = bin_features(chunk_f16.astype(jnp.float32), edges) \
        .astype(jnp.int8)
    return jax.lax.dynamic_update_slice(buf, binned, (r0, 0))


@partial(jax.jit, donate_argnums=(0, 1))
def _dual_write_rows(buf16, bufb, chunk_f16, edges, r0):
    """ONE wire chunk → BOTH device representations: widen to the
    linear-family dtype and quantile-bin to int8, each fused into its
    donated row write. The store is read once and the bytes cross the
    host→device link once."""
    from transmogrifai_tpu.models.trees import bin_features
    binned = bin_features(chunk_f16.astype(jnp.float32), edges) \
        .astype(jnp.int8)
    return (jax.lax.dynamic_update_slice(
                buf16, chunk_f16.astype(buf16.dtype), (r0, 0)),
            jax.lax.dynamic_update_slice(bufb, binned, (r0, 0)))


@jax.jit
def _probe(buf):
    """Tiny array depending on `buf`: its readiness is the completion
    token for the write that produced `buf` — blocking on it instead of
    the (donated, multi-GB) buffer itself lets later writes stay in
    flight."""
    return buf[(0,) * buf.ndim]


# -- quantized (compressed) wire: dequant fused into the donated write ------ #

def _unpack_dequant(chunk, scale, lo, bits: int, d: int):
    """Wire uint8 → f32 features ON DEVICE: unpack int4 nibbles when
    packed (feature 2j low, 2j+1 high — mirrors
    `feature_cache._pack4`), then the per-feature affine dequant
    x = q·scale + lo. Runs inside the donated write, so the host ships
    1 (int8) or 0.5 (int4) bytes/elem instead of the 2-byte f16 wire."""
    if bits == 4:
        lo_nib = chunk & jnp.uint8(0x0F)
        hi_nib = (chunk >> 4).astype(jnp.uint8)
        chunk = jnp.stack([lo_nib, hi_nib], axis=-1) \
            .reshape(chunk.shape[0], -1)[:, :d]
    return chunk.astype(jnp.float32) * scale + lo


@partial(jax.jit, donate_argnums=(0,), static_argnames=("bits",))
def _dequant_write_rows(buf, chunk_q, scale, lo, r0, *, bits):
    x = _unpack_dequant(chunk_q, scale, lo, bits, buf.shape[1])
    return jax.lax.dynamic_update_slice(buf, x.astype(buf.dtype), (r0, 0))


@partial(jax.jit, donate_argnums=(0,), static_argnames=("bits",))
def _dequant_bin_write_rows(buf, chunk_q, scale, lo, edges, r0, *, bits):
    from transmogrifai_tpu.models.trees import bin_features
    x = _unpack_dequant(chunk_q, scale, lo, bits, buf.shape[1])
    binned = bin_features(x, edges).astype(jnp.int8)
    return jax.lax.dynamic_update_slice(buf, binned, (r0, 0))


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("bits",))
def _dequant_dual_write_rows(buf16, bufb, chunk_q, scale, lo, edges, r0, *,
                             bits):
    from transmogrifai_tpu.models.trees import bin_features
    x = _unpack_dequant(chunk_q, scale, lo, bits, buf16.shape[1])
    binned = bin_features(x, edges).astype(jnp.int8)
    return (jax.lax.dynamic_update_slice(
                buf16, x.astype(buf16.dtype), (r0, 0)),
            jax.lax.dynamic_update_slice(bufb, binned, (r0, 0)))


def _zeros(shape, dtype, sharding):
    if sharding is None:
        return jnp.zeros(shape, dtype)
    # allocate ON the mesh (out_shardings) — a host-side zeros +
    # device_put would ship shape-many zero bytes through the link
    return jax.jit(lambda: jnp.zeros(shape, dtype),
                   out_shardings=sharding)()


def _put(chunk_np, sharding):
    return (jnp.asarray(chunk_np) if sharding is None
            else jax.device_put(chunk_np, sharding))


def _resolve_upload_plan(store, chunk_rows: int, workers, depth,
                         stats, bytes_per_elem: float = 2.0
                         ) -> Tuple[int, int]:
    """Pick the upload pipeline shape. Explicit `workers`/`depth` win
    unchanged (bench env knobs, tests); a None axis is filled by the
    learned cost model's predicted read-vs-upload balance
    (`perf.choose_upload_plan`) when the ingest target is warm, else by
    the hand-tuned `UPLOAD_WORKERS`/`UPLOAD_DEPTH` defaults — a cold
    corpus reproduces today's plan exactly. The chosen plan's predicted
    wall lands in `stats.predicted_wall_s` so the pipeline can score
    the prediction against the measured wall."""
    if workers is not None and depth is not None:
        return workers, depth
    try:
        from transmogrifai_tpu import perf
        # bytes_per_elem comes from the RESOLVED wire (f16=2, int8=1,
        # int4=0.5): training rows carry measured wire bytes, so the
        # plan query must use the same scale or the model is read off
        # its training distribution
        bytes_wire = float(store.n_rows) * store.n_features * bytes_per_elem
        chunks = -(-store.n_rows // max(chunk_rows, 1))
        w, d, pred = perf.choose_upload_plan(
            bytes_wire, chunks, UPLOAD_WORKERS, UPLOAD_DEPTH,
            fixed_workers=workers, fixed_depth=depth)
        if pred is not None:
            stats.predicted_wall_s = pred.value
            stats.plan = "model"
        return w, d
    except Exception:
        log.debug("upload plan resolution failed; using defaults",
                  exc_info=True)
        return (workers if workers is not None else UPLOAD_WORKERS,
                depth if depth is not None else UPLOAD_DEPTH)


def _default_ingest_retry():
    """Bounded-retry policy for transient IO during bulk ingest
    (tf.data-style bounded retry instead of fail-fast: a single flaky
    NFS read must not burn a 600 s upload). `TRANSMOGRIFAI_INGEST_RETRIES`
    sets total attempts (1 disables retrying)."""
    from transmogrifai_tpu.runtime.retry import RetryPolicy
    attempts = int(os.environ.get("TRANSMOGRIFAI_INGEST_RETRIES", "3"))
    return RetryPolicy(max_attempts=max(1, attempts),
                       base_delay_s=0.1, max_delay_s=5.0)


def _pipelined_upload(items, chunk_rows: int, prepare, label: str,
                      bufs: dict, write, *, n_rows: int,
                      workers: int, depth: int,
                      deadline_s: Optional[float], sharding,
                      profile, retry=None, stats: IngestStats = None,
                      tee=None) -> IngestStats:
    """Shared scaffold for the upload builders: timed prepare, bounded
    pipeline, progress/summary logging, profile record. `write(bufs,
    chunk_dev, r0)` dispatches the donated write(s), rebinding `bufs`
    entries, and returns the completion token. `prepare` is the
    worker-side chunk producer (store sweep, quantizing sweep, or
    cache-artifact replay). `tee(chunk)` — the feature-cache artifact
    append — runs on the main thread in item order BEFORE the device
    dispatch, so a readwrite miss persists exactly the bytes it ships.
    Chunk reads retry transient IO under `retry` (default
    `_default_ingest_retry`); attempts land in the returned stats."""
    st = stats if stats is not None else IngestStats(label=label)
    st.label = label

    def upload(prep):
        r0, c = prep
        if tee is not None:
            tee(c)
        token = write(bufs, _put(c, sharding), r0)
        if r0 and (r0 // chunk_rows) % 8 == 0:
            log.info("%s: %d/%d rows", label, r0, n_rows)
        return token

    run_chunk_pipeline(items, prepare, upload, workers=workers,
                       depth=depth, deadline_s=deadline_s,
                       label=f"{label} upload", stats=st,
                       retry=retry if retry is not None
                       else _default_ingest_retry())
    log.info("%s: %d rows in %.1fs (%.2f GB/s, overlap %.2f, retries %d"
             "%s)", label, n_rows, st.wall_s, st.gbps, st.overlap_frac,
             st.retries, f", cache {st.cache}" if st.cache else "")
    if profile is not None:
        profile.record_ingest(f"{label}_upload", st)
    return st


def _chunk_prepare(store: ColumnarStore, chunk_rows: int, wire: np.dtype,
                   stats: IngestStats):
    """prepare(r0) for the upload pipelines: memmap read → wire-dtype
    cast → tail pad, timed into `stats` (runs on worker threads; numpy
    releases the GIL for the copy and the cast)."""
    d = store.n_features

    def prepare(r0: int):
        t0 = time.perf_counter()
        # copy=True: a memmap slice is a lazy VIEW — without the copy the
        # page faults (the actual disk read) would happen on the MAIN
        # thread inside the device transfer, silently re-serializing the
        # pipeline and zeroing read_s
        c = np.array(store.chunk(r0, r0 + chunk_rows), copy=True)
        stats.note_read(time.perf_counter() - t0, c.nbytes)
        t0 = time.perf_counter()
        if c.dtype != wire:
            c = c.astype(wire)
        if len(c) < chunk_rows:  # pad the tail chunk to the static shape
            c = np.concatenate(
                [c, np.zeros((chunk_rows - len(c), d), wire)])
        stats.note_cast(time.perf_counter() - t0, c.nbytes)
        return r0, c

    return prepare


def _quant_prepare(store: ColumnarStore, chunk_rows: int,
                   plan: "fc.QuantPlan", stats: IngestStats):
    """prepare(r0) for the compressed wire path: memmap read →
    per-feature affine quantize (+ int4 nibble pack) → tail pad with the
    quantized-zero row. Ships 2–4× fewer bytes than the f16 wire; the
    device side dequantizes inside the donated write."""
    def prepare(r0: int):
        t0 = time.perf_counter()
        c = np.array(store.chunk(r0, r0 + chunk_rows), copy=True)
        stats.note_read(time.perf_counter() - t0, c.nbytes)
        t0 = time.perf_counter()
        q = plan.quantize(c)
        if len(q) < chunk_rows:
            q = np.concatenate(
                [q, np.tile(plan.pad_row, (chunk_rows - len(q), 1))])
        stats.note_cast(time.perf_counter() - t0, q.nbytes)
        return r0, q

    return prepare


def _artifact_prepare(art: "fc.CacheArtifact", chunk_rows: int,
                      stats: IngestStats):
    """prepare(r0) for a cache HIT: replay the artifact's wire tape.
    The bytes are already wire-ready (cast/quantized/padded at cold
    build time), so there is no store read and no cast — artifact IO
    lands in `stats.cache_read_s`, and `stats.read_s`/`bytes_read` stay
    0 (the warm-path proof the tests assert)."""
    mm = art.wire

    def prepare(r0: int):
        t0 = time.perf_counter()
        c = np.array(mm[r0:r0 + chunk_rows], copy=True)
        stats.note_cache_read(time.perf_counter() - t0, c.nbytes)
        stats.note_cast(0.0, c.nbytes)  # wire-ready: nothing to cast
        return r0, c

    return prepare


class _CacheSession:
    """Per-build feature-cache orchestration shared by the three
    builders: resolves the `cache=` policy, computes the content
    address, consults the resident registry and the on-disk cache,
    picks warm-replay vs cold-sweep prepare, tees the wire stream into
    a staged artifact on a readwrite miss, and emits the hit/miss/
    corrupt events + counters the goodput report and serving /metrics
    read. Corrupt or torn artifacts are REJECTED (structured
    `FeatureCacheError`, counted) and fall back to a cold rebuild —
    never a crash, never stale data."""

    def __init__(self, kind: str, store: ColumnarStore, chunk_rows: int, *,
                 legacy_wire, target_name: str, edges=None, sharding=None,
                 cache=None):
        self.kind = kind
        self.store = store
        self.chunk_rows = int(chunk_rows)
        self.edges = edges
        self.sharding = sharding
        self.d = store.n_features
        self.n_pad = _pad_rows(store.n_rows, chunk_rows)
        self.params = fc.resolve_cache_params(cache)
        self.legacy_wire = np.dtype(legacy_wire)
        mode = self.params.wire if self.params is not None else "auto"
        if mode in ("int8", "int4"):
            self.wire_mode = mode
            self.bits: Optional[int] = 8 if mode == "int8" else 4
        else:
            if mode == "f16":
                # explicit f16 wire: force 2-byte chunks even when the
                # narrowest-dtype rule would keep a wider store dtype
                # (an f32 store rounds through f16 on the wire — the
                # same contract the binned/dual builders document)
                self.legacy_wire = np.dtype(np.float16)
            self.wire_mode = self.legacy_wire.name
            self.bits = None
        self.quant: Optional[fc.QuantPlan] = None
        self.cache_obj = None
        self.key = ""
        if self.params is not None:
            self.cache_obj = fc.FeatureCache(self.params)
            self.key = fc.cache_key(
                kind, store, target_dtype=target_name, wire=self.wire_mode,
                chunk_rows=self.chunk_rows, edges=edges, sharding=sharding,
                quant_sample=self.params.quant_sample,
                quant_seed=self.params.quant_seed)
        self.artifact: Optional[fc.CacheArtifact] = None
        self.writer: Optional[fc.ArtifactWriter] = None
        self._stats: Optional[IngestStats] = None

    # -- resident layer -------------------------------------------------- #

    def resident(self) -> Optional[Tuple[Tuple, IngestStats]]:
        """HBM-resident arrays for this exact key, when the policy opts
        in — a sweep resume or serving warm re-requesting the same build
        gets the live device buffers with zero IO."""
        if self.params is None or not self.params.resident or not self.key:
            return None
        entry = fc.resident_get(self.key)
        if entry is None:
            return None
        stats = IngestStats(label=f"{self.kind}_resident")
        stats.cache = "resident"
        stats.cache_key = self.key
        stats.wire = self.wire_mode
        saved = float(entry["extra"].get("cold_wall_s", 0.0))
        fc.count_hit(self.store.nbytes(), saved)
        record_event("cache_hit", key=self.key, build=self.kind,
                     resident=True, saved_s=round(saved, 6))
        return entry["arrays"], stats

    # -- build-time hooks ------------------------------------------------ #

    def _expected_wire_cols(self) -> int:
        return (self.d + 1) // 2 if self.bits == 4 else self.d

    def _check_meta(self, art: "fc.CacheArtifact") -> None:
        meta = art.meta
        expect = {"kind": self.kind, "n_pad": self.n_pad,
                  "n_features": self.d, "wire": self.wire_mode,
                  "wire_cols": self._expected_wire_cols(),
                  "chunk_rows": self.chunk_rows}
        for field_, want in expect.items():
            if meta.get(field_) != want:
                raise fc.FeatureCacheError(
                    art.path, f"meta {field_}={meta.get(field_)!r} does "
                              f"not match the requested build ({want!r})",
                    self.key)
        if self.bits is not None and art.quant is None:
            raise fc.FeatureCacheError(
                art.path, "quantized wire artifact lacks quant.npz",
                self.key)

    def _meta(self) -> dict:
        return {
            "kind": self.kind,
            "store_fingerprint": fc.store_fingerprint(self.store),
            "n_rows": int(self.store.n_rows),
            "n_pad": int(self.n_pad),
            "n_features": int(self.d),
            "store_dtype": self.store.dtype.name,
            "wire": self.wire_mode,
            "wire_dtype": ("uint8" if self.bits is not None
                           else self.legacy_wire.name),
            "wire_cols": self._expected_wire_cols(),
            "chunk_rows": self.chunk_rows,
            "edges_sha": fc._edges_digest(self.edges),
            "sharding": (None if self.sharding is None
                         else str(self.sharding)),
        }

    def begin(self, stats: IngestStats):
        """Resolve warm vs cold. Returns (prepare, items) for
        `_pipelined_upload`."""
        self._stats = stats
        stats.wire = self.wire_mode
        stats.cache_key = self.key
        if self.cache_obj is not None:
            try:
                art = self.cache_obj.load(self.key)
                if art is not None:
                    self._check_meta(art)
                self.artifact = art
            except fc.FeatureCacheError as e:
                fc.count_corrupt()
                record_event("cache_corrupt", key=self.key,
                             build=self.kind, reason=e.reason)
                log.warning("feature cache: %s — rebuilding", e)
                self.artifact = None
        if self.artifact is not None:
            self.quant = self.artifact.quant
            stats.cache = "hit"
            return (_artifact_prepare(self.artifact, self.chunk_rows,
                                      stats),
                    range(0, self.n_pad, self.chunk_rows))
        if self.bits is not None:
            self.quant = fc.compute_quant_plan(
                self.store, self.bits, sample=self.params.quant_sample,
                seed=self.params.quant_seed)
        if self.cache_obj is not None:
            stats.cache = "miss"
            if self.params.writable:
                try:
                    self.writer = self.cache_obj.writer(self.key,
                                                        self._meta())
                except OSError:
                    log.warning("feature cache: cannot stage artifact "
                                "under %s; building uncached",
                                self.params.resolved_dir(), exc_info=True)
                    self.writer = None
        if self.quant is not None:
            prepare = _quant_prepare(self.store, self.chunk_rows,
                                     self.quant, stats)
        else:
            prepare = _chunk_prepare(self.store, self.chunk_rows,
                                     self.legacy_wire, stats)
        return prepare, range(0, self.store.n_rows, self.chunk_rows)

    def quant_device(self):
        """(scale, lo) as device arrays for the fused-dequant writes."""
        return jnp.asarray(self.quant.scale), jnp.asarray(self.quant.lo)

    def tee(self, chunk: np.ndarray) -> None:
        """Artifact append off the upload stream (main thread, item
        order). A failing disk degrades to an uncached build — it must
        not kill a multi-hundred-second upload."""
        if self.writer is None:
            return
        t0 = time.perf_counter()
        try:
            self.writer.append(chunk)
        except OSError:
            log.warning("feature cache: artifact append failed; "
                        "continuing uncached", exc_info=True)
            self.writer.abort()
            self.writer = None
            return
        if self._stats is not None:
            self._stats.cache_write_s += time.perf_counter() - t0

    def finish(self, stats: IngestStats, arrays: Tuple) -> None:
        """Post-pipeline bookkeeping: finalize the staged artifact
        (integrity manifest LAST → crash-consistent rename), emit
        hit/miss events + counters, stamp wire savings, and publish
        resident arrays when the policy keeps them."""
        if self.bits is not None:
            f16_equiv = self.n_pad * self.d * 2
            stats.bytes_saved_wire = max(0, f16_equiv - stats.bytes_wire)
        if self.params is None:
            return
        if stats.cache == "hit":
            saved = max(0.0, self.artifact.cold_wall_s - stats.wall_s)
            fc.count_hit(self.store.nbytes(), saved)
            record_event("cache_hit", key=self.key, build=self.kind,
                         saved_s=round(saved, 6), bytes=stats.cache_bytes)
        else:
            fc.count_miss()
            record_event("cache_miss", key=self.key, build=self.kind)
            if self.writer is not None:
                try:
                    self.writer.finalize(
                        quant=self.quant,
                        cold={"wall_s": round(stats.wall_s, 6),
                              "gbps": round(stats.gbps, 6),
                              "bytes_wire": stats.bytes_wire})
                except OSError:
                    log.warning("feature cache: artifact finalize failed; "
                                "next run rebuilds", exc_info=True)
                finally:
                    self.writer = None
        if self.params.resident and self.key:
            cold_wall = (self.artifact.cold_wall_s
                         if self.artifact is not None else stats.wall_s)
            fc.resident_put(self.key, arrays,
                            cold_wall_s=cold_wall or stats.wall_s)

    def abort(self) -> None:
        """Build died (deadline, worker error): remove the staged
        artifact so a torn tape can never be mistaken for a cache
        entry."""
        if self.writer is not None:
            self.writer.abort()
            self.writer = None


def device_matrix(store: ColumnarStore, dtype=jnp.bfloat16,
                  chunk_rows: int = UPLOAD_CHUNK_ROWS,
                  deadline_s: Optional[float] = None, *,
                  workers: Optional[int] = None,
                  depth: Optional[int] = None,
                  sharding=None, profile=None, return_stats: bool = False,
                  retry=None, cache=None):
    """Stream the store into one (n_pad, d) device buffer through the
    bounded-depth chunk pipeline (`data/pipeline.py`): worker threads
    read+cast upcoming chunks while up to `depth` donated writes are in
    flight. Rows pad to a chunk multiple with zeros (weight-masked
    everywhere downstream); donation keeps peak HBM = buffer + in-flight
    chunks. The returned buffer is READY (the pipeline drains all
    writes), so recorded timings are transfer time, not enqueue time.

    The wire dtype is the narrower of (store dtype, `dtype`); widening
    happens on device inside the donated write — an f16 store headed for
    a bf16 buffer ships 2 bytes/elem and casts on the VPU, bit-identical
    to a host-side cast (both round-to-nearest-even).

    `sharding`: optional NamedSharding for the buffer — each chunk is
    `jax.device_put` with the same spec, so multichip uploads spread
    across the mesh (a feature-axis spec like P(None, "data") splits
    every chunk's bytes across chips).

    `deadline_s`: optional wall-clock budget — tunnel upload bandwidth
    varies 100× between sessions (r4: 18-44 MB/s; r5 observed ~5 MB/s).
    Depth backpressure makes the per-chunk check track real transfer
    progress, so TimeoutError fires mid-upload for the caller to turn
    into an explicit skip marker.

    `cache`: feature-cache policy (None → process default/env;
    "off"/"read"/"readwrite"; or a `FeatureCacheParams`). On a hit the
    build replays the content-addressed wire artifact — zero store
    reads — and is bit-identical to the cold build that wrote it; on a
    readwrite miss the wire stream tees into a crash-consistent
    artifact for free. `FeatureCacheParams(wire="int8"/"int4")` ships a
    quantized wire with dequant fused into the donated write (2–4×
    fewer bytes; max abs error scale/2 per feature — see
    data/feature_cache.py)."""
    target = np.dtype(dtype)
    legacy_wire = (target if target.itemsize < store.dtype.itemsize
                   else store.dtype)
    sess = _CacheSession("matrix", store, chunk_rows,
                         legacy_wire=legacy_wire, target_name=target.name,
                         sharding=sharding, cache=cache)
    res = sess.resident()
    if res is not None:
        (x,), stats = res
        if profile is not None:
            profile.record_ingest("device_matrix_upload", stats)
        return (x, stats) if return_stats else x
    stats = IngestStats(label="device_matrix")
    workers, depth = _resolve_upload_plan(
        store, chunk_rows, workers, depth, stats,
        bytes_per_elem=(sess.bits / 8.0 if sess.bits
                        else float(sess.legacy_wire.itemsize)))
    prepare, items = sess.begin(stats)
    n_pad = sess.n_pad
    bufs = {"x": _zeros((n_pad, store.n_features), dtype, sharding)}

    if sess.quant is None:
        def write(bufs, cdev, r0):
            bufs["x"] = _write_cast_rows(bufs["x"], cdev, r0)
            return _probe(bufs["x"])
    else:
        scale_dev, lo_dev = sess.quant_device()
        bits = sess.quant.bits

        def write(bufs, cdev, r0):
            bufs["x"] = _dequant_write_rows(bufs["x"], cdev, scale_dev,
                                            lo_dev, r0, bits=bits)
            return _probe(bufs["x"])

    try:
        _pipelined_upload(items, chunk_rows, prepare, "device_matrix",
                          bufs, write, n_rows=store.n_rows,
                          workers=workers, depth=depth,
                          deadline_s=deadline_s, sharding=sharding,
                          profile=profile, retry=retry, stats=stats,
                          tee=sess.tee)
    except BaseException:
        sess.abort()
        raise
    sess.finish(stats, (bufs["x"],))
    return (bufs["x"], stats) if return_stats else bufs["x"]


def device_binned(store: ColumnarStore, edges: np.ndarray,
                  chunk_rows: int = UPLOAD_CHUNK_ROWS,
                  deadline_s: Optional[float] = None, *,
                  workers: Optional[int] = None,
                  depth: Optional[int] = None,
                  sharding=None, profile=None, return_stats: bool = False,
                  retry=None, cache=None):
    """(n_pad, d) int8 quantile-binned device buffer through the same
    chunk pipeline as `device_matrix`. Chunks ship as f16 and bin ON
    DEVICE (broadcast-compare, VPU): the r3 host `searchsorted` loop
    cost ~420 s at 10M×500 while f16 wire + device-side binning costs
    one pipelined upload pass. `deadline_s`/`sharding`/`profile`/
    `cache` as in `device_matrix`; a cache hit replays the f16 wire
    tape, so the binned matrix is BIT-IDENTICAL to the direct build
    (same wire bytes through the same device binning)."""
    sess = _CacheSession("binned", store, chunk_rows,
                         legacy_wire=np.dtype(np.float16),
                         target_name="int8", edges=edges,
                         sharding=sharding, cache=cache)
    res = sess.resident()
    if res is not None:
        (b,), stats = res
        if profile is not None:
            profile.record_ingest("device_binned_upload", stats)
        return (b, stats) if return_stats else b
    stats = IngestStats(label="device_binned")
    workers, depth = _resolve_upload_plan(
        store, chunk_rows, workers, depth, stats,
        bytes_per_elem=(sess.bits / 8.0 if sess.bits
                        else float(sess.legacy_wire.itemsize)))
    prepare, items = sess.begin(stats)
    n_pad = sess.n_pad
    edges_dev = jnp.asarray(edges)
    bufs = {"b": _zeros((n_pad, store.n_features), jnp.int8, sharding)}

    if sess.quant is None:
        def write(bufs, cdev, r0):
            bufs["b"] = _bin_write_rows(bufs["b"], cdev, edges_dev, r0)
            return _probe(bufs["b"])
    else:
        scale_dev, lo_dev = sess.quant_device()
        bits = sess.quant.bits

        def write(bufs, cdev, r0):
            bufs["b"] = _dequant_bin_write_rows(
                bufs["b"], cdev, scale_dev, lo_dev, edges_dev, r0,
                bits=bits)
            return _probe(bufs["b"])

    try:
        _pipelined_upload(items, chunk_rows, prepare, "device_binned",
                          bufs, write, n_rows=store.n_rows,
                          workers=workers, depth=depth,
                          deadline_s=deadline_s, sharding=sharding,
                          profile=profile, retry=retry, stats=stats,
                          tee=sess.tee)
    except BaseException:
        sess.abort()
        raise
    sess.finish(stats, (bufs["b"],))
    return (bufs["b"], stats) if return_stats else bufs["b"]


def dual_device_matrices(store: ColumnarStore, edges: np.ndarray,
                         dtype=jnp.bfloat16,
                         chunk_rows: int = UPLOAD_CHUNK_ROWS,
                         deadline_s: Optional[float] = None, *,
                         workers: Optional[int] = None,
                         depth: Optional[int] = None, sharding=None,
                         profile=None, return_stats: bool = False,
                         retry=None, cache=None):
    """ONE pass over the store → BOTH device representations: the
    (n_pad, d) `dtype` (bf16) linear-family matrix AND the (n_pad, d)
    int8 quantile-binned matrix. Halves host IO versus running
    `device_matrix` + `device_binned` back to back (the memmap is read
    once) and halves wire traffic too: chunks ship once as f16 and each
    donated write fans out device-side into the widen AND the bin.

    For an f16 store the bf16 matrix is bit-identical to
    `device_matrix`'s and the binned matrix to `device_binned`'s (same
    f16 wire, same device ops). For wider stores the wire is still f16
    — matching `device_binned`'s contract — so the bf16 matrix rounds
    through f16 first; use the separate builders when that matters.

    Both buffers must be HBM-resident simultaneously (3 bytes/elem
    total) — at 10M×500 that is ~15 GB before tree working set, so the
    bench gates this path on the memory plan fitting.

    `cache` as in `device_matrix`: the artifact is the SINGLE wire tape
    (the one f16 — or quantized — stream that fans out device-side into
    both representations), so caching the dual build costs one compact
    file, and a hit reproduces BOTH matrices bit-identically with zero
    store reads."""
    d = store.n_features
    target = np.dtype(dtype)
    sess = _CacheSession("dual", store, chunk_rows,
                         legacy_wire=np.dtype(np.float16),
                         target_name=target.name, edges=edges,
                         sharding=sharding, cache=cache)
    res = sess.resident()
    if res is not None:
        (x, b), stats = res
        if profile is not None:
            profile.record_ingest("dual_upload", stats)
        return (x, b, stats) if return_stats else (x, b)
    stats = IngestStats(label="dual")
    workers, depth = _resolve_upload_plan(
        store, chunk_rows, workers, depth, stats,
        bytes_per_elem=(sess.bits / 8.0 if sess.bits
                        else float(sess.legacy_wire.itemsize)))
    prepare, items = sess.begin(stats)
    n_pad = sess.n_pad
    edges_dev = jnp.asarray(edges)
    bufs = {"x": _zeros((n_pad, d), dtype, sharding),
            "b": _zeros((n_pad, d), jnp.int8, sharding)}

    if sess.quant is None:
        def write(bufs, cdev, r0):
            bufs["x"], bufs["b"] = _dual_write_rows(bufs["x"], bufs["b"],
                                                    cdev, edges_dev, r0)
            # one executable produces both buffers: either probe tokens
            # the completion of the pair
            return _probe(bufs["b"])
    else:
        scale_dev, lo_dev = sess.quant_device()
        bits = sess.quant.bits

        def write(bufs, cdev, r0):
            bufs["x"], bufs["b"] = _dequant_dual_write_rows(
                bufs["x"], bufs["b"], cdev, scale_dev, lo_dev, edges_dev,
                r0, bits=bits)
            return _probe(bufs["b"])

    try:
        _pipelined_upload(items, chunk_rows, prepare, "dual", bufs, write,
                          n_rows=store.n_rows, workers=workers,
                          depth=depth, deadline_s=deadline_s,
                          sharding=sharding, profile=profile, retry=retry,
                          stats=stats, tee=sess.tee)
    except BaseException:
        sess.abort()
        raise
    sess.finish(stats, (bufs["x"], bufs["b"]))
    if return_stats:
        return bufs["x"], bufs["b"], stats
    return bufs["x"], bufs["b"]


# --------------------------------------------------------------------------- #
# linear family                                                               #
# --------------------------------------------------------------------------- #

@partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_logreg_big(X16: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
                   l2, n_classes: int, max_iter: int = 50) -> Dict:
    """`fit_logreg` against a bf16 device-resident X: the X·W / Xᵀ·R
    matmuls run with bf16 operands at full MXU rate and f32 accumulation
    instead of promoting X to f32 (which would materialize a 20 GB copy).
    Same L-BFGS loop, vmappable over (l2, w)."""
    d = X16.shape[1]
    y1 = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=jnp.float32)
    wsum = jnp.maximum(w.sum(), 1.0)

    def loss_fn(p):
        logits = jnp.matmul(X16, p["W"].astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32) + p["b"]
        ll = optax.softmax_cross_entropy(logits, y1)
        return (ll * w).sum() / wsum + 0.5 * l2 * (p["W"] ** 2).sum()

    params = {"W": jnp.zeros((d, n_classes), jnp.float32),
              "b": jnp.zeros((n_classes,), jnp.float32)}
    opt = optax.lbfgs()
    state = opt.init(params)
    vg = optax.value_and_grad_from_state(loss_fn)

    def step(carry, _):
        p, s = carry
        v, g = vg(p, state=s)
        updates, s = opt.update(g, s, p, value=v, grad=g, value_fn=loss_fn)
        return (optax.apply_updates(p, updates), s), v

    (params, _), _ = jax.lax.scan(step, (params, state), None,
                                  length=max_iter)
    return params


@partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_logreg_enet_big(X16: jnp.ndarray, y: jnp.ndarray, w: jnp.ndarray,
                        l1, l2, n_classes: int, max_iter: int = 200) -> Dict:
    """`fit_logreg_enet` (FISTA) against bf16 device-resident X — the
    default LR grid is elastic-net, so the 10M-row sweep needs this
    path. All X-touching products are bf16×bf16 → f32."""
    d = X16.shape[1]
    y1 = jax.nn.one_hot(y.astype(jnp.int32), n_classes, dtype=jnp.float32)
    wsum = jnp.maximum(w.sum(), 1.0)

    def mv(v):  # Xᵀ diag(w) X v with bf16 X
        xv = jnp.matmul(X16, v.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        return jnp.matmul(X16.T, (w * xv).astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)

    v0 = jnp.full((d,), 1.0 / jnp.sqrt(jnp.float32(d)), jnp.float32)

    def pw(v, _):
        u = mv(v)
        nrm = jnp.linalg.norm(u)
        return u / jnp.maximum(nrm, 1e-12), nrm

    _, norms = jax.lax.scan(pw, v0, None, length=16)
    L = 0.5 * 1.05 * norms[-1] / wsum + l2 + 1e-8  # softmax Hessian bound 1/2
    step = 1.0 / L

    def smooth_grads(W, b):
        logits = jnp.matmul(X16, W.astype(jnp.bfloat16),
                            preferred_element_type=jnp.float32) + b
        R = (jax.nn.softmax(logits) - y1) * w[:, None]
        gW = jnp.matmul(X16.T, R.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) / wsum + l2 * W
        return gW, R.sum(0) / wsum

    def fista_step(carry, _):
        W, b, Wm, bm, t = carry
        gW, gb = smooth_grads(Wm, bm)
        W1 = Wm - step * gW
        W1 = jnp.sign(W1) * jnp.maximum(jnp.abs(W1) - step * l1, 0.0)
        b1 = bm - step * gb
        t1 = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t1
        return (W1, b1, W1 + beta * (W1 - W), b1 + beta * (b1 - b), t1), None

    W0 = jnp.zeros((d, n_classes), jnp.float32)
    b0 = jnp.zeros((n_classes,), jnp.float32)
    (W, b, _, _, _), _ = jax.lax.scan(
        fista_step, (W0, b0, W0, b0, jnp.float32(1.0)), None,
        length=max_iter)
    return {"W": W, "b": b}


@partial(jax.jit, static_argnames=("n_classes", "max_iter"))
def fit_logreg_enet_grids_big(X16: jnp.ndarray, y: jnp.ndarray,
                              w: jnp.ndarray, l1v: jnp.ndarray,
                              l2v: jnp.ndarray, n_classes: int,
                              max_iter: int = 200) -> Dict:
    """The WHOLE elastic-net grid in one program with X read once per
    FISTA step: weights live as (d, g·k) so the forward/adjoint products
    are single wide matmuls — at 10M×500 bf16 (10 GB) the fit is HBM-
    bandwidth bound, and a vmap over grids would re-stream X per grid
    (g× the traffic, 60s+ dispatches); stacking grids into the matmul
    output dim costs one X pass for all of them. Returns
    {"W": (g, d, k), "b": (g, k)}."""
    d = X16.shape[1]
    g = l1v.shape[0]
    k = n_classes
    y1 = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=jnp.float32)
    wsum = jnp.maximum(w.sum(), 1.0)

    def mv(v):
        xv = jnp.matmul(X16, v.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
        return jnp.matmul(X16.T, (w * xv).astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)

    v0 = jnp.full((d,), 1.0 / jnp.sqrt(jnp.float32(d)), jnp.float32)

    def pw(v, _):
        u = mv(v)
        nrm = jnp.linalg.norm(u)
        return u / jnp.maximum(nrm, 1e-12), nrm

    _, norms = jax.lax.scan(pw, v0, None, length=16)
    lam = norms[-1] / wsum                       # shared λmax(XᵀWX)/wsum
    L = 0.5 * 1.05 * lam + l2v + 1e-8            # (g,) softmax bound 1/2
    step = (1.0 / L)[None, :, None]              # (1, g, 1) for W
    step_b = (1.0 / L)[:, None]                  # (g, 1) for b
    l1 = l1v[None, :, None]
    l2 = l2v[None, :, None]

    def smooth_grads(W, b):                      # W (d, g, k), b (g, k)
        logits = jnp.matmul(
            X16, W.reshape(d, g * k).astype(jnp.bfloat16),
            preferred_element_type=jnp.float32).reshape(-1, g, k) + b
        R = (jax.nn.softmax(logits, axis=-1) - y1[:, None, :]) \
            * w[:, None, None]                   # (n, g, k)
        gW = jnp.matmul(X16.T, R.reshape(-1, g * k).astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32
                        ).reshape(d, g, k) / wsum + l2 * W
        return gW, R.sum(0) / wsum

    def fista_step(carry, _):
        W, b, Wm, bm, t = carry
        gW, gb = smooth_grads(Wm, bm)
        W1 = Wm - step * gW
        W1 = jnp.sign(W1) * jnp.maximum(jnp.abs(W1) - step * l1, 0.0)
        b1 = bm - step_b * gb
        t1 = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        beta = (t - 1.0) / t1
        return (W1, b1, W1 + beta * (W1 - W), b1 + beta * (b1 - b), t1), None

    W0 = jnp.zeros((d, g, k), jnp.float32)
    b0 = jnp.zeros((g, k), jnp.float32)
    (W, b, _, _, _), _ = jax.lax.scan(
        fista_step, (W0, b0, W0, b0, jnp.float32(1.0)), None,
        length=max_iter)
    return {"W": jnp.transpose(W, (1, 0, 2)), "b": b}


@partial(jax.jit, static_argnames=())
def predict_logreg_grids_big(W, b, X16):
    """(g, n, k) probabilities for stacked grid weights — one X pass."""
    d, (g, _, k) = X16.shape[1], W.shape
    logits = jnp.matmul(
        X16, jnp.transpose(W, (1, 0, 2)).reshape(d, g * k).astype(
            jnp.bfloat16),
        preferred_element_type=jnp.float32).reshape(-1, g, k) + b
    return jnp.transpose(jax.nn.softmax(logits, axis=-1), (1, 0, 2))


@partial(jax.jit, static_argnames=())
def predict_logreg_big(W, b, X16):
    logits = jnp.matmul(X16, W.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) + b
    prob = jax.nn.softmax(logits, axis=-1)
    return {"prediction": jnp.argmax(logits, -1).astype(jnp.float32),
            "rawPrediction": logits, "probability": prob}


# --------------------------------------------------------------------------- #
# tree families: chunked-histogram growth                                     #
# --------------------------------------------------------------------------- #

def _chunked_histograms(Xb, node_idx, V, n_nodes: int, n_bins: int,
                        chunk: int):
    """(V_cols, nodes, d, bins) f32 histograms without materializing the
    full bin one-hot: scan over row chunks, per chunk ONE matmul
    (V·nodes, c) @ (c, d·bins) — the A side stacks every histogram value
    column (gradients + weights) so the 1-2 GB per-chunk one-hot B is
    read exactly once."""
    n, d = Xb.shape
    m = V.shape[1]
    n_chunks = n // chunk

    # scan over chunk INDICES and dynamic-slice each operand: passing the
    # reshaped (n_chunks, chunk, d) array as scan xs makes XLA materialize
    # a re-laid-out copy of the whole multi-GB buffer (the r5 10M×500
    # lockstep OOM'd by 62M with TWO such copies resident); aligned
    # dynamic slices read the argument buffer in place
    def body(acc, i):
        r0 = i * chunk
        xb_c = jax.lax.dynamic_slice(Xb, (r0, 0), (chunk, d))
        ni_c = jax.lax.dynamic_slice(node_idx, (r0,), (chunk,))
        v_c = jax.lax.dynamic_slice(V, (r0, 0), (chunk, m))
        B = jax.nn.one_hot(xb_c, n_bins,
                           dtype=jnp.bfloat16).reshape(chunk, d * n_bins)
        A = jax.nn.one_hot(ni_c, n_nodes, dtype=jnp.bfloat16)  # (c, nodes)
        # (c, m·nodes): value v times node indicator, all columns at once
        Av = (A[:, None, :] * v_c.astype(jnp.bfloat16)[:, :, None]
              ).reshape(chunk, m * n_nodes)
        h = jnp.matmul(Av.T, B, preferred_element_type=jnp.float32)
        return acc + h.reshape(m, n_nodes, d, n_bins), None

    acc0 = jnp.zeros((m, n_nodes, d, n_bins), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, jnp.arange(n_chunks, dtype=jnp.int32))
    return acc


def _chunked_leaf_sums(node_idx, V, n_nodes: int, chunk: int):
    """(nodes, m) Σ per-node values via chunked matmul (scatter-add
    serializes at 10M rows)."""
    n, m = V.shape
    n_chunks = n // chunk

    def body(acc, i):
        r0 = i * chunk
        ni_c = jax.lax.dynamic_slice(node_idx, (r0,), (chunk,))
        v_c = jax.lax.dynamic_slice(V, (r0, 0), (chunk, m))
        A = jax.nn.one_hot(ni_c, n_nodes, dtype=jnp.bfloat16)
        return acc + jnp.matmul(A.T, v_c.astype(jnp.bfloat16),
                                preferred_element_type=jnp.float32), None

    acc, _ = jax.lax.scan(body, jnp.zeros((n_nodes, m), jnp.float32),
                          jnp.arange(n_chunks, dtype=jnp.int32))
    return acc


def _chunked_histograms_multi(Xb, node_K, V_K, n_nodes: int, n_bins: int,
                              chunk: int):
    """(K, p, nodes, d, bins) f32 histograms for K LOCKSTEP learners from
    ONE bin one-hot build per row chunk.

    The r5 cost measurement (see `grow_trees_big_lockstep`) showed the
    per-chunk cost of the histogram matmul is FLAT in the number of
    histogram rows up to several hundred (the MXU pads the output M axis
    to the 128-row tile; streaming the (chunk, d·bins) one-hot operand is
    the floor). Growing K learners level-synchronized therefore amortizes
    the dominant one-hot cost K-fold: the A side stacks every learner's
    node-indicator × value columns into one (chunk, K·p·nodes) operand.

    node_K: (K, n) int32 per-learner node assignment; V_K: (K, n, p)
    value columns (gradient cols + weight col — bf16 is enough: the
    matmul quantizes operands to bf16 anyway, matching `_histograms`'s
    documented precision contract)."""
    n, d = Xb.shape
    K, _, p = V_K.shape
    n_chunks = n // chunk

    # index-scan + dynamic slices, NOT reshaped/transposed scan xs: the
    # (n_chunks, chunk, d) view chose a transposed layout and XLA kept a
    # second full copy of the 4.9 GB Xb — 9.7 GB of HLO temps that OOM'd
    # the 10M×500 lockstep compile (r5); slices read the buffers in place
    def body(acc, i):
        r0 = i * chunk
        xb_c = jax.lax.dynamic_slice(Xb, (r0, 0), (chunk, d))
        ni_c = jax.lax.dynamic_slice(node_K, (0, r0), (K, chunk))
        v_c = jax.lax.dynamic_slice(V_K, (0, r0, 0), (K, chunk, p))
        B = jax.nn.one_hot(xb_c, n_bins,
                           dtype=jnp.bfloat16).reshape(chunk, d * n_bins)
        # joint A operand (c, K·p·nodes): per-row, K·p nonzeros
        oh = (jnp.transpose(ni_c)[:, :, None]
              == jnp.arange(n_nodes, dtype=jnp.int32)[None, None, :]
              )                                        # (c, K, nodes)
        vt = jnp.transpose(v_c, (1, 0, 2)).astype(jnp.bfloat16)  # (c, K, p)
        Av = (oh[:, :, None, :].astype(jnp.bfloat16)
              * vt[:, :, :, None]).reshape(chunk, K * p * n_nodes)
        h = jnp.matmul(Av.T, B, preferred_element_type=jnp.float32)
        return acc + h.reshape(K, p, n_nodes, d, n_bins), None

    acc0 = jnp.zeros((K, p, n_nodes, d, n_bins), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, jnp.arange(n_chunks, dtype=jnp.int32))
    return acc


def _chunked_leaf_sums_multi(node_K, V_K, n_nodes: int, chunk: int):
    """(K, nodes, p) per-learner leaf sums, one pass over the rows."""
    K, n, p = V_K.shape
    n_chunks = n // chunk

    def body(acc, i):
        r0 = i * chunk
        ni_c = jax.lax.dynamic_slice(node_K, (0, r0), (K, chunk))
        v_c = jax.lax.dynamic_slice(V_K, (0, r0, 0), (K, chunk, p))
        A = jax.nn.one_hot(ni_c, n_nodes, dtype=jnp.bfloat16)  # (K, c, nodes)
        h = jnp.einsum("kcn,kcp->knp", A, v_c.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
        return acc + h, None

    acc, _ = jax.lax.scan(body, jnp.zeros((K, n_nodes, p), jnp.float32),
                          jnp.arange(n_chunks, dtype=jnp.int32))
    return acc


def _select_bin_big(Xb: jnp.ndarray, feat_idx: jnp.ndarray) -> jnp.ndarray:
    """Xb[r, feat_idx[r]] as a fused compare+reduce (elementwise over the
    int8 matrix; XLA fuses the one-hot into the reduction, nothing
    (n, d)-sized materializes)."""
    d = Xb.shape[1]
    onehot = jnp.arange(d, dtype=jnp.int32)[None, :] == feat_idx[:, None]
    return jnp.where(onehot, Xb.astype(jnp.int32), 0).sum(axis=1)


@partial(jax.jit, static_argnames=("max_depth", "n_bins", "chunk"))
def grow_tree_big(Xb: jnp.ndarray, G: jnp.ndarray, H: jnp.ndarray,
                  max_depth: int, n_bins: int, reg_lambda=1.0,
                  min_child_weight=1.0, min_gain=0.0, min_gain_norm=0.0,
                  feature_mask: Optional[jnp.ndarray] = None,
                  chunk: int = HIST_CHUNK_ROWS) -> Dict:
    """`grow_tree` for device-resident int8 bins at out-of-core row
    counts. Same dense-array tree encoding, same split rule
    (`split_from_histograms`), chunked reductions."""
    n, d = Xb.shape
    m = G.shape[1]
    max_nodes = 2 ** max_depth
    node_idx = jnp.zeros(n, dtype=jnp.int32)
    feats = jnp.zeros((max_depth, max_nodes), jnp.int32)
    bins = jnp.full((max_depth, max_nodes), n_bins, jnp.int32)
    GH = jnp.concatenate([G, H[:, None]], axis=1)  # (n, m+1)

    for level in range(max_depth):
        n_nodes = 2 ** level
        hist = _chunked_histograms(Xb, node_idx, GH, n_nodes, n_bins, chunk)
        hg, hh = hist[:m], hist[m]
        bf, bb = split_from_histograms(
            hg, hh, n_bins, reg_lambda, min_child_weight, min_gain,
            min_gain_norm, feature_mask, level, None)
        feats = feats.at[level, :n_nodes].set(bf)
        bins = bins.at[level, :n_nodes].set(bb)
        from transmogrifai_tpu.models.trees import (
            _ONEHOT_LOOKUP_MAX, _table_lookup2)
        if n_nodes <= _ONEHOT_LOOKUP_MAX:
            sample_feat, split_bin = _table_lookup2(bf, bb, node_idx)
        else:
            sample_feat, split_bin = bf[node_idx], bb[node_idx]
        sample_bin = _select_bin_big(Xb, sample_feat)
        node_idx = node_idx * 2 + (sample_bin > split_bin).astype(jnp.int32)

    sums = _chunked_leaf_sums(node_idx, GH, max_nodes, chunk)
    leaf_g, leaf_h = sums[:, :m], sums[:, m]
    leaf = leaf_g / (leaf_h + reg_lambda)[:, None]
    return {"feat": feats, "bin": bins, "leaf": leaf}


@partial(jax.jit, static_argnames=("max_depth", "n_bins", "chunk"))
def grow_trees_big_lockstep(Xb, V_K, max_depth: int, n_bins: int,
                            reg_lambda=1.0, min_child_weight=1.0,
                            min_gain=0.0, min_gain_norm=0.0,
                            feature_mask_K: Optional[jnp.ndarray] = None,
                            chunk: int = HIST_CHUNK_ROWS) -> Dict:
    """Grow K trees LEVEL-SYNCHRONIZED, sharing each chunk's bin one-hot.

    r5 measurement (65536×500×32 chunk, v5e): one histogram matmul costs
    ~17-24 ms per chunk whether it produces 2 histogram rows or 514 —
    the (chunk, d·bins) one-hot operand stream is the floor, so a single
    tree wastes ~98% of the M axis. Growing the whole lockstep batch
    against one B build amortizes that floor K-fold (6.5 s/tree →
    ~1 s/tree at K=8, the r4 VERDICT #2 target). The per-learner value
    columns V_K (K, n, m+1) carry [G·, H] (gradients/labels × bootstrap
    weights, then the weight column); trees may differ in bootstrap
    weights (RF), gradients (GBT fold pairs), and feature masks.

    Returns {"feat": (K, depth, 2^depth), "bin": ..., "leaf":
    (K, 2^depth, m)} — `fit_forest`-shaped stacked arrays."""
    from transmogrifai_tpu.models.trees import (
        _ONEHOT_LOOKUP_MAX, _table_lookup2)
    n, d = Xb.shape
    K, _, p = V_K.shape
    m = p - 1
    max_nodes = 2 ** max_depth
    node_K = jnp.zeros((K, n), dtype=jnp.int32)
    feats = jnp.zeros((K, max_depth, max_nodes), jnp.int32)
    bins = jnp.full((K, max_depth, max_nodes), n_bins, jnp.int32)

    def split_k(hg, hh, fmask, level):
        return split_from_histograms(
            hg, hh, n_bins, reg_lambda, min_child_weight, min_gain,
            min_gain_norm, fmask, level, None)

    for level in range(max_depth):
        n_nodes = 2 ** level
        hist = _chunked_histograms_multi(Xb, node_K, V_K, n_nodes,
                                         n_bins, chunk)
        hg_K, hh_K = hist[:, :m], hist[:, m]
        if feature_mask_K is None:
            bf_K, bb_K = jax.vmap(split_k, in_axes=(0, 0, None, None))(
                hg_K, hh_K, None, level)
        else:
            bf_K, bb_K = jax.vmap(split_k, in_axes=(0, 0, 0, None))(
                hg_K, hh_K, feature_mask_K, level)
        feats = feats.at[:, level, :n_nodes].set(bf_K)
        bins = bins.at[:, level, :n_nodes].set(bb_K)

        def route(args):
            bf, bb, node = args
            if n_nodes <= _ONEHOT_LOOKUP_MAX:
                sf, sb_ = _table_lookup2(bf, bb, node)
            else:
                sf, sb_ = bf[node], bb[node]
            sample_bin = _select_bin_big(Xb, sf)
            return node * 2 + (sample_bin > sb_).astype(jnp.int32)

        # lax.map (not vmap): a vmapped (K, n, d) one-hot select would
        # gamble on full fusion of a 40 GB intermediate at 10M rows; the
        # sequential per-learner pass is a bounded (n, d) VPU stream
        node_K = jax.lax.map(route, (bf_K, bb_K, node_K))

    sums = _chunked_leaf_sums_multi(node_K, V_K, max_nodes, chunk)
    leaf_g, leaf_h = sums[:, :, :m], sums[:, :, m]
    leaf = leaf_g / (leaf_h + reg_lambda)[:, :, None]
    return {"feat": feats, "bin": bins, "leaf": leaf}


# r5-measured per-chunk histogram-matmul floor: ~8 ms for one
# (65536, 500·32) one-hot operand stream (v5e), scaling with the operand
# width; cost stays flat until the matmul's output M axis (K·p·nodes
# rows) exceeds ~512, then grows roughly linearly with M tiles.
_CHUNK_FLOOR_S = 0.008
_FLAT_M_ROWS = 512.0


def lockstep_dispatch_estimate_s(n: int, d: int, n_bins: int,
                                 max_depth: int, K: int, p: int,
                                 chunk: int = HIST_CHUNK_ROWS) -> float:
    """Wall-clock model for one lockstep batch dispatch: per level, every
    row chunk pays the one-hot stream floor times the M-tile factor."""
    n_chunks = -(-n // chunk)
    per_chunk = _CHUNK_FLOOR_S * (d * n_bins) / 16000.0
    total = sum(max(1.0, K * p * (2.0 ** level) / _FLAT_M_ROWS)
                for level in range(max_depth)) * n_chunks * per_chunk
    return total * 1.2  # routing + leaf passes ride on top (~20%)


def lockstep_width(max_depth: int, d: int, n_bins: int, m: int,
                   requested: int, n: Optional[int] = None,
                   target_s: float = 20.0) -> int:
    """How many lockstep learners per dispatch: bound the deepest level's
    carried histogram (K·(m+1)·2^(depth-1)·d·bins f32) to ~800 MB AND —
    when the row count is known — bound the modeled dispatch wall-clock
    to `target_s` (the serving layer kills single executions past ~60s;
    deep levels leave the flat-cost regime, so K must shrink with
    depth). A deep-enough single tree can exceed the target by itself;
    K=1 then matches the pre-lockstep behavior."""
    budget_elems = 2e8  # ~800 MB f32 carried histogram
    per_learner = (m + 1) * (2 ** (max_depth - 1)) * d * n_bins
    k_mem = max(1, int(budget_elems // max(per_learner, 1)))
    k = max(1, min(requested, k_mem, 16))
    if n is not None:
        while k > 1 and lockstep_dispatch_estimate_s(
                n, d, n_bins, max_depth, k, m + 1) > target_s:
            k -= 1
    return k


@partial(jax.jit, static_argnames=("max_depth", "n_bins", "chunk",
                                   "bootstrap", "n_sub"))
def _forest_lockstep_batch(Xb, Y, w, keys, max_depth: int, n_bins: int,
                           min_child_weight, min_gain,
                           n_sub: Optional[int], bootstrap: bool,
                           chunk: int):
    """One lockstep batch of keys.shape[0] bootstrap trees: per-tree
    Poisson weights and feature masks drawn in-program, value columns
    [Y·boot, boot] stacked bf16 (the histogram matmul quantizes to bf16
    regardless — see `_histograms`'s precision contract)."""
    n, d = Xb.shape

    def inputs(key):
        k1, k2 = jax.random.split(key)
        if bootstrap:
            boot = jax.random.poisson(k1, 1.0, (n,)).astype(jnp.float32) * w
        else:
            boot = w
        V = jnp.concatenate([Y * boot[:, None], boot[:, None]],
                            axis=1).astype(jnp.bfloat16)
        if n_sub is not None and n_sub < d:
            scores = jax.random.uniform(k2, (d,))
            fmask = scores <= jnp.sort(scores)[n_sub - 1]
        else:
            fmask = jnp.ones((d,), bool)
        return V, fmask

    V_K, fm_K = jax.vmap(inputs)(keys)
    return grow_trees_big_lockstep(
        Xb, V_K, max_depth, n_bins, reg_lambda=1e-6,
        min_child_weight=min_child_weight, min_gain_norm=min_gain,
        feature_mask_K=fm_K, chunk=chunk)


def fit_forest_big(Xb, Y, w, n_trees: int, max_depth: int, n_bins: int,
                   n_outputs: int, seed: int = 0,
                   subsample_features: bool = True,
                   min_child_weight: float = 1.0, min_gain: float = 0.0,
                   bootstrap: bool = True,
                   chunk: int = HIST_CHUNK_ROWS,
                   trees_per_dispatch: Optional[int] = None) -> Dict:
    """Host loop dispatching LOCKSTEP tree batches (r5): each dispatch
    grows `trees_per_dispatch` trees level-synchronized against shared
    per-chunk bin one-hots — the dominant out-of-core histogram cost
    amortizes across the batch (~6.5 s/tree alone → ~1 s/tree at K=8;
    see `grow_trees_big_lockstep`). No single execution can hit the ~60s
    serving kill. Returns stacked (T, ...) tree arrays like
    `fit_forest`. (`n_outputs` is accepted for `fit_forest` signature
    parity; the output width comes from Y's trailing dim.)"""
    n, d = int(Xb.shape[0]), int(Xb.shape[1])
    n_sub = max(int(np.sqrt(d)), 1) if subsample_features else None
    m = int(Y.shape[1])
    K = lockstep_width(max_depth, d, n_bins, m,
                       trees_per_dispatch or 16, n=n)
    K = min(K, n_trees)
    # pad the tree count up to a batch multiple (extra trees are grown
    # and sliced off) so every dispatch reuses ONE compiled batch shape
    n_batches = -(-n_trees // K)
    keys = jax.random.split(jax.random.PRNGKey(seed), n_batches * K)
    parts = []
    for b in range(n_batches):
        ks = keys[b * K:(b + 1) * K]
        parts.append(_forest_lockstep_batch(
            Xb, Y, w, ks, max_depth, n_bins,
            min_child_weight, min_gain, n_sub, bootstrap, chunk))
    trees = jax.tree.map(lambda *a: jnp.concatenate(a), *parts)
    return jax.tree.map(lambda a: a[:n_trees], trees)


@partial(jax.jit, static_argnames=("max_depth", "n_bins", "objective",
                                   "chunk"))
def _gbt_round_big(Xb, y, w, margin, max_depth: int, n_bins: int,
                   learning_rate, reg_lambda, objective: str,
                   min_child_weight=1.0, gamma=0.0,
                   chunk: int = HIST_CHUNK_ROWS):
    """One deterministic boosting round (the big path has no row/column
    subsampling, so no PRNG plumbing)."""
    if objective == "logistic":
        p = jax.nn.sigmoid(margin)
        g, h = (p - y) * w, jnp.maximum(p * (1 - p), 1e-6) * w
    else:
        g, h = (margin - y) * w, w
    tree = grow_tree_big(Xb, (-g)[:, None], h, max_depth, n_bins,
                         reg_lambda=reg_lambda,
                         min_child_weight=min_child_weight, min_gain=gamma,
                         chunk=chunk)
    upd = predict_tree_big(tree, Xb)[:, 0]
    return margin + learning_rate * upd, tree


def fit_gbt_big(Xb, y, w, n_estimators: int, max_depth: int, n_bins: int,
                learning_rate, reg_lambda, objective: str = "logistic",
                min_child_weight: float = 1.0, gamma: float = 0.0,
                seed: int = 0, chunk: int = HIST_CHUNK_ROWS
                ) -> Tuple[Dict, jnp.ndarray]:
    """Host loop over boosting rounds carrying the device margin.
    `seed` is accepted for signature parity with `fit_gbt` but currently
    unused — the big path has no row/column subsampling (deterministic
    rounds)."""
    n = Xb.shape[0]
    margin = jnp.zeros(n, jnp.float32)
    trees = []
    for r in range(n_estimators):
        margin, tree = _gbt_round_big(
            Xb, y, w, margin, max_depth, n_bins,
            jnp.float32(learning_rate), jnp.float32(reg_lambda), objective,
            min_child_weight, jnp.float32(gamma), chunk)
        trees.append(tree)
    return jax.tree.map(lambda *a: jnp.stack(a), *trees), margin


@partial(jax.jit, static_argnames=("max_depth", "n_bins", "objective",
                                   "chunk"))
def _gbt_round_big_lockstep(Xb, y, w_K, margin_K, max_depth: int,
                            n_bins: int, learning_rate, reg_lambda,
                            objective: str, min_child_weight=1.0,
                            gamma=0.0, chunk: int = HIST_CHUNK_ROWS):
    """One boosting round for K LOCKSTEP grid×fold pairs: each pair has
    its own margin and row weights (fold masks), but every pair's
    gradient histograms contract against the SAME per-chunk bin one-hot
    (`grow_trees_big_lockstep`) — one round for a 6-pair CV sweep costs
    ~the same as 1-2 single-pair rounds instead of 6 (r5)."""
    if objective == "logistic":
        p = jax.nn.sigmoid(margin_K)
        g = (p - y[None, :]) * w_K
        h = jnp.maximum(p * (1 - p), 1e-6) * w_K
    else:
        g = (margin_K - y[None, :]) * w_K
        h = w_K
    V_K = jnp.stack([-g, h], axis=-1).astype(jnp.bfloat16)  # (K, n, 2)
    trees = grow_trees_big_lockstep(
        Xb, V_K, max_depth, n_bins, reg_lambda=reg_lambda,
        min_child_weight=min_child_weight, min_gain=gamma, chunk=chunk)

    def upd(t):  # sequential per pair: bounded (n, d) routing streams
        return predict_tree_big(t, Xb)[:, 0]

    upd_K = jax.lax.map(upd, trees)
    return margin_K + learning_rate * upd_K, trees


def fit_gbt_big_lockstep(Xb, y, w_K, n_estimators: int, max_depth: int,
                         n_bins: int, learning_rate, reg_lambda,
                         objective: str = "logistic",
                         min_child_weight: float = 1.0, gamma: float = 0.0,
                         chunk: int = HIST_CHUNK_ROWS
                         ) -> Tuple[Dict, jnp.ndarray]:
    """Host loop over rounds for K lockstep pairs; returns
    ({"feat": (T, K, ...), ...}, margins (K, n)). The caller picks K:
    check `lockstep_dispatch_estimate_s(n, d, n_bins, max_depth, K, 2)`
    stays well under the ~60s serving exec kill (deep rounds at 10M rows
    may need the pair set split across two host loops)."""
    n = Xb.shape[0]
    K = int(w_K.shape[0])
    margin_K = jnp.zeros((K, n), jnp.float32)
    trees = []
    for r in range(n_estimators):
        margin_K, tree = _gbt_round_big_lockstep(
            Xb, y, w_K, margin_K, max_depth, n_bins,
            jnp.float32(learning_rate), jnp.float32(reg_lambda), objective,
            min_child_weight, jnp.float32(gamma), chunk)
        trees.append(tree)
    return jax.tree.map(lambda *a: jnp.stack(a), *trees), margin_K


def predict_tree_big(tree: Dict, Xb: jnp.ndarray) -> jnp.ndarray:
    """`predict_tree` with the big-n fused compare-select — the shared
    walk + gather-free leaf reads, just a different per-row selector."""
    from transmogrifai_tpu.models.trees import predict_tree
    return predict_tree(tree, Xb, select_fn=_select_bin_big)


@partial(jax.jit, static_argnames=())
def predict_forest_big(trees: Dict, Xb: jnp.ndarray) -> jnp.ndarray:
    preds = jax.lax.map(lambda t: predict_tree_big(t, Xb), trees)
    return preds.mean(axis=0)
