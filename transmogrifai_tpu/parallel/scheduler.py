"""Distributed sweep execution: a work-stealing grid scheduler over a mesh.

The reference distributes exactly this workload — model×grid×fold fits
fanned out as Futures over a Spark executor pool
(`OpValidator.scala:299-358`) — and the TensorFlow paper (arxiv
1605.08695, PAPERS.md) maps the same shape onto dataflow workers. This
module is that story on a `jax.sharding.Mesh`: the grid-config blocks
the family handlers in `parallel/sweep.py` already compile as single
XLA programs become the scheduler's work units, partitioned across the
mesh's SWEEP axis, one worker lane per sweep row.

Design:

- **block = compiled group.** `sweep.static_signature(est, grid)` cuts
  each family's grids along the exact boundaries the handlers group
  them for compilation, so a scheduled block regroups into ONE batched
  program on its worker — distribution never splits a compile.
- **work stealing.** Blocks are dealt round-robin into per-worker
  deques (longest-first, LPT-style packing); a worker that drains its
  own deque steals from the back of the longest other deque (recorded
  as a ``steal`` event on its lane). A worker that dies of a
  worker-level fault retires and its in-flight block is requeued for
  the survivors — a preempted worker costs only its in-flight block.
- **the journal is the shared completion log.** Each worker appends
  completed blocks to its own `ShardedSweepJournal` shard
  (``journal-w<k>.jsonl`` — no shared fd, so concurrent appends cannot
  interleave), and lookups merge every shard: resume skips the union
  of all workers' completed blocks and reproduces the bit-identical
  winner, the PR-4 single-device invariant now under concurrency.
- **preemption (InjectedKill / BaseException) drains.** A kill observed
  by one worker cancels undispatched work, lets the other lanes finish
  (and journal) their in-flight blocks, then re-raises — a resumed
  schedule re-runs only the killed worker's in-flight block plus any
  blocks never dispatched before the kill (with blocks ≤ lanes, exactly
  the one in-flight block); completed blocks never re-run.
- **per-worker lanes in the trace.** Every worker opens a
  ``sweep:worker:<k>`` span under the scheduling root; steal/idle
  events land on the lane, and the end-of-run ``mesh_utilization``
  event (Σbusy / workers·wall, straggler flag) feeds the
  `GoodputReport` mesh rollup (obs/goodput.py).

Device placement: worker k owns sweep-row k of the (sweep, data) mesh.
With a 1-wide data axis the block's inputs are `device_put` onto the
worker's device and the block runs exactly the single-device program
(bit-identical metrics). With data > 1 the worker gets a (1, data)
sub-mesh as its `FitContext.mesh`, so `run_sweep`'s existing data-axis
path shards the rows across the worker's devices — data-parallel fits
and sweep-parallel grid execution compose on one 2-D mesh.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from transmogrifai_tpu.obs import export as obs_export
from transmogrifai_tpu.obs.trace import TRACER
from transmogrifai_tpu.parallel.mesh import DATA_AXIS, SWEEP_AXIS
from transmogrifai_tpu.parallel.sweep import (
    journal_prefill, run_sweep, static_signature)
from transmogrifai_tpu.runtime.faults import SITE_WORKER_BLOCK, fault_point

__all__ = ["SweepJob", "GridScheduler", "HostScheduler", "SchedulerReport",
           "WorkerStats"]

log = logging.getLogger(__name__)


@dataclass
class SweepJob:
    """One model family's sweep, as submitted to the scheduler."""

    index: int                 # caller's job id (the selector's model index)
    est: Any                   # the family estimator prototype
    grids: List[Dict]
    journal: Any = None        # ShardedSweepJournal (or None)
    name: str = ""
    # optional run_sweep-signature callable wrapping the block execution
    # (the selector passes run_sweep behind its transient-RPC
    # RetryPolicy, so distribution keeps the single-device path's
    # fault tolerance); None = plain run_sweep
    run: Any = None


@dataclass
class _Block:
    job: int                   # index into the jobs sequence
    key: Tuple                 # static_signature group key
    idxs: List[int]            # grid indices within the job
    home: int = 0              # worker the block was dealt to
    pred_s: Optional[float] = None  # cost-model predicted seconds


@dataclass
class WorkerStats:
    worker: int
    blocks: int = 0
    steals: int = 0
    busy_s: float = 0.0
    idle_s: float = 0.0
    retired: Optional[str] = None   # worker-level failure, if any


@dataclass
class SchedulerReport:
    """What the schedule did with the mesh: the measured counterpart of
    the pod-extrapolation's perfect-packing assumption."""

    n_workers: int = 0
    wall_s: float = 0.0
    blocks: int = 0
    steals: int = 0
    requeues: int = 0
    utilization_frac: float = 0.0
    straggler: Optional[int] = None
    workers: List[WorkerStats] = field(default_factory=list)
    # pod tier (HostScheduler runs only): host id + lease-table traffic
    pod: Optional[Dict[str, Any]] = None

    def to_json(self) -> Dict[str, Any]:
        out = {
            "n_workers": self.n_workers,
            "wall_s": round(self.wall_s, 6),
            "blocks": self.blocks,
            "steals": self.steals,
            "requeues": self.requeues,
            "utilization_frac": round(self.utilization_frac, 4),
            "straggler": self.straggler,
            "workers": [{
                "worker": w.worker, "blocks": w.blocks, "steals": w.steals,
                "busy_s": round(w.busy_s, 6), "idle_s": round(w.idle_s, 6),
                "retired": w.retired} for w in self.workers],
        }
        if self.pod is not None:
            out["pod"] = dict(self.pod)
        return out


class GridScheduler:
    """Schedule grid blocks across the sweep axis of a device mesh.

    `on_worker_death` governs a worker-level **Exception** at the claim
    site (`scheduler.worker_block`): ``"requeue"`` (default) retires the
    worker and requeues its block for the survivors to steal. A
    **BaseException** (InjectedKill, KeyboardInterrupt — preemption
    semantics) always takes the whole schedule down via the drain path
    regardless of this setting.
    """

    def __init__(self, mesh=None, n_workers: Optional[int] = None,
                 on_worker_death: str = "requeue", pod=None):
        import jax
        if on_worker_death not in ("requeue", "abort"):
            raise ValueError(f"on_worker_death={on_worker_death!r}")
        self.mesh = mesh
        self.on_worker_death = on_worker_death
        # pod tier: a parallel.pod.PodCoordinator makes this one HOST's
        # scheduler in a multi-host sweep — workers CAS-acquire each
        # block fleet-wide before running it (see HostScheduler)
        self.pod = pod
        if mesh is not None:
            rows = np.asarray(mesh.devices)
            names = list(getattr(mesh, "axis_names", ()) or ())
            if SWEEP_AXIS in names and names.index(SWEEP_AXIS) != 0:
                # Workflow.train(mesh=) accepts any user mesh, e.g. axes
                # ("data", "sweep"): lanes are rows of the sweep axis by
                # NAME — axis order must not silently invert the layout
                rows = np.moveaxis(rows, names.index(SWEEP_AXIS), 0)
            if rows.ndim == 1:
                rows = rows[:, None]
            elif rows.ndim > 2:  # >2-D user mesh: flatten non-sweep axes
                rows = rows.reshape(rows.shape[0], -1)
            self._rows = [rows[k] for k in range(rows.shape[0])]
        else:
            self._rows = [np.asarray([d]) for d in jax.devices()[:1]]
        if n_workers is not None:
            if n_workers < 1:
                raise ValueError("n_workers must be >= 1")
            # fewer lanes than sweep rows: use the first n rows (the
            # remaining devices serve data-parallel duty only)
            self._rows = self._rows[:n_workers]
        self.n_workers = len(self._rows)
        self.report = SchedulerReport(n_workers=self.n_workers)
        # shared queue state
        self._cond = threading.Condition()
        self._queues: List[deque] = []
        self._inflight = 0
        self._abort_exc: Optional[BaseException] = None
        self._job_errors: Dict[int, Exception] = {}
        # pod-mode plan identity: _Block id -> fleet block key, and back
        self._block_keys: Dict[int, str] = {}
        self._blocks_by_key: Dict[str, "_Block"] = {}
        self._pod_finished = False  # guarded-by: self._cond
        self._placed: Dict[int, Tuple[Any, Any, Any, Any]] = {}
        self._place_lock = threading.Lock()
        # per-worker (1, data) sub-meshes, built once: _place tests this
        # on every block, and a lane's topology is fixed for the
        # scheduler's lifetime
        self._submeshes = [self._build_submesh(k)
                           for k in range(self.n_workers)]

    # -- device topology --------------------------------------------------- #

    def _device(self, k: int):
        return self._rows[k][0]

    def _build_submesh(self, k: int):
        """Worker k's (1, data) sub-mesh when its sweep row holds more
        than one device (data-parallel fits inside the lane)."""
        if self.mesh is None or len(self._rows[k]) <= 1:
            return None
        from jax.sharding import Mesh
        return Mesh(np.asarray(self._rows[k])[None, :],
                    (SWEEP_AXIS, DATA_AXIS))

    def _submesh(self, k: int):
        return self._submeshes[k]

    def _place(self, k: int, X, y):
        """Pin the training arrays to worker k's device ONCE (committed
        inputs drag the whole block's execution onto the lane's device —
        uncommitted inputs would silently serialize every lane onto the
        default device). Data-parallel lanes skip this: `run_sweep`'s
        mesh path shards the rows itself. The cache RETAINS the keying
        objects and compares identity on BOTH inputs — an id()-only key
        could false-hit after GC address reuse, or return a stale y for
        a reused scheduler instance."""
        import jax
        if self._submesh(k) is not None:
            return X, y
        with self._place_lock:
            hit = self._placed.get(k)
            if hit is not None and hit[0] is X and hit[1] is y:
                return hit[2], hit[3]
        dev = self._device(k)
        Xk = jax.device_put(X, dev)
        yk = jax.device_put(y, dev)
        with self._place_lock:
            self._placed[k] = (X, y, Xk, yk)
        return Xk, yk

    # -- scheduling -------------------------------------------------------- #

    def run(self, jobs: Sequence[SweepJob], X, y, folds, evaluator,
            ctx) -> List[Any]:
        """Execute every job's sweep across the mesh. Returns one outcome
        per job: the [grid][fold] metric matrix, or the Exception that
        failed the family (the caller applies its family-drop policy).
        A BaseException (preemption) drains in-flight blocks on the
        surviving lanes, then re-raises."""
        import jax  # noqa: F401  (workers need an initialized backend)

        results: List[List[Optional[List[float]]]] = [
            [None] * len(j.grids) for j in jobs]
        self._job_errors = {}

        # resume: the merged journal shards are the shared completion
        # log — blocks any worker completed in a previous (or killed)
        # schedule never re-run (shared resume-skip implementation with
        # the in-family path)
        for ji, job in enumerate(jobs):
            journal_prefill(job.journal, job.grids, results[ji])

        blocks: List[_Block] = []
        for ji, job in enumerate(jobs):
            groups: Dict[Tuple, List[int]] = {}
            for i, g in enumerate(job.grids):
                if results[ji][i] is None:
                    groups.setdefault(
                        static_signature(job.est, g), []).append(i)
            blocks += [_Block(ji, key, idxs) for key, idxs in groups.items()]
        blocks = self._plan(blocks, X, y, folds)

        self._queues = [deque() for _ in range(self.n_workers)]
        if any(b.pred_s is None for b in blocks):
            # cold cost model: count-LPT + round-robin deal — today's
            # heuristic, bit for bit
            for bi, blk in enumerate(blocks):
                blk.home = bi % self.n_workers
                self._queues[blk.home].append(blk)
        else:
            # warm model: TRUE LPT — each block (longest predicted
            # first) lands on the least-loaded lane, so the packing is
            # driven by predicted seconds instead of config counts
            loads = [0.0] * self.n_workers
            for blk in blocks:
                k = min(range(self.n_workers), key=lambda j: (loads[j], j))
                blk.home = k
                self._queues[k].append(blk)
                loads[k] += blk.pred_s or 0.0
        self._inflight = 0
        self._abort_exc = None
        self._pod_finished = False  # guarded-by: self._cond (pre-start reset)
        self._placed = {}  # drop a previous run's pinned device buffers
        self.report = SchedulerReport(
            n_workers=self.n_workers, blocks=len(blocks),
            workers=[WorkerStats(worker=k) for k in range(self.n_workers)])

        self._block_keys, self._blocks_by_key = {}, {}
        if self.pod is not None:
            from transmogrifai_tpu.parallel.pod import block_key
            for ji, job in enumerate(jobs):
                if job.journal is None:
                    raise ValueError(
                        "pod scheduling requires a journal per job: the "
                        "shards are the cross-host completion log")
            for blk in blocks:
                bkey = block_key(blk.job, blk.key, blk.idxs)
                self._block_keys[id(blk)] = bkey
                self._blocks_by_key[bkey] = blk
            # every host registers the same deterministic plan; first
            # writer wins per key, so the table converges to the union
            self.pod.register(sorted(self._blocks_by_key))
            self.pod.start()

        t0 = time.perf_counter()
        try:
            with TRACER.span("sweep:scheduler", category="scheduler",
                             workers=self.n_workers, blocks=len(blocks),
                             jobs=len(jobs)) as root:
                worker_ctxs = [self._worker_ctx(k, ctx)
                               for k in range(self.n_workers)]
                threads = [
                    threading.Thread(
                        target=self._worker_loop,
                        args=(k, root, jobs, results, worker_ctxs[k],
                              X, y, folds, evaluator),
                        name=f"sweep-worker-{k}", daemon=True)
                    for k in range(self.n_workers)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                self.report.wall_s = time.perf_counter() - t0
                self._rollup(root)
        finally:
            if self.pod is not None:
                self.pod.stop()
        if self._abort_exc is not None:
            raise self._abort_exc
        leftover = sum(len(q) for q in self._queues)
        if leftover:
            raise RuntimeError(
                f"all {self.n_workers} sweep workers retired with "
                f"{leftover} grid blocks unfinished")
        if self.pod is not None:
            self._pod_fill(jobs, results)
        return [self._job_errors.get(ji, results[ji])
                for ji in range(len(jobs))]

    def _pod_fill(self, jobs: Sequence[SweepJob], results) -> None:
        """Fill the rows OTHER hosts computed: re-merge their journal
        shards from the shared store (the cross-host completion log —
        `complete()` is ordered after the records are durable, so a
        done block's rows are readable by now) and prefill exactly like
        a resume; the JSON float round trip keeps the winner
        bit-identical to a single-host run. A family that failed
        fleet-wide surfaces as that job's error, mirroring the local
        family-drop policy."""
        for ji, job in enumerate(jobs):
            if hasattr(job.journal, "refresh"):
                job.journal.refresh()
            # "pod_merge", not "journal_resume": these blocks were run
            # by OTHER hosts this run — fleet work, not resume savings
            journal_prefill(job.journal, job.grids, results[ji],
                            event="pod_merge")
        snap = self.pod.snapshot()
        for ji in range(len(jobs)):
            if ji in self._job_errors:
                continue
            missing = [i for i, row in enumerate(results[ji])
                       if row is None]
            if not missing:
                continue
            failed = [b for key, b in snap.items()
                      if b.get("state") == "failed"
                      and key in self._blocks_by_key
                      and self._blocks_by_key[key].job == ji]
            if failed:
                self._job_errors[ji] = RuntimeError(
                    f"sweep family failed fleet-wide on host "
                    f"{failed[0].get('owner')}: {failed[0].get('error')}")
            else:
                raise RuntimeError(
                    f"pod sweep: job {ji} still missing {len(missing)} "
                    "grid rows after the fleet drained (done block "
                    "without journal records?)")

    def _plan(self, blocks: List[_Block], X, y, folds) -> List[_Block]:
        """Order (and, with a warm cost model, size) the grid blocks.

        Cold model (empty corpus / disabled): EXACTLY today's heuristic
        — blocks sorted by config count, longest-first, deterministic
        tie-break (`pred_s` stays None and the caller deals
        round-robin). Warm model: every block gets a predicted wall
        time from `perf` block features; blocks predicted far past the
        seconds-per-block target are SPLIT into narrower sub-blocks
        (same static signature, so each part still compiles as one
        batched program — the same regrouping a journal resume already
        exercises), then sorted by predicted seconds for true-LPT
        packing. A single cold block degrades the WHOLE plan to the
        count heuristic: half-predicted orderings are worse than
        either."""
        count_key = lambda b: (-len(b.idxs), b.job, repr(b.key))  # noqa: E731
        blocks.sort(key=count_key)
        if not blocks:
            return blocks
        try:
            from transmogrifai_tpu import perf
            model = perf.get_model()
        except Exception:
            model = None
        if model is None:
            return blocks
        n_rows = int(np.shape(y)[0])
        try:
            n_cols = int(X.shape[1])
            dtype_bytes = int(np.dtype(X.dtype).itemsize)
        except (AttributeError, IndexError, TypeError):
            n_cols, dtype_bytes = 0, 4
        n_folds = len(folds)
        for blk in blocks:
            family = blk.key[0] if blk.key else "generic"
            static = blk.key[1] if len(blk.key) > 1 else ()
            p = model.predict("block_runtime", perf.block_features(
                family, static, len(blk.idxs), n_rows, n_cols, n_folds,
                dtype_bytes))
            if p is None:
                for b in blocks:
                    b.pred_s = None
                return blocks
            blk.pred_s = p.value
        # width sizing: a block predicted well past the target makes the
        # tail lane a straggler no steal can fix (blocks are atomic) —
        # split it toward target seconds per block. Only clearly
        # oversize blocks split (2x hysteresis): every extra part is an
        # extra dispatch + journal granularity, and near-target blocks
        # pack fine as-is.
        target = perf.target_block_s()
        sized: List[_Block] = []
        for blk in blocks:
            if target > 0 and blk.pred_s > 2.0 * target \
                    and len(blk.idxs) > 1:
                k = min(len(blk.idxs),
                        max(2, int(np.ceil(blk.pred_s / target))))
                step = -(-len(blk.idxs) // k)
                parts = [blk.idxs[i:i + step]
                         for i in range(0, len(blk.idxs), step)]
                frac = 1.0 / len(blk.idxs)
                obs_export.record_event(
                    "block_resize", job=blk.job, configs=len(blk.idxs),
                    parts=len(parts), predicted_s=round(blk.pred_s, 3),
                    target_s=target)
                for part in parts:
                    sized.append(_Block(blk.job, blk.key, part,
                                        pred_s=blk.pred_s * len(part) * frac))
            else:
                sized.append(blk)
        sized.sort(key=lambda b: (-(b.pred_s or 0.0),) + count_key(b))
        return sized

    def _worker_ctx(self, k: int, ctx):
        """Same n_rows and — critically — the SAME seed as the caller's
        context: bootstrap/fold streams must match the single-device
        sweep bit for bit."""
        from transmogrifai_tpu.stages.base import FitContext
        return FitContext(n_rows=getattr(ctx, "n_rows", 0),
                          seed=getattr(ctx, "seed", 42),
                          mesh=self._submesh(k))

    # -- queue protocol ----------------------------------------------------- #

    def _claim(self, k: int) -> Optional[Tuple[_Block, bool]]:
        """Own deque first; otherwise steal from the BACK of the longest
        other deque. Returns None when every deque is empty and nothing
        is in flight (or the schedule is aborting); blocks while other
        lanes still run — a dying lane may requeue its block for us."""
        with self._cond:
            while True:
                if self._abort_exc is not None:
                    return None
                if self._queues[k]:
                    self._inflight += 1
                    return self._queues[k].popleft(), False
                donors = [(len(q), j) for j, q in enumerate(self._queues)
                          if j != k and q]
                if donors:
                    donors.sort(key=lambda p: (-p[0], p[1]))
                    self._inflight += 1
                    return self._queues[donors[0][1]].pop(), True
                if self._inflight == 0:
                    return None
                self._cond.wait(timeout=0.1)

    def _complete(self) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()

    def _requeue(self, blk: _Block) -> None:
        with self._cond:
            self._queues[blk.home].append(blk)
            self._inflight -= 1
            self.report.requeues += 1
            self._cond.notify_all()

    def _abort(self, exc: BaseException) -> None:
        """Preemption: cancel undispatched work so the surviving lanes
        drain only their IN-FLIGHT blocks (journaling them), then the
        schedule re-raises. What was cancelled or in flight on the dead
        lane re-runs on resume via the journal."""
        with self._cond:
            if self._abort_exc is None:
                self._abort_exc = exc
            for q in self._queues:
                q.clear()
            self._inflight -= 1
            self._cond.notify_all()

    def _fail_job(self, ji: int, exc: Exception) -> None:
        with self._cond:
            self._job_errors.setdefault(ji, exc)
            for q in self._queues:  # cancel the family's remaining blocks
                for blk in [b for b in q if b.job == ji]:
                    q.remove(blk)
            self._cond.notify_all()

    # -- worker ------------------------------------------------------------- #

    def _claims(self, k: int, stats: WorkerStats, lane):
        """Yield (block, stolen) claims for lane k until the schedule
        drains, charging wait time to the lane's idle account. In pod
        mode a locally drained lane keeps polling the fleet lease table
        (cross-host stealing) until every block is done fleet-wide."""
        while True:
            t_wait = time.perf_counter()
            claim = self._claim(k)
            if claim is None and self.pod is not None \
                    and not self._pod_over():
                claim = self._pod_takeover(k)
            waited = time.perf_counter() - t_wait
            if waited > 0.002:
                stats.idle_s += waited
                lane.event("idle", waited_s=round(waited, 6))
            if claim is None:
                if self.pod is not None and not self._pod_over():
                    continue  # fleet still has live blocks: poll again
                return
            yield claim

    def _pod_over(self) -> bool:
        with self._cond:
            return self._pod_finished or self._abort_exc is not None

    def _pod_takeover(self, k: int):
        """One fleet poll round for a locally drained lane: claim a
        pool or TTL-expired block (cross-host steal), flag the schedule
        finished when every block is done fleet-wide, or sleep until
        the earliest foreign lease can expire. Returns a (block,
        stolen) claim or None (caller re-polls)."""
        remaining, next_expiry = self.pod.pending()
        if remaining == 0:
            with self._cond:
                self._pod_finished = True
                self._cond.notify_all()
            return None
        key = self.pod.claim_any()
        if key is not None:
            blk = self._blocks_by_key.get(key)
            if blk is None:
                # a key from a DIVERGENT foreign plan (e.g. a different
                # warm cost model split the blocks differently): not
                # ours to run — hand it back to its planner's host
                self.pod.foreign += 1
                self.pod.release(key)
            else:
                with self._cond:
                    if self._abort_exc is None:
                        self._inflight += 1
                        return blk, True
                self.pod.release(key)
                return None
        # everything left is live-leased elsewhere (or foreign): sleep
        # until the earliest lease could expire, woken early by a local
        # requeue/abort notify — TTL-derived, never a blind poll
        delay = 0.05 if next_expiry == float("inf") \
            else min(max(next_expiry, 0.05), self.pod.ttl_s)
        with self._cond:
            if self._abort_exc is None and not self._queues[k]:
                self._cond.wait(timeout=delay)
        return None

    def _worker_loop(self, k: int, root, jobs, results, wctx,
                     X, y, folds, evaluator) -> None:
        stats = self.report.workers[k]
        with TRACER.span(f"sweep:worker:{k}", category="sweep_worker",
                         parent=root, worker=k,
                         devices=int(len(self._rows[k]))) as lane:
            for blk, stolen in self._claims(k, stats, lane):
                job = jobs[blk.job]
                bkey = self._block_keys.get(id(blk)) \
                    if self.pod is not None else None
                if bkey is not None:
                    with self._cond:
                        job_failed = blk.job in self._job_errors
                    if job_failed:
                        # our host already failed this family: propagate
                        # instead of letting the block ping-pong
                        self.pod.fail(bkey, "family failed on this host")
                        self._complete()
                        continue
                    if not self.pod.try_acquire(bkey):
                        # another host owns or finished it: drop the
                        # block locally — its rows arrive at _pod_fill
                        # via the merged journal shards
                        self._complete()
                        continue
                if stolen:
                    stats.steals += 1
                    with self._cond:  # += from N lanes loses increments
                        self.report.steals += 1
                    obs_export.record_event(
                        "steal", worker=k, from_worker=blk.home,
                        job=job.name or type(job.est).__name__,
                        configs=len(blk.idxs))
                try:
                    fault_point(SITE_WORKER_BLOCK)
                except Exception as e:
                    # worker-level failure (the executor died, not the
                    # family): retire this lane, hand the block to the
                    # survivors — the preemption costs one in-flight block
                    stats.retired = f"{type(e).__name__}: {e}"
                    obs_export.record_event(
                        "worker_retired", worker=k, configs=len(blk.idxs))
                    if self.on_worker_death == "abort":
                        self._abort(e)
                        return
                    log.warning("sweep worker %d retired (%s); block "
                                "requeued for stealing", k, e)
                    self._requeue(blk)
                    return
                except BaseException as e:
                    stats.retired = f"{type(e).__name__}: {e}"
                    obs_export.record_event("worker_killed", worker=k,
                                            configs=len(blk.idxs))
                    self._abort(e)
                    return
                t0 = time.perf_counter()
                try:
                    rows = self._run_block(k, job, blk, wctx, X, y, folds,
                                           evaluator)
                except Exception as e:
                    log.error("sweep worker %d: family %s block failed",
                              k, job.name or type(job.est).__name__,
                              exc_info=True)
                    self._fail_job(blk.job, e)
                    if bkey is not None:
                        self.pod.fail(bkey, f"{type(e).__name__}: {e}")
                    self._complete()
                    continue
                except BaseException as e:
                    stats.retired = f"{type(e).__name__}: {e}"
                    obs_export.record_event("worker_killed", worker=k,
                                            configs=len(blk.idxs))
                    self._abort(e)
                    return
                with self._cond:
                    for i, row in zip(blk.idxs, rows):
                        results[blk.job][i] = row
                block_s = time.perf_counter() - t0
                if bkey is not None:
                    # ordered AFTER _run_block: the journal records are
                    # durable, so done-in-the-lease-table implies
                    # readable-by-any-host
                    self.pod.complete(bkey)
                # NOT residual-scored here: the lane's run_sweep already
                # predicts and scores this same block with the same
                # features inside _run_groups_resilient — a second note
                # would double-weight scheduled blocks in the
                # perf_model_abs_rel_err scorecard (blk.pred_s exists
                # for the packing decision, which that residual covers)
                stats.busy_s += block_s
                stats.blocks += 1
                self._complete()

    def _run_block(self, k: int, job: SweepJob, blk: _Block, wctx,
                   X, y, folds, evaluator):
        import jax
        grids = [job.grids[i] for i in blk.idxs]
        journal = None
        if job.journal is not None:
            # pod mode: host-qualified shard ids so two hosts' lane-k
            # workers never share a shard file on the shared store
            tag = k if self.pod is None else f"{self.pod.host}_{k}"
            journal = job.journal.shard(tag)
        Xk, yk = self._place(k, X, y)
        fn = job.run or run_sweep
        with jax.default_device(self._device(k)):
            return fn(job.est, grids, Xk, yk, folds, evaluator,
                      wctx, sharding=None, journal=journal)

    # -- rollup ------------------------------------------------------------- #

    def _rollup(self, root) -> None:
        rep = self.report
        busy = [w.busy_s for w in rep.workers]
        denom = rep.n_workers * max(rep.wall_s, 1e-9)
        rep.utilization_frac = min(1.0, sum(busy) / denom)
        alive = [(b, w.worker) for b, w in zip(busy, rep.workers)
                 if w.retired is None]
        if len(alive) > 1:
            med = float(np.median([b for b, _ in alive]))
            worst_busy, worst = max(alive)  # retired lanes can't straggle
            if med > 0 and worst_busy > 1.5 * med:
                rep.straggler = worst
                obs_export.record_event(
                    "straggler", worker=worst,
                    busy_s=round(worst_busy, 6), median_s=round(med, 6))
        extra: Dict[str, Any] = {}
        if self.pod is not None:
            rep.pod = {"host": self.pod.host, "ttl_s": self.pod.ttl_s,
                       **self.pod.stats()}
            extra = {"host": self.pod.host,
                     "pod_takeovers": self.pod.takeovers,
                     "pod_skips": self.pod.skips}
        obs_export.record_event(
            "mesh_utilization", workers=rep.n_workers,
            utilization_frac=round(rep.utilization_frac, 4),
            steals=rep.steals, requeues=rep.requeues,
            idle_s=round(sum(w.idle_s for w in rep.workers), 6),
            blocks=rep.blocks, wall_s=round(rep.wall_s, 6), **extra)
        root.set(utilization_frac=round(rep.utilization_frac, 4),
                 steals=rep.steals)


class HostScheduler(GridScheduler):
    """One pod host's scheduler tier: the work-stealing `GridScheduler`
    for the host's local lanes plus a `parallel.pod.PodCoordinator`
    claiming every block from the shared lease table before running it.

    K processes (one per host), each constructed over the SAME shared
    `store_root` and `sweep_id` with a unique `host` id, cooperatively
    drain one sweep: blocks distribute by claim-order racing, a drained
    host steals pool/TTL-expired blocks, a killed host's in-flight
    block is TTL-reclaimed by a survivor, and every host returns the
    complete, bit-identical result matrix (its own rows plus the other
    hosts' rows merged from the host-qualified journal shards).

    Determinism note: hosts must compute the same plan — same jobs in
    the same order, and a shared (or equally cold) perf corpus so warm-
    model block splitting agrees. A divergent plan only costs the
    dedupe (both hosts run overlapping blocks; the journal merge still
    converges).
    """

    def __init__(self, store_root: str, host: str, sweep_id: str = "pod",
                 mesh=None, n_workers: Optional[int] = None,
                 on_worker_death: str = "requeue",
                 lease_ttl_s: float = 30.0):
        from transmogrifai_tpu.parallel.pod import PodCoordinator
        super().__init__(mesh=mesh, n_workers=n_workers,
                         on_worker_death=on_worker_death,
                         pod=PodCoordinator(store_root, sweep_id, host,
                                            ttl_s=lease_ttl_s))
