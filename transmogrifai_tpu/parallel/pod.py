"""Pod tier of the sweep scheduler: cross-host block coordination.

One `HostScheduler` process runs per host (parallel/scheduler.py — the
PR-7 work-stealing engine for the host's local lanes); this module is
the thin tier above it, in the shape of the TF distributed runtime
(arxiv 1605.08695): no master process, just a shared lease table on the
`store/` state plane (`store.state.LeaseTable` — `StateCell` CAS with
TTL expiry) that every host's workers claim grid blocks from.

Every host computes the SAME deterministic block plan (same jobs → same
`static_signature` groups → same `block_key`s), registers it
idempotently, and deals all blocks into its local lanes; a worker
CAS-acquires a block fleet-wide right before running it and skips
blocks another host owns or finished. Work distribution is therefore
claim-order racing — the faster host simply acquires more blocks — and
cross-host stealing is the drained host claiming pool or TTL-expired
blocks. A host that dies mid-block stops renewing its lease; when the
TTL passes, a survivor's claim takes the block over, so the preemption
costs the fleet exactly the in-flight block (the PR-7 lane-retirement
unit, now across hosts).

The per-worker journal shards are the cross-host completion log: pod
workers journal under host-qualified shard ids (``<base>-wh0_3.jsonl``)
on the shared store, `complete()` is only called after the block's
journal records are durable, and a drained host re-merges foreign
shards (`ShardedSweepJournal.refresh`) before filling the rows other
hosts computed — winner selection stays bit-identical to single-host
because every row round-trips through the same JSON journal bytes.
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
from typing import Dict, List, Optional, Tuple

from transmogrifai_tpu.store.state import LeaseTable

__all__ = ["PodCoordinator", "block_key"]

_HOST_RE = re.compile(r"^[A-Za-z0-9_]{1,32}$")


def block_key(job: int, sig_key: Tuple, idxs: List[int]) -> str:
    """Deterministic fleet-wide identity of one planned grid block: the
    job index, the static-signature group, and the exact grid indices
    (post-split). Hosts running the same plan derive the same keys; a
    host with a divergent plan (e.g. a different warm cost model) only
    loses the dedupe — the journal merge still dedupes the results."""
    blob = json.dumps([job, repr(sig_key), sorted(int(i) for i in idxs)])
    return f"j{job}." + hashlib.sha256(blob.encode()).hexdigest()[:12]


class PodCoordinator:
    """One host's handle on the shared block lease table.

    Wraps `LeaseTable` with the scheduler's idioms: host-idempotent
    acquire (two lanes of one host may pass the same requeued block),
    a background lease renewer so blocks longer than the TTL are not
    torn from a live host, and failure propagation (a family that
    fails on one host marks its blocks ``failed`` so the fleet applies
    the same family-drop policy instead of ping-ponging the block).
    """

    def __init__(self, root: str, sweep_id: str, host: str,
                 ttl_s: float = 30.0) -> None:
        if not _HOST_RE.match(host):
            raise ValueError(f"illegal pod host id: {host!r} "
                             "(need [A-Za-z0-9_]+, it names journal shards)")
        self.host = host
        self.ttl_s = float(ttl_s)
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", sweep_id)[:80] or "sweep"
        self.table = LeaseTable(root, safe, owner=host, ttl_s=ttl_s)
        self._lock = threading.Lock()
        self._held: set = set()          # guarded-by: self._lock
        self._stop = threading.Event()
        self._renewer: Optional[threading.Thread] = None
        self.skips = 0                   # blocks another host owned/finished
        self.foreign = 0                 # claimed keys outside our plan
        self.renew_errors = 0            # CAS bursts the renewer rode out

    # -- lifecycle --------------------------------------------------------- #

    def register(self, keys: List[str]) -> None:
        self.table.register(keys)

    def start(self) -> None:
        """Start the lease renewer (idempotent)."""
        with self._lock:
            if self._renewer is not None:
                return
            self._stop.clear()
            self._renewer = threading.Thread(
                target=self._renew_loop, name=f"pod-renew-{self.host}",
                daemon=True)
            self._renewer.start()

    def stop(self) -> None:
        with self._lock:
            t, self._renewer = self._renewer, None
        self._stop.set()
        if t is not None:
            t.join(timeout=5.0)

    def _renew_loop(self) -> None:
        # interval-paced on the TTL (never a blind poll): renew held
        # leases at a third of their expiry so one missed beat — a GC
        # pause, a slow CAS round — still leaves two chances before a
        # survivor is allowed to tear the block away
        while not self._stop.wait(self.ttl_s / 3.0):
            with self._lock:
                held = list(self._held)
            for key in held:
                try:
                    if not self.table.renew(key):
                        # TTL takeover revoked us: the block re-runs
                        # elsewhere; our journal append (if any) merges
                        # harmlessly — records are keyed by config
                        with self._lock:
                            self._held.discard(key)
                except Exception:
                    # CAS contention burst: the lease still has ~2/3 of
                    # its TTL, so count it and let the next beat retry
                    self.renew_errors += 1
                    continue

    # -- claims ------------------------------------------------------------ #

    def try_acquire(self, key: str) -> bool:
        """Acquire `key` for this host right before running it. True for
        a pool block, a TTL-expired foreign lease, or a lease this host
        already holds (requeue-within-host); False when another host
        owns it live or it is already done/failed — the caller drops
        the block locally."""
        status = self.table.acquire(key, meta=self._lease_meta())
        if status in ("acquired", "takeover", "held"):
            with self._lock:
                self._held.add(key)
            return True
        self.skips += 1
        return False

    def claim_any(self, prefer: Optional[List[str]] = None) -> Optional[str]:
        """Cross-host steal: claim any pool or expired block."""
        key = self.table.claim(prefer=prefer, meta=self._lease_meta())
        if key is not None:
            with self._lock:
                self._held.add(key)
        return key

    @staticmethod
    def _lease_meta() -> Optional[Dict[str, str]]:
        """Ambient trace context stamped into the lease record: when a
        sweep lane claims a block under a sampled request/sweep span,
        the lease carries the W3C ``traceparent``, so the fleet trace
        merge (obs/federate.py) can attribute remote block work to the
        driving trace. None (no stamp) outside any span."""
        try:
            from transmogrifai_tpu.obs.trace import ambient_traceparent
            tp = ambient_traceparent()
        except Exception:
            return None
        return {"traceparent": tp} if tp else None

    def complete(self, key: str) -> None:
        """Mark `key` done fleet-wide. Callers MUST have made the
        block's journal records durable first — done is the signal a
        drained host trusts before merging shards."""
        with self._lock:
            self._held.discard(key)
        self.table.complete(key)

    def release(self, key: str) -> None:
        with self._lock:
            self._held.discard(key)
        self.table.release(key)

    def fail(self, key: str, error: str) -> None:
        """Mark `key` failed fleet-wide (family-level error): every host
        applies its family-drop policy instead of re-running the block."""
        with self._lock:
            self._held.discard(key)
        self.table.fail(key, error)

    # -- reads ------------------------------------------------------------- #

    def pending(self) -> Tuple[int, float]:
        return self.table.pending()

    def snapshot(self) -> Dict[str, Dict]:
        return self.table.snapshot()

    @property
    def takeovers(self) -> int:
        return self.table.takeovers

    def stats(self) -> Dict[str, int]:
        return {"takeovers": self.table.takeovers, "skips": self.skips,
                "cas_rounds": self.table.cas_rounds,
                "foreign": self.foreign,
                "renew_errors": self.renew_errors}
