"""Content-addressed artifact store over a pluggable backend.

Every replica-portable artifact the fleet produces — feature-cache wire
tapes, warmup manifests, perf-corpus shards — commits through ONE store
so a second replica's cold start is artifact replay instead of rebuild.
The durability story is the PR-4/PR-6 staged-dir protocol reused, not
reimplemented: payload files are staged and fsynced, the sha256 manifest
(`artifact.json`) is written LAST, and `runtime/integrity.commit_staged_dir`
swaps the directory into place — a crash at any instruction leaves the
previous artifact or the new one, never a torn mix. Readers verify
against the manifest and raise a structured `StoreCorruptError`;
consumers treat it as a miss and rebuild (never serve from a torn tape).

Tier-0 backend is a directory on shared storage (`LocalDirBackend`); the
`Backend` surface is deliberately small (path/commit/remove/keys) so an
object-store tier can slot in by materializing artifacts to a local
scratch dir behind the same `ArtifactStore.get`.

Multi-TB hygiene lives here too: `gc()` applies TTL then LRU eviction
(last-access touch files kept OUTSIDE the sealed artifact, like
warmup.json, so access tracking never invalidates a manifest), and
`prefetch()` streams an artifact's wire tape through the page cache —
and through sha256 — on a named background thread ahead of its first
consumer read.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from transmogrifai_tpu.runtime.integrity import (
    commit_staged_dir, fsync_dir, fsync_file, sha256_file)

__all__ = [
    "MANIFEST",
    "STORE_VERSION",
    "StoreCorruptError",
    "ArtifactInfo",
    "Backend",
    "LocalDirBackend",
    "ArtifactStore",
]

log = logging.getLogger(__name__)

MANIFEST = "artifact.json"
STORE_VERSION = 1

# keys are content digests or slugs — path-safe by construction, but the
# backend enforces it so a hostile key can never escape the root
_KEY_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,200}$")

# access-time sidecar dir at the store root; one empty touch file per
# key whose mtime is the LRU clock (kept off the sealed artifact dirs)
_ACCESS_DIR = ".access"
_GC_DIR = ".gc"


class StoreCorruptError(RuntimeError):
    """An artifact failed integrity verification. Structured so callers
    can log WHAT failed and fall back to a rebuild instead of serving
    from a torn tape."""

    def __init__(self, path: str, reason: str,
                 key: Optional[str] = None) -> None:
        super().__init__(f"corrupt artifact at {path}: {reason}")
        self.path = path
        self.reason = reason
        self.key = key


@dataclass
class ArtifactInfo:
    key: str
    path: str
    bytes: int
    created: float
    files: int
    meta: Dict[str, Any]


class Backend:
    """Placement + atomic publish/remove for one artifact namespace.

    Implementations must make `commit` atomic (all-or-nothing publish of
    a fully staged dir) and `remove` crash-safe (a half-removed artifact
    must never look present). Everything content-related — manifests,
    hashing, verification, eviction policy — stays in `ArtifactStore`.
    """

    name = "base"

    def path_of(self, key: str) -> str:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def commit(self, staged_dir: str, key: str) -> str:
        raise NotImplementedError

    def remove(self, key: str) -> None:
        raise NotImplementedError

    def keys(self) -> List[str]:
        raise NotImplementedError


class LocalDirBackend(Backend):
    """Tier-0: a directory on local or shared (NFS-style) storage."""

    name = "localdir"

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(os.path.expanduser(root))

    def path_of(self, key: str) -> str:
        if not _KEY_RE.match(key):
            raise ValueError(f"illegal artifact key: {key!r}")
        return os.path.join(self.root, key)

    def exists(self, key: str) -> bool:
        return os.path.isfile(os.path.join(self.path_of(key), MANIFEST))

    def commit(self, staged_dir: str, key: str) -> str:
        final = self.path_of(key)
        commit_staged_dir(staged_dir, final)
        return final

    def remove(self, key: str) -> None:
        # rename aside first: a crash mid-rmtree leaves the victim in
        # .gc/ (invisible to exists/keys) instead of half-deleted in
        # place; the next gc() sweep finishes the job
        path = self.path_of(key)
        if not os.path.isdir(path):
            return
        aside = os.path.join(self.root, _GC_DIR,
                             f"{key}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(os.path.dirname(aside), exist_ok=True)
        try:
            os.rename(path, aside)
        except OSError:
            return  # lost a remove race — the other remover owns it
        shutil.rmtree(aside, ignore_errors=True)
        fsync_dir(self.root)

    def keys(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n for n in names
                      if _KEY_RE.match(n) and self.exists(n))


class ArtifactStore:
    """get/put/stat over a backend, with verification, GC and prefetch."""

    def __init__(self, backend: Backend, registry=None,
                 ttl_s: Optional[float] = None,
                 max_bytes: Optional[int] = None) -> None:
        self.backend = backend
        self.ttl_s = ttl_s
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._inflight: Dict[str, threading.Thread] = {}  # guarded-by: self._lock
        self._prefetched: Dict[str, Optional[str]] = {}  # guarded-by: self._lock
        if registry is None:
            from transmogrifai_tpu.obs.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        b = backend.name
        self._m_hit = registry.counter(
            "store_hits_total", "artifact store verified hits", backend=b)
        self._m_miss = registry.counter(
            "store_misses_total", "artifact store misses", backend=b)
        self._m_corrupt = registry.counter(
            "store_corrupt_total", "artifacts rejected by verification",
            backend=b)
        self._m_put = registry.counter(
            "store_puts_total", "artifacts committed", backend=b)
        self._m_put_bytes = registry.counter(
            "store_put_bytes_total", "payload bytes committed", backend=b)
        self._m_evict = registry.counter(
            "store_evicted_total", "artifacts evicted by gc", backend=b)
        self._m_prefetch = registry.counter(
            "store_prefetch_total", "artifacts streamed by prefetch",
            backend=b)

    # -- write path ------------------------------------------------------ #

    def put(self, key: str, stage: Callable[[str], None],
            meta: Optional[Dict[str, Any]] = None) -> str:
        """Stage payload files via `stage(tmp_dir)`, seal and publish.

        The store is the only legal writer into the namespace (lint
        L020): it fsyncs every staged file, writes the sha256 manifest
        LAST, and commits through the staged-dir rename protocol.
        """
        final = self.backend.path_of(key)
        parent = os.path.dirname(final) or "."
        os.makedirs(parent, exist_ok=True)
        # dot-prefixed staging name: invisible to keys()/gc() until the
        # atomic rename publishes it under the real key
        tmp = os.path.join(
            parent, f".stage-{key}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        os.makedirs(tmp)
        try:
            stage(tmp)
            self.seal_and_commit(key, tmp, meta)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final

    def seal_and_commit(self, key: str, staged_dir: str,
                        meta: Optional[Dict[str, Any]] = None) -> str:
        """Tail of `put` for writers that staged files themselves (the
        feature-cache ArtifactWriter streams chunks into the staging dir
        before handing it over). Manifest goes in LAST, then the atomic
        swap."""
        files: Dict[str, Dict[str, Any]] = {}
        total = 0
        for name in sorted(os.listdir(staged_dir)):
            p = os.path.join(staged_dir, name)
            if not os.path.isfile(p) or name == MANIFEST:
                continue
            fsync_file(p)
            size = os.path.getsize(p)
            files[name] = {"sha256": sha256_file(p), "bytes": size}
            total += size
        manifest = dict(meta or {})
        manifest.update({
            "store_version": STORE_VERSION,
            "key": key,
            "created": time.time(),
            "files": files,
        })
        mpath = os.path.join(staged_dir, MANIFEST)
        with open(mpath, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=1, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        final = self.backend.commit(staged_dir, key)
        with self._lock:
            self._prefetched.pop(key, None)
        self._m_put.inc()
        self._m_put_bytes.inc(total)
        self._touch(key)
        return final

    # -- read path ------------------------------------------------------- #

    def manifest(self, key: str) -> Dict[str, Any]:
        """Parsed manifest, with the structural checks every reader
        needs (valid JSON, key match, files table)."""
        path = self.backend.path_of(key)
        mpath = os.path.join(path, MANIFEST)
        try:
            with open(mpath, "r", encoding="utf-8") as fh:
                manifest = json.load(fh)
        except FileNotFoundError:
            raise StoreCorruptError(path, "manifest missing", key)
        except (OSError, ValueError) as e:
            raise StoreCorruptError(path, f"manifest unreadable: {e}", key)
        if not isinstance(manifest, dict):
            raise StoreCorruptError(path, "manifest is not an object", key)
        if manifest.get("key", key) != key:
            raise StoreCorruptError(
                path, f"key mismatch: manifest says "
                f"{manifest.get('key')!r}", key)
        if not isinstance(manifest.get("files"), dict):
            raise StoreCorruptError(path, "manifest has no files table", key)
        return manifest

    def stat(self, key: str) -> Optional[ArtifactInfo]:
        """Cheap existence + shape probe (no hashing); None when absent,
        StoreCorruptError when present but structurally broken."""
        if not self.backend.exists(key):
            return None
        manifest = self.manifest(key)
        files = manifest["files"]
        meta = {k: v for k, v in manifest.items()
                if k not in ("files", "key", "store_version", "created")}
        return ArtifactInfo(
            key=key, path=self.backend.path_of(key),
            bytes=sum(int(f.get("bytes", 0)) for f in files.values()),
            created=float(manifest.get("created", 0.0)),
            files=len(files), meta=meta)

    def get(self, key: str, verify: bool = True) -> Optional[str]:
        """Local path of a verified artifact, or None on miss.

        verify=True re-hashes every payload file against the manifest;
        verify=False checks existence + sizes only (the feature cache's
        `verify="auto"` warm path). A prefetch in flight for the key is
        joined first — its streaming read already paid for the hashes,
        so a verified prefetch upgrades this get to the cheap path.
        """
        if not self.backend.exists(key):
            self._m_miss.inc()
            return None
        with self._lock:
            thread = self._inflight.get(key)
        if thread is not None:
            thread.join()
        with self._lock:
            # consume the marker: a prefetch vouches for exactly ONE
            # read — later gets re-verify (the tape may have rotted
            # since)
            pre = self._prefetched.pop(key, False)
        if pre not in (False, None):  # prefetch found corruption
            self._m_corrupt.inc()
            raise StoreCorruptError(self.backend.path_of(key), pre, key)
        path = self.backend.path_of(key)
        manifest = self.manifest(key)
        for name, entry in manifest["files"].items():
            p = os.path.join(path, name)
            if not os.path.isfile(p):
                self._m_corrupt.inc()
                raise StoreCorruptError(path, f"missing file {name}", key)
            size = os.path.getsize(p)
            if size != int(entry.get("bytes", -1)):
                self._m_corrupt.inc()
                raise StoreCorruptError(
                    path, f"{name} truncated or resized: {size} bytes on "
                    f"disk, {entry.get('bytes')} recorded", key)
            if verify and pre is not None:  # None == prefetch verified it
                if sha256_file(p) != entry.get("sha256"):
                    self._m_corrupt.inc()
                    raise StoreCorruptError(
                        path, f"checksum mismatch for {name}", key)
        self._m_hit.inc()
        self._touch(key)
        return path

    def delete(self, key: str) -> None:
        self.backend.remove(key)
        with self._lock:
            self._prefetched.pop(key, None)
        self._drop_touch(key)

    def keys(self) -> List[str]:
        return self.backend.keys()

    # -- prefetch -------------------------------------------------------- #

    def prefetch(self, key: str) -> Optional[threading.Thread]:
        """Stream an artifact's payload through the page cache (and
        through sha256) on a named daemon thread, ahead of its first
        consumer read. `get` joins the stream and skips re-hashing.
        Returns the thread, or None when the artifact is absent."""
        if not self.backend.exists(key):
            return None
        with self._lock:
            thread = self._inflight.get(key)
            if thread is not None:
                return thread
            thread = threading.Thread(
                target=self._prefetch_run, args=(key,),
                name=f"store-prefetch-{key[:16]}", daemon=True)
            self._inflight[key] = thread
        thread.start()
        return thread

    def _prefetch_run(self, key: str) -> None:
        verdict: Optional[str] = None  # None == verified clean
        try:
            path = self.backend.path_of(key)
            manifest = self.manifest(key)
            for name, entry in manifest["files"].items():
                p = os.path.join(path, name)
                if (not os.path.isfile(p)
                        or os.path.getsize(p) != int(entry.get("bytes", -1))):
                    verdict = f"missing or short file {name}"
                    break
                if sha256_file(p) != entry.get("sha256"):
                    verdict = f"checksum mismatch for {name}"
                    break
            else:
                self._m_prefetch.inc()
        except StoreCorruptError as e:
            verdict = e.reason
        except OSError as e:
            verdict = f"unreadable during prefetch: {e}"
        finally:
            with self._lock:
                self._prefetched[key] = verdict
                self._inflight.pop(key, None)

    # -- eviction / GC --------------------------------------------------- #

    def _touch_path(self, key: str) -> str:
        root = getattr(self.backend, "root", None)
        if root is None:
            return ""
        return os.path.join(root, _ACCESS_DIR, key)

    def _touch(self, key: str) -> None:
        p = self._touch_path(key)
        if not p:
            return
        try:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            with open(p, "a"):
                os.utime(p, None)
        except OSError:
            log.debug("store access touch failed for %s", key)

    def _drop_touch(self, key: str) -> None:
        p = self._touch_path(key)
        if p:
            try:
                os.unlink(p)
            except OSError:
                pass

    def _last_access(self, key: str, info: ArtifactInfo) -> float:
        p = self._touch_path(key)
        if p:
            try:
                return os.path.getmtime(p)
            except OSError:
                pass
        return info.created

    def gc(self, ttl_s: Optional[float] = None,
           max_bytes: Optional[int] = None) -> Dict[str, Any]:
        """TTL sweep, then LRU eviction down to the byte budget.

        Last access comes from the touch sidecars (falling back to the
        manifest's created stamp), so a replica that keeps replaying a
        tape keeps it resident while one-shot artifacts age out. Also
        finishes any half-removed victims left in `.gc/` by a crashed
        remover.
        """
        ttl_s = self.ttl_s if ttl_s is None else ttl_s
        max_bytes = self.max_bytes if max_bytes is None else max_bytes
        t0 = time.monotonic()
        root = getattr(self.backend, "root", None)
        if root:
            shutil.rmtree(os.path.join(root, _GC_DIR), ignore_errors=True)
        entries = []
        evicted: List[str] = []
        for key in self.backend.keys():
            try:
                info = self.stat(key)
            except StoreCorruptError:
                # structurally broken artifacts are dead weight: reclaim
                self.delete(key)
                evicted.append(key)
                continue
            if info is None:
                continue
            entries.append((self._last_access(key, info), info))
        now = time.time()
        live: List = []
        for atime, info in sorted(entries):  # oldest-access first
            if ttl_s is not None and now - atime > ttl_s:
                self.delete(info.key)
                evicted.append(info.key)
            else:
                live.append((atime, info))
        if max_bytes is not None:
            total = sum(info.bytes for _, info in live)
            for atime, info in list(live):
                if total <= max_bytes:
                    break
                self.delete(info.key)
                evicted.append(info.key)
                live.remove((atime, info))
                total -= info.bytes
        self._m_evict.inc(len(evicted))
        return {
            "evicted": evicted,
            "kept": len(live),
            "bytes": sum(info.bytes for _, info in live),
            "gc_s": round(time.monotonic() - t0, 6),
        }
