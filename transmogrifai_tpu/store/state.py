"""CAS-guarded shared mutable state on the artifact store's directory.

Artifacts are immutable; quota balances and SLO burn counters are not.
`StateCell` gives them a lock-free compare-and-swap on any POSIX-ish
shared filesystem: each write publishes a fully written, fsynced temp
file under the NEXT version number via `os.link` — link creation is
atomic and fails with EEXIST when another replica claimed that version
first, which IS the CAS failure. Readers take the highest parseable
version (a reader can never observe a torn value, because the link only
ever exposes complete files). Old versions are pruned behind a keep
window so the cell stays O(1) on disk.

`SharedQuota` builds the K-replica tenant invariant on top: one shared
token balance per tenant, refilled by wall clock at CAS time, from
which each replica WITHDRAWS a lease (a fraction of the burst budget)
and spends it locally per-request. The shared balance is only touched
when a lease runs dry, so admission stays a local counter decrement in
the hot path — no per-request round trip — while the sum of what K
replicas can admit between syncs stays bounded by the one shared
refill rate.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from transmogrifai_tpu.runtime.integrity import fsync_dir

__all__ = ["StateCell", "SharedQuota", "LeaseTable"]

log = logging.getLogger(__name__)

_CELL_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,120}$")
_KEEP_VERSIONS = 4


class StateCell:
    """A named JSON value with filesystem compare-and-swap."""

    def __init__(self, root: str, name: str) -> None:
        if not _CELL_RE.match(name):
            raise ValueError(f"illegal state cell name: {name!r}")
        self.dir = os.path.join(os.path.abspath(os.path.expanduser(root)),
                                "state")
        self.name = name

    def _version_path(self, version: int) -> str:
        return os.path.join(self.dir, f"{self.name}.v{version}.json")

    def _versions(self) -> List[int]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        prefix = f"{self.name}.v"
        for n in names:
            if n.startswith(prefix) and n.endswith(".json"):
                try:
                    out.append(int(n[len(prefix):-len(".json")]))
                except ValueError:
                    continue
        return sorted(out)

    def read(self) -> Tuple[int, Optional[Any]]:
        """(version, value) of the newest parseable version; (0, None)
        for a never-written cell. Values are complete by construction
        (link-published), but a version written by a crashed process
        before its fsync landed could in principle read short after a
        power cut — fall back one version instead of failing."""
        for version in reversed(self._versions()):
            try:
                with open(self._version_path(version), "r",
                          encoding="utf-8") as fh:
                    return version, json.load(fh)
            except (OSError, ValueError):
                continue
        return 0, None

    def try_write(self, version: int, value: Any) -> bool:
        """Publish `value` as version `version + 1`. False = CAS lost
        (someone else claimed the version) — re-read and retry."""
        os.makedirs(self.dir, exist_ok=True)
        target = self._version_path(version + 1)
        tmp = os.path.join(
            self.dir,
            f".{self.name}-{os.getpid()}-{uuid.uuid4().hex[:8]}.tmp")
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(value, fh)
                fh.flush()
                os.fsync(fh.fileno())
            try:
                os.link(tmp, target)  # atomic claim-or-fail
            except FileExistsError:
                return False
            except OSError as e:
                # no hardlink support on this filesystem: O_EXCL create
                # + byte copy is the degraded path (claim is still
                # atomic; the value was already durable in tmp)
                log.debug("state cell link failed (%s); O_EXCL fallback", e)
                try:
                    fd = os.open(target,
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    return False
                with os.fdopen(fd, "w", encoding="utf-8") as fh:
                    json.dump(value, fh)
                    fh.flush()
                    os.fsync(fh.fileno())
            fsync_dir(self.dir)
            self._prune(version + 1)
            return True
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _prune(self, latest: int) -> None:
        for version in self._versions():
            if version <= latest - _KEEP_VERSIONS:
                try:
                    os.unlink(self._version_path(version))
                except OSError:
                    pass

    def update(self, fn: Callable[[Optional[Any]], Any],
               retries: int = 32) -> Any:
        """CAS loop: read, transform, try_write; backs off a few ms on
        contention. Raises RuntimeError if `retries` straight CAS
        losses (K replicas hammering one cell — raise the lease size,
        not the retry count)."""
        for attempt in range(retries):
            version, value = self.read()
            new = fn(value)
            if self.try_write(version, new):
                return new
            time.sleep(min(0.001 * (2 ** min(attempt, 5)), 0.05))
        raise RuntimeError(
            f"state cell {self.name}: CAS contention exceeded "
            f"{retries} retries")


class LeaseTable:
    """TTL-leased work claims on one `StateCell` — the pod scheduler's
    shared block pool.

    The cell value is ``{"blocks": {key: {"state": pool|leased|done,
    "owner": host, "deadline": wall_clock, "attempts": n}}}``. Every
    transition is a CAS transform, so two hosts racing for the same
    block resolve to exactly one owner, and a host that dies mid-block
    simply stops renewing: when its deadline passes, any survivor's
    `claim` takes the block over (attempts increments — the preemption
    costs the fleet that one in-flight block, the same unit PR-7 lane
    retirement costs a single host).

    Wall-clock TTLs assume the hosts' clocks agree to within a fraction
    of `ttl_s` — the same assumption `SharedQuota`'s refill already
    makes on this store.
    """

    def __init__(self, root: str, name: str, owner: str,
                 ttl_s: float = 30.0) -> None:
        self.owner = str(owner)
        self.ttl_s = float(ttl_s)
        self._cell = StateCell(root, f"lease-{name}")
        self.takeovers = 0       # expired-lease claims we performed
        self.cas_rounds = 0      # update() calls (round trips)

    # -- transforms -------------------------------------------------------- #

    @staticmethod
    def _blocks(value: Optional[Any]) -> Dict[str, Dict[str, Any]]:
        if isinstance(value, dict) and isinstance(value.get("blocks"), dict):
            return value["blocks"]
        return {}

    def register(self, keys: List[str]) -> None:
        """Idempotently add `keys` to the pool. Every host registers the
        same deterministic block plan; first writer wins per key, so the
        table converges to the union without coordination."""
        keys = [str(k) for k in keys]

        def transform(value):
            blocks = dict(self._blocks(value))
            for k in keys:
                blocks.setdefault(k, {"state": "pool", "attempts": 0})
            return {"blocks": blocks}

        self._cell.update(transform)
        self.cas_rounds += 1

    def claim(self, prefer: Optional[List[str]] = None,
              meta: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """CAS-claim one block: a pool block, else an EXPIRED lease
        (takeover). `prefer` orders the scan (a host tries its own plan
        slice first, then steals), making claim order deterministic
        under no contention. Returns the claimed key, or None when
        nothing is claimable right now (all leased-and-live or done).

        `meta` (JSON-safe dict) is stamped into the leased block —
        e.g. the claimer's ambient ``traceparent`` so a cross-host
        trace merge can attribute the lease to the request that drove
        it. Merged INSIDE the transform: lease transforms replace
        block dicts wholesale, so the stamp survives CAS retries."""
        got: Dict[str, Any] = {"key": None, "takeover": False}

        def transform(value):
            blocks = dict(self._blocks(value))
            got["key"] = None
            got["takeover"] = False
            now = time.time()
            order = [k for k in (prefer or []) if k in blocks]
            order += [k for k in sorted(blocks) if k not in set(order)]
            for k in order:
                b = blocks[k]
                state = b.get("state")
                expired = (state == "leased"
                           and float(b.get("deadline", 0.0)) < now)
                if state == "pool" or expired:
                    lease = {"state": "leased", "owner": self.owner,
                             "deadline": now + self.ttl_s,
                             "attempts": int(b.get("attempts", 0)) + 1}
                    if meta:
                        lease.update(meta)
                    blocks[k] = lease
                    got["key"] = k
                    got["takeover"] = expired
                    break
            return {"blocks": blocks}

        self._cell.update(transform)
        self.cas_rounds += 1
        if got["takeover"]:
            self.takeovers += 1
        return got["key"]

    def acquire(self, key: str,
                meta: Optional[Dict[str, Any]] = None) -> str:
        """Targeted claim of one block: ``acquired`` (was pool),
        ``takeover`` (expired foreign lease), ``held`` (our own live
        lease, deadline renewed — two lanes of one host may pass the
        same requeued block), ``busy`` (live foreign lease), ``done``,
        ``failed``, or ``missing``. `meta` as in :meth:`claim`."""
        out = {"status": "missing"}

        def transform(value):
            blocks = dict(self._blocks(value))
            b = blocks.get(key)
            if not isinstance(b, dict):
                out["status"] = "missing"
                return {"blocks": blocks}
            now = time.time()
            state = b.get("state")
            if state in ("done", "failed"):
                out["status"] = state
                return {"blocks": blocks}
            if state == "leased":
                live = float(b.get("deadline", 0.0)) >= now
                if live and b.get("owner") != self.owner:
                    out["status"] = "busy"
                    return {"blocks": blocks}
                out["status"] = "held" if b.get("owner") == self.owner \
                    else "takeover"
            else:
                out["status"] = "acquired"
            attempts = int(b.get("attempts", 0))
            if out["status"] != "held":
                attempts += 1
            lease = {"state": "leased", "owner": self.owner,
                     "deadline": now + self.ttl_s,
                     "attempts": attempts}
            if meta:
                lease.update(meta)
            blocks[key] = lease
            return {"blocks": blocks}

        self._cell.update(transform)
        self.cas_rounds += 1
        if out["status"] == "takeover":
            self.takeovers += 1
        return out["status"]

    def fail(self, key: str, error: str) -> bool:
        """Mark our leased block permanently failed (family-level error:
        every host must apply the same family-drop policy rather than
        re-running a block that fails deterministically)."""
        ok = {"v": False}

        def transform(value):
            blocks = dict(self._blocks(value))
            b = blocks.get(key)
            ok["v"] = (isinstance(b, dict) and b.get("state") == "leased"
                       and b.get("owner") == self.owner)
            if ok["v"]:
                blocks[key] = {"state": "failed", "owner": self.owner,
                               "error": str(error)[:500],
                               "attempts": int(b.get("attempts", 0))}
            return {"blocks": blocks}

        self._cell.update(transform)
        self.cas_rounds += 1
        return ok["v"]

    def _transition(self, key: str, state: str) -> bool:
        """Move `key` to `state` iff we still hold its lease (a TTL
        takeover revokes the old owner: its late complete/release must
        not clobber the new owner's claim)."""
        ok = {"v": False}

        def transform(value):
            blocks = dict(self._blocks(value))
            b = blocks.get(key)
            ok["v"] = (isinstance(b, dict) and b.get("state") == "leased"
                       and b.get("owner") == self.owner)
            if ok["v"]:
                nb = {"state": state, "owner": self.owner,
                      "attempts": int(b.get("attempts", 0))}
                if state == "leased":
                    nb["deadline"] = time.time() + self.ttl_s
                elif state == "pool":
                    nb.pop("owner")
                blocks[key] = nb
            return {"blocks": blocks}

        self._cell.update(transform)
        self.cas_rounds += 1
        return ok["v"]

    def renew(self, key: str) -> bool:
        """Extend our lease by `ttl_s`; False = lost to a takeover."""
        return self._transition(key, "leased")

    def complete(self, key: str) -> bool:
        """Mark our leased block done (its journal record is durable)."""
        return self._transition(key, "done")

    def release(self, key: str) -> bool:
        """Return our leased block to the pool (lane-retirement path:
        the block failed locally; let another host run it)."""
        return self._transition(key, "pool")

    # -- reads ------------------------------------------------------------- #

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        _, value = self._cell.read()
        return dict(self._blocks(value))

    def pending(self) -> Tuple[int, float]:
        """(blocks not done, seconds until the earliest live lease
        expires). The second value is what a drained host's wait loop
        sleeps against — TTL-aware, never a blind poll; `inf` when
        nothing is leased (only pool blocks remain: claim immediately)."""
        now = time.time()
        remaining = 0
        next_expiry = float("inf")
        for b in self.snapshot().values():
            state = b.get("state")
            if state in ("done", "failed"):
                continue
            remaining += 1
            if state == "leased":
                next_expiry = min(next_expiry,
                                  float(b.get("deadline", 0.0)) - now)
        return remaining, next_expiry


class SharedQuota:
    """Lease-based cross-replica token budget per tenant.

    The shared cell holds ``{"tokens": float, "ts": wall_clock}`` —
    refill happens inside the CAS transform from the wall-clock delta,
    capped at the burst budget, so K replicas reading concurrently can
    never mint more than `rate * elapsed` between them. A replica
    withdraws ``lease_frac * burst`` tokens at a time and spends the
    lease locally; `try_spend` is the hot-path call and only goes to the
    shared cell when the local lease runs dry.
    """

    def __init__(self, root: str, replica: str = "r0",
                 lease_frac: float = 0.25, registry=None) -> None:
        self.root = root
        self.replica = replica
        self.lease_frac = float(lease_frac)
        self._lock = threading.Lock()
        self._leases: Dict[str, float] = {}  # guarded-by: self._lock
        self._cells: Dict[str, StateCell] = {}  # guarded-by: self._lock
        if registry is None:
            from transmogrifai_tpu.obs.metrics import get_registry
            registry = get_registry()
        self._m_sync = registry.counter(
            "router_quota_syncs_total",
            "shared-quota cell round trips", replica=replica)
        self._m_denied = registry.counter(
            "router_quota_denied_total",
            "admissions denied by the shared balance", replica=replica)

    def _cell(self, tenant: str) -> StateCell:
        with self._lock:
            cell = self._cells.get(tenant)
            if cell is None:
                safe = re.sub(r"[^A-Za-z0-9._-]", "_", tenant)[:80] or "t"
                cell = StateCell(self.root, f"quota-{safe}")
                self._cells[tenant] = cell
        return cell

    def _withdraw(self, tenant: str, rate: float, burst: float,
                  want: float) -> float:
        """CAS-withdraw up to `want` tokens from the shared balance.
        Runs OUTSIDE self._lock — the cell update can touch shared
        storage and must never serialize the other tenants."""
        granted = {"v": 0.0}

        def transform(value: Optional[Dict[str, Any]]) -> Dict[str, Any]:
            now = time.time()
            if not isinstance(value, dict):
                tokens, ts = burst, now
            else:
                tokens = float(value.get("tokens", 0.0))
                ts = float(value.get("ts", now))
                tokens = min(burst, tokens + max(0.0, now - ts) * rate)
            granted["v"] = max(0.0, min(tokens, want))
            return {"tokens": tokens - granted["v"], "ts": now,
                    "rate": rate, "burst": burst}

        self._cell(tenant).update(transform)
        self._m_sync.inc()
        return granted["v"]

    def try_spend(self, tenant: str, n: float, rate: float,
                  burst: float) -> bool:
        """Spend `n` tokens for `tenant`; False = over the K-replica
        budget (caller maps to quota_exceeded/429)."""
        if rate == float("inf"):
            return True
        with self._lock:
            lease = self._leases.get(tenant, 0.0)
            if lease >= n:
                self._leases[tenant] = lease - n
                return True
        want = max(n, burst * self.lease_frac)
        granted = self._withdraw(tenant, rate, burst, want)
        with self._lock:
            lease = self._leases.get(tenant, 0.0) + granted
            if lease >= n:
                self._leases[tenant] = lease - n
                return True
            # not enough fleet-wide: keep the partial lease for later
            self._leases[tenant] = lease
        self._m_denied.inc()
        return False

    def refill_eta_s(self, tenant: str, n: float, rate: float) -> float:
        """Honest Retry-After for a denied admission: how long the
        SHARED refill needs to cover `n` tokens beyond what this
        replica already holds."""
        if rate <= 0.0:
            return 3600.0
        with self._lock:
            lease = self._leases.get(tenant, 0.0)
        return min(3600.0, max(0.0, (n - lease)) / rate)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            leases = dict(self._leases)
        out: Dict[str, Any] = {"replica": self.replica, "tenants": {}}
        for tenant, lease in sorted(leases.items()):
            _, value = self._cell(tenant).read()
            out["tenants"][tenant] = {
                "lease": round(lease, 3),
                "shared": value if isinstance(value, dict) else None,
            }
        return out
