"""One resolution point for every shared on-disk location.

Before the store existed, each subsystem hardcoded its own corner of
`~/.cache/transmogrifai_tpu` (feature cache, perf corpus, XLA compile
cache, sweep calibration), so pointing a K-replica fleet at shared
storage meant chasing N env vars and still missing the hardcoded
fallbacks. Now: `TRANSMOGRIFAI_STORE_DIR` moves the WHOLE root (every
subsystem follows), while each subsystem's existing env var still wins
for its own subtree — nothing previously configurable got less so.
"""

from __future__ import annotations

import os

__all__ = [
    "ENV_STORE",
    "cache_root",
    "resolve_dir",
    "store_configured",
]

ENV_STORE = "TRANSMOGRIFAI_STORE_DIR"

# subsystem env overrides, kept here so callers and docs agree on the
# precedence order: explicit arg > subsystem env > store root env > HOME
ENV_FEATURE_CACHE = "TRANSMOGRIFAI_FEATURE_CACHE_DIR"
ENV_PERF_CORPUS = "TRANSMOGRIFAI_PERF_CORPUS_DIR"
ENV_COMPILE_CACHE = "TRANSMOGRIFAI_TPU_CACHE"


def store_configured() -> bool:
    """True when a shared store root was explicitly pointed somewhere —
    the signal consumers use to ALSO publish replica-portable artifacts
    (warmup manifests, corpus shards) instead of only local sidecars."""
    return bool(os.environ.get(ENV_STORE))


def cache_root() -> str:
    env = os.environ.get(ENV_STORE)
    if env:
        return env
    return os.path.expanduser("~/.cache/transmogrifai_tpu")


def resolve_dir(kind: str, env: str | None = None,
                explicit: str | None = None) -> str:
    """Resolve the directory for one artifact kind.

    Precedence: explicit caller arg, then the subsystem's own env var,
    then `<store root>/<kind>` (where the store root itself honors
    `TRANSMOGRIFAI_STORE_DIR` before falling back to the home cache).
    """
    if explicit:
        return explicit
    if env:
        val = os.environ.get(env)
        if val:
            return val
    return os.path.join(cache_root(), kind)
