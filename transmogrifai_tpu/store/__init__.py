"""Shared state plane: content-addressed artifact store + CAS state.

`ArtifactStore` (get/put/stat over a pluggable backend) carries every
replica-portable artifact — feature-cache tapes, warmup manifests,
perf-corpus shards — on the PR-4/PR-6 staged-commit protocol, with
LRU+TTL GC and wire-tape prefetch. `StateCell`/`SharedQuota` add
CAS-guarded mutable state (token-bucket snapshots, SLO burn) on the
same directory, so the K-replica tenant invariant holds without a
per-request round trip. `config` is the single resolution point for
every shared on-disk location (`TRANSMOGRIFAI_STORE_DIR`).
"""

from transmogrifai_tpu.store.artifact import (
    MANIFEST, STORE_VERSION, ArtifactInfo, ArtifactStore, Backend,
    LocalDirBackend, StoreCorruptError)
from transmogrifai_tpu.store.config import (
    ENV_STORE, cache_root, resolve_dir, store_configured)
from transmogrifai_tpu.store.state import (
    LeaseTable, SharedQuota, StateCell)

__all__ = [
    "MANIFEST",
    "STORE_VERSION",
    "ArtifactInfo",
    "ArtifactStore",
    "Backend",
    "LocalDirBackend",
    "StoreCorruptError",
    "ENV_STORE",
    "cache_root",
    "resolve_dir",
    "store_configured",
    "LeaseTable",
    "SharedQuota",
    "StateCell",
]
