"""Data readers: ingestion + event-time aggregation (readers/ module)."""

from transmogrifai_tpu.readers.readers import (
    AggregateDataReader,
    AvroReader,
    ConditionalDataReader,
    CSVReader,
    DataReaders,
    JoinedDataReader,
    Reader,
    SimpleReader,
    StreamingReader,
)

__all__ = [
    "AggregateDataReader", "AvroReader", "ConditionalDataReader", "CSVReader",
    "DataReaders", "JoinedDataReader", "Reader", "SimpleReader",
    "StreamingReader",
]
