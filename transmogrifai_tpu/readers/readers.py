"""Readers: record ingestion, key-grouped event aggregation, joins, streaming.

Reference parity: `readers/src/main/scala/com/salesforce/op/readers/` —
`Reader.generateDataFrame` (Reader.scala:96-168, DataReader.scala:174-259),
`DataReaders.Simple/Aggregate/Conditional` factories (DataReaders.scala:44-290),
`AggregateDataReader`/`ConditionalDataReader` cutoff semantics
(DataReader.scala:216-367), `JoinedDataReader` (JoinedDataReader.scala:119-356),
`StreamingReader` (StreamingReader.scala:54).

TPU-first: a reader's product is a host-side columnar `Dataset` (the device
sees only dense batches later). Aggregating readers fold unbounded per-key
event streams through monoid aggregators (transmogrifai_tpu.aggregators) so
row width is constant regardless of history length — the reference's Spark
groupBy+fold becomes a host dict-group + monoid fold.

Aggregating readers emit *pre-extracted* datasets: columns are final typed
feature values keyed by feature name (FeatureGeneratorStage.materialize
reads them directly instead of re-running extract functions).
"""

from __future__ import annotations

import csv as _csv
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.aggregators import (
    CutOffTime, Event, MonoidAggregator, aggregate_events, default_aggregator)
from transmogrifai_tpu.data.dataset import Dataset

KEY_COLUMN = "key"  # reference: DataFrameFieldNames.KeyFieldName


def _record_value(stage, record: Mapping[str, Any]) -> Any:
    """Extract one raw value from a record via the feature's generator stage
    (extract fn or named column) — DataReader.scala:174-213."""
    if stage.extract is not None:
        return stage.extract(record)
    return record.get(stage.column)


def _mark_pre_extracted(ds: Dataset, names) -> Dataset:
    # per-column marking read by FeatureGeneratorStage.materialize — a
    # dataset-global flag would wrongly bypass extract/null_fill for columns
    # contributed by a non-aggregating side of a join
    ds.pre_extracted = set(names)
    return ds


def _own_features(reader, raw_features: Sequence) -> List:
    """Restrict to the raw features this reader produces (its `features`
    allowlist when given — the analogue of each reader in a join owning its
    own feature set, JoinedDataReader.scala:119-180)."""
    allow = getattr(reader, "features", None)
    if allow is None:
        return list(raw_features)
    names = {f.name if hasattr(f, "name") else str(f) for f in allow}
    return [f for f in raw_features if f.name in names]


class Reader:
    """Base reader: `read(raw_features) -> Dataset`."""

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raise NotImplementedError

    # -- composition (Reader.scala `innerJoin/leftOuterJoin/outerJoin`) --- #

    def inner_join(self, other: "Reader") -> "JoinedDataReader":
        return JoinedDataReader(self, other, how="inner")

    def left_outer_join(self, other: "Reader") -> "JoinedDataReader":
        return JoinedDataReader(self, other, how="left")

    def outer_join(self, other: "Reader") -> "JoinedDataReader":
        return JoinedDataReader(self, other, how="outer")


class SimpleReader(Reader):
    """Non-aggregating reader over records or a prebuilt Dataset
    (DataReaders.Simple — one row per record, raw features extracted
    lazily by the workflow's generator stages)."""

    def __init__(self, records: Optional[Sequence[Mapping[str, Any]]] = None,
                 dataset: Optional[Dataset] = None,
                 key_fn: Optional[Callable[[Mapping[str, Any]], str]] = None,
                 schema: Optional[Mapping[str, type]] = None):
        if (records is None) == (dataset is None):
            raise ValueError("SimpleReader: pass exactly one of records/dataset")
        self.records = records
        self.dataset = dataset
        self.key_fn = key_fn
        self.schema = schema

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        if self.dataset is not None:
            ds = self.dataset
        else:
            ds = Dataset.from_rows(list(self.records), schema=self.schema)
        if self.key_fn is not None and KEY_COLUMN not in ds.columns:
            rows = self.records if self.records is not None else ds.to_rows()
            keys = np.array([str(self.key_fn(r)) for r in rows], dtype=object)
            ds = ds.with_column(KEY_COLUMN, keys, T.ID)
        return ds


class CSVReader(SimpleReader):
    """CSV-file reader (CSVAutoReaders/CSVReaders analogue): schema inferred
    unless given."""

    def __init__(self, path: str, schema: Optional[Mapping[str, type]] = None,
                 key_column: Optional[str] = None, delimiter: str = ","):
        self.path = path
        self._schema = schema
        self.key_column = key_column
        self.delimiter = delimiter
        self.key_fn = None
        self.dataset = None
        self.records = None

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        ds = Dataset.from_csv(self.path, schema=self._schema,
                              delimiter=self.delimiter)
        if self.key_column and self.key_column in ds.columns \
                and KEY_COLUMN not in ds.columns:
            keys = np.array([str(v) for v in ds.column(self.key_column)],
                            dtype=object)
            ds = ds.with_column(KEY_COLUMN, keys, T.ID)
        return ds


def _group_events(records: Iterable[Mapping[str, Any]],
                  key_fn: Callable, time_fn: Callable
                  ) -> Dict[str, List[Any]]:
    groups: Dict[str, List[Any]] = {}
    for rec in records:
        groups.setdefault(str(key_fn(rec)), []).append(
            (int(time_fn(rec)), rec))
    return groups


def _aggregate_groups(groups: Dict[str, List[Any]], raw_features: Sequence,
                      cutoffs: Mapping[str, Optional[CutOffTime]]) -> Dataset:
    """Fold each key's event list through every raw feature's aggregator
    (DataReader.scala:229-330: groupBy key → monoid fold per feature)."""
    rows: List[Dict[str, Any]] = []
    schema: Dict[str, type] = {KEY_COLUMN: T.ID}
    for f in raw_features:
        schema[f.name] = f.ftype
    for key in groups:
        events_rec = groups[key]
        row: Dict[str, Any] = {KEY_COLUMN: key}
        for f in raw_features:
            stage = f.origin_stage
            agg: Optional[MonoidAggregator] = stage.params.get("aggregator")
            window = stage.params.get("aggregate_window")
            events = [Event(t, _record_value(stage, rec))
                      for t, rec in events_rec]
            row[f.name] = aggregate_events(
                events, f.ftype, aggregator=agg, cutoff=cutoffs.get(key),
                is_response=f.is_response, window_ms=window)
        rows.append(row)
    return _mark_pre_extracted(Dataset.from_rows(rows, schema=schema),
                               [f.name for f in raw_features])


class AggregateDataReader(Reader):
    """Event-time aggregating reader (DataReaders.Aggregate,
    DataReader.scala:216-300): group records by key, fold each feature's
    events through its monoid with a global `CutOffTime` — predictors see
    pre-cutoff events, responses post-cutoff."""

    def __init__(self, records: Sequence[Mapping[str, Any]],
                 key_fn: Callable[[Mapping[str, Any]], str],
                 time_fn: Callable[[Mapping[str, Any]], int],
                 cutoff: Optional[CutOffTime] = None,
                 features: Optional[Sequence] = None):
        self.records = records
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.cutoff = cutoff or CutOffTime.no_cutoff()
        self.features = features  # allowlist when joined with other readers

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raw_features = _own_features(self, raw_features or [])
        if not raw_features:
            raise ValueError(
                "AggregateDataReader needs the workflow's raw features to "
                "aggregate (call through Workflow, or pass raw_features)")
        groups = _group_events(self.records, self.key_fn, self.time_fn)
        cutoffs = {k: self.cutoff for k in groups}
        return _aggregate_groups(groups, raw_features, cutoffs)


class ConditionalDataReader(Reader):
    """Per-key dynamic cutoff (DataReaders.Conditional,
    DataReader.scala:303-367): the cutoff for each key is the time of its
    earliest record satisfying `target_condition` — "simulate the state at
    the moment event X happened". Keys with no matching record are dropped
    when `drop_if_not_met` (else they keep all events as predictors)."""

    def __init__(self, records: Sequence[Mapping[str, Any]],
                 key_fn: Callable[[Mapping[str, Any]], str],
                 time_fn: Callable[[Mapping[str, Any]], int],
                 target_condition: Callable[[Mapping[str, Any]], bool],
                 drop_if_not_met: bool = True,
                 features: Optional[Sequence] = None):
        self.records = records
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.target_condition = target_condition
        self.drop_if_not_met = drop_if_not_met
        self.features = features

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raw_features = _own_features(self, raw_features or [])
        if not raw_features:
            raise ValueError("ConditionalDataReader needs raw features")
        groups = _group_events(self.records, self.key_fn, self.time_fn)
        cutoffs: Dict[str, Optional[CutOffTime]] = {}
        for key, evs in list(groups.items()):
            match = [t for t, rec in evs if self.target_condition(rec)]
            if match:
                cutoffs[key] = CutOffTime.unix_epoch(min(match))
            elif self.drop_if_not_met:
                del groups[key]
            else:
                # unmatched keys: all events are predictors, responses stay
                # empty (an infinite-future cutoff — nothing is ever at/after)
                cutoffs[key] = CutOffTime.infinite_future()
        return _aggregate_groups(groups, raw_features, cutoffs)


class JoinedDataReader(Reader):
    """Key-based join of two readers (JoinedDataReader.scala:119-356):
    both sides are read (each producing a keyed Dataset), then joined on
    `key`. `with_secondary_aggregation` folds duplicate right-side rows per
    key through type-default monoids (the post-join aggregation stage)."""

    def __init__(self, left: Reader, right: Reader, how: str = "left"):
        if how not in ("inner", "left", "outer"):
            raise ValueError(f"Unsupported join type {how!r}")
        self.left = left
        self.right = right
        self.how = how
        self._secondary = False

    def with_secondary_aggregation(self) -> "JoinedDataReader":
        """Fold duplicate right-side rows per key through type-default
        monoids. (Time-windowed post-join filtering belongs in the child
        reader's own CutOffTime — joined rows no longer carry event times.)"""
        self._secondary = True
        return self

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raw_features = list(raw_features or [])
        aggregating = (AggregateDataReader, ConditionalDataReader)
        if (isinstance(self.left, aggregating) and self.left.features is None
                and isinstance(self.right, aggregating)
                and self.right.features is None):
            raise ValueError(
                "Joining two aggregating readers requires each to declare "
                "its own features= allowlist, otherwise both sides "
                "aggregate every raw feature and shadow each other")
        left_ds = self.left.read(raw_features)
        right_ds = self.right.read(raw_features)
        for side, ds in (("left", left_ds), ("right", right_ds)):
            if KEY_COLUMN not in ds.columns:
                raise ValueError(
                    f"JoinedDataReader: {side} reader produced no "
                    f"{KEY_COLUMN!r} column (give it a key_fn)")

        lrows = left_ds.to_rows()
        rrows = right_ds.to_rows()
        rindex: Dict[str, List[Dict[str, Any]]] = {}
        for r in rrows:
            rindex.setdefault(str(r[KEY_COLUMN]), []).append(r)

        schema: Dict[str, type] = dict(left_ds.schema)
        for name, t in right_ds.schema.items():
            schema.setdefault(name, t)
        rcols = [c for c in right_ds.schema if c != KEY_COLUMN
                 and c not in left_ds.schema]

        ftypes = {f.name: f.ftype for f in raw_features}

        def merge(l_row: Optional[Dict], r_group: List[Dict]) -> Dict[str, Any]:
            if l_row is not None:
                row = dict(l_row)
                copy_cols = rcols  # left values win on shared names
            else:  # right-only row: every right column carries over
                row = {KEY_COLUMN: r_group[0][KEY_COLUMN]}
                copy_cols = [c for c in right_ds.schema if c != KEY_COLUMN]
            if not r_group:
                for c in copy_cols:
                    row.setdefault(c, None)
            elif len(r_group) == 1 or not self._secondary:
                for c in copy_cols:
                    row[c] = r_group[0].get(c)
            else:  # secondary aggregation of duplicate child rows
                for c in copy_cols:
                    ftype = ftypes.get(c) or right_ds.schema.get(c, T.Text)
                    events = [Event(0, g.get(c)) for g in r_group]
                    row[c] = default_aggregator(ftype)(events)
            return row

        out: List[Dict[str, Any]] = []
        seen_keys = set()
        for l_row in lrows:
            k = str(l_row[KEY_COLUMN])
            seen_keys.add(k)
            group = rindex.get(k, [])
            if group and not self._secondary and len(group) > 1:
                # no secondary aggregation: one output row per child match
                for g in group:
                    out.append(merge(l_row, [g]))
            elif group:
                out.append(merge(l_row, group))
            elif self.how in ("left", "outer"):
                out.append(merge(l_row, []))
        if self.how == "outer":
            for k, group in rindex.items():
                if k in seen_keys:
                    continue
                if not self._secondary and len(group) > 1:
                    for g in group:  # same per-child expansion as left matches
                        out.append(merge(None, [g]))
                else:
                    out.append(merge(None, group))
        ds = Dataset.from_rows(out, schema=schema)
        pre = set(getattr(left_ds, "pre_extracted", ()) or ()) | \
            set(getattr(right_ds, "pre_extracted", ()) or ())
        if pre:
            _mark_pre_extracted(ds, pre & set(ds.columns))
        return ds


class StreamingReader(Reader):
    """Micro-batch streaming source (StreamingReader.scala:54): yields
    Datasets of up to `batch_size` records for the runner's streaming-score
    loop. `read()` materializes everything (the batch path)."""

    def __init__(self, records: Optional[Iterable[Mapping[str, Any]]] = None,
                 csv_path: Optional[str] = None, batch_size: int = 1024,
                 schema: Optional[Mapping[str, type]] = None):
        if (records is None) == (csv_path is None):
            raise ValueError("StreamingReader: pass exactly one of records/csv_path")
        self.records = records
        self.csv_path = csv_path
        self.batch_size = int(batch_size)
        self.schema = schema

    def _record_iter(self) -> Iterator[Mapping[str, Any]]:
        if self.records is not None:
            yield from self.records
            return
        # parse CSV cells with the same typed inference as Dataset.from_csv
        # so the streaming path matches DataReaders.csv on the same file
        from transmogrifai_tpu.data.dataset import _infer_ftype, _parse_cell
        with open(self.csv_path, "r", newline="") as f:
            reader = _csv.DictReader(f)
            rows = list(reader)
        if self.schema is None:
            fields = rows[0].keys() if rows else ()
            self.schema = {
                name: _infer_ftype([r.get(name) or None for r in rows])
                for name in fields}
        for r in rows:
            yield {k: _parse_cell(v, self.schema.get(k, T.Text))
                   for k, v in r.items()}

    def stream(self) -> Iterator[Dataset]:
        buf: List[Mapping[str, Any]] = []
        for rec in self._record_iter():
            buf.append(rec)
            if len(buf) >= self.batch_size:
                yield Dataset.from_rows(buf, schema=self.schema)
                buf = []
        if buf:
            yield Dataset.from_rows(buf, schema=self.schema)

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        return Dataset.from_rows(list(self._record_iter()), schema=self.schema)


class DataReaders:
    """Factory namespace mirroring `DataReaders.Simple/Aggregate/Conditional`
    (DataReaders.scala:44-290)."""

    @staticmethod
    def simple(records=None, dataset=None, key_fn=None, schema=None) -> SimpleReader:
        return SimpleReader(records=records, dataset=dataset, key_fn=key_fn,
                            schema=schema)

    @staticmethod
    def csv(path, schema=None, key_column=None, delimiter=",") -> CSVReader:
        return CSVReader(path, schema=schema, key_column=key_column,
                         delimiter=delimiter)

    @staticmethod
    def aggregate(records, key_fn, time_fn, cutoff=None,
                  features=None) -> AggregateDataReader:
        return AggregateDataReader(records, key_fn, time_fn, cutoff=cutoff,
                                   features=features)

    @staticmethod
    def conditional(records, key_fn, time_fn, target_condition,
                    drop_if_not_met=True,
                    features=None) -> ConditionalDataReader:
        return ConditionalDataReader(records, key_fn, time_fn,
                                     target_condition,
                                     drop_if_not_met=drop_if_not_met,
                                     features=features)

    @staticmethod
    def stream(records=None, csv_path=None, batch_size=1024,
               schema=None) -> StreamingReader:
        return StreamingReader(records=records, csv_path=csv_path,
                               batch_size=batch_size, schema=schema)
