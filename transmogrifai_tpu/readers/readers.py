"""Readers: record ingestion, key-grouped event aggregation, joins, streaming.

Reference parity: `readers/src/main/scala/com/salesforce/op/readers/` —
`Reader.generateDataFrame` (Reader.scala:96-168, DataReader.scala:174-259),
`DataReaders.Simple/Aggregate/Conditional` factories (DataReaders.scala:44-290),
`AggregateDataReader`/`ConditionalDataReader` cutoff semantics
(DataReader.scala:216-367), `JoinedDataReader` (JoinedDataReader.scala:119-356),
`StreamingReader` (StreamingReader.scala:54).

TPU-first: a reader's product is a host-side columnar `Dataset` (the device
sees only dense batches later). Aggregating readers fold unbounded per-key
event streams through monoid aggregators (transmogrifai_tpu.aggregators) so
row width is constant regardless of history length — the reference's Spark
groupBy+fold becomes a host dict-group + monoid fold.

Aggregating readers emit *pre-extracted* datasets: columns are final typed
feature values keyed by feature name (FeatureGeneratorStage.materialize
reads them directly instead of re-running extract functions).
"""

from __future__ import annotations

import csv as _csv
import logging
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.aggregators import (
    CutOffTime, Event, MonoidAggregator, aggregate_events, default_aggregator)
from transmogrifai_tpu.data.dataset import Dataset

KEY_COLUMN = "key"  # reference: DataFrameFieldNames.KeyFieldName

log = logging.getLogger(__name__)


def _record_value(stage, record: Mapping[str, Any]) -> Any:
    """Extract one raw value from a record via the feature's generator stage
    (extract fn or named column) — DataReader.scala:174-213."""
    if stage.extract is not None:
        return stage.extract(record)
    return record.get(stage.column)


def _mark_pre_extracted(ds: Dataset, names) -> Dataset:
    # per-column marking read by FeatureGeneratorStage.materialize — a
    # dataset-global flag would wrongly bypass extract/null_fill for columns
    # contributed by a non-aggregating side of a join
    ds.pre_extracted = set(names)
    return ds


def _own_features(reader, raw_features: Sequence) -> List:
    """Restrict to the raw features this reader produces (its `features`
    allowlist when given — the analogue of each reader in a join owning its
    own feature set, JoinedDataReader.scala:119-180)."""
    allow = getattr(reader, "features", None)
    if allow is None:
        return list(raw_features)
    names = {f.name if hasattr(f, "name") else str(f) for f in allow}
    return [f for f in raw_features if f.name in names]


def _derivable_features(reader, raw_features: Sequence,
                        probe_limit: int = 100) -> List:
    """Features actually derivable from this reader's records, probed over
    the first `probe_limit` records (event streams have heterogeneous
    records, so one record is not enough): column-based features need their
    column present in SOME record; extract-fn features must yield a
    non-None value on some record. Used when an aggregating reader joins
    another reader without declaring a features= allowlist — it must not
    aggregate (and then shadow) raw features owned by the other side.

    Caveat: an extract fn with a non-None fallback (e.g.
    `lambda r: r.get("age", 0.0)`) probes as derivable on ANY record and
    will be claimed by the wrong side — declare a features= allowlist on
    joined aggregating readers whenever extract fns have defaults."""
    records = list(getattr(reader, "records", None) or [])
    # column-based features: exact check over ALL records (cheap key scan —
    # rare record types can first appear arbitrarily late in a stream)
    all_keys: set = set()
    for r in records:
        all_keys.update(r.keys())
    probes = records[:probe_limit]
    out = []
    for f in raw_features:
        stage = f.origin_stage
        if stage.extract is None:
            if stage.column in all_keys:
                out.append(f)
            continue
        for probe in probes:
            try:
                if stage.extract(probe) is not None:
                    out.append(f)
                    break
            except Exception:
                # an extract-fn crash on a probe record means "not
                # derivable from this record" — try the next probe
                log.debug("probe record rejected by extract fn for %r",
                          f.name, exc_info=True)
                continue
        else:
            log.warning(
                "JoinedDataReader: feature %r (extract fn) probed "
                "non-derivable on the first %d records of an aggregating "
                "side with no features= allowlist — it will come from the "
                "other side / null-fill; declare features= to silence",
                f.name, len(probes))
    return out


class Reader:
    """Base reader: `read(raw_features) -> Dataset`."""

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raise NotImplementedError

    # -- composition (Reader.scala `innerJoin/leftOuterJoin/outerJoin`) --- #

    def inner_join(self, other: "Reader") -> "JoinedDataReader":
        return JoinedDataReader(self, other, how="inner")

    def left_outer_join(self, other: "Reader") -> "JoinedDataReader":
        return JoinedDataReader(self, other, how="left")

    def outer_join(self, other: "Reader") -> "JoinedDataReader":
        return JoinedDataReader(self, other, how="outer")


class SimpleReader(Reader):
    """Non-aggregating reader over records or a prebuilt Dataset
    (DataReaders.Simple — one row per record, raw features extracted
    lazily by the workflow's generator stages)."""

    def __init__(self, records: Optional[Sequence[Mapping[str, Any]]] = None,
                 dataset: Optional[Dataset] = None,
                 key_fn: Optional[Callable[[Mapping[str, Any]], str]] = None,
                 schema: Optional[Mapping[str, type]] = None):
        if (records is None) == (dataset is None):
            raise ValueError("SimpleReader: pass exactly one of records/dataset")
        self.records = records
        self.dataset = dataset
        self.key_fn = key_fn
        self.schema = schema

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        if self.dataset is not None:
            ds = self.dataset
        else:
            ds = Dataset.from_rows(list(self.records), schema=self.schema)
        if self.key_fn is not None and KEY_COLUMN not in ds.columns:
            rows = self.records if self.records is not None else ds.to_rows()
            keys = np.array([str(self.key_fn(r)) for r in rows], dtype=object)
            ds = ds.with_column(KEY_COLUMN, keys, T.ID)
        return ds


def _with_key_column(ds: Dataset, key_column: Optional[str]) -> Dataset:
    """Stringify a key column into the reserved KEY_COLUMN; integral-typed
    keys format without the float-storage ".0" suffix."""
    if not key_column or key_column not in ds.columns \
            or KEY_COLUMN in ds.columns:
        return ds
    ftype = ds.schema.get(key_column)
    integral = ftype is not None and issubclass(
        ftype, (T.Integral, T.Date, T.DateTime))

    def fmt(v) -> str:
        if integral and isinstance(v, float) and not np.isnan(v) \
                and v == int(v):
            return str(int(v))
        return str(v)
    keys = np.array([fmt(v) for v in ds.column(key_column)], dtype=object)
    return ds.with_column(KEY_COLUMN, keys, T.ID)


class CSVReader(SimpleReader):
    """CSV-file reader (CSVAutoReaders/CSVReaders analogue): schema inferred
    unless given."""

    def __init__(self, path: str, schema: Optional[Mapping[str, type]] = None,
                 key_column: Optional[str] = None, delimiter: str = ","):
        self.path = path
        self._schema = schema
        self.key_column = key_column
        self.delimiter = delimiter
        self.key_fn = None
        self.dataset = None
        self.records = None

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        ds = Dataset.from_csv(self.path, schema=self._schema,
                              delimiter=self.delimiter)
        return _with_key_column(ds, self.key_column)


class AvroReader(Reader):
    """Avro container-file reader (AvroReaders.scala analogue): decoded by
    the in-tree pure-Python container codec (data/avro.py)."""

    def __init__(self, path: str, schema: Optional[Mapping[str, type]] = None,
                 key_column: Optional[str] = None):
        self.path = path
        self._schema = schema
        self.key_column = key_column
        self.features = None

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        ds = Dataset.from_avro(self.path, schema=self._schema)
        return _with_key_column(ds, self.key_column)


class ParquetReader(Reader):
    """Columnar Parquet reader (ParquetProductReader.scala analogue):
    typed columns land directly from the arrow table — no row dicts."""

    def __init__(self, path: str, schema: Optional[Mapping[str, type]] = None,
                 key_column: Optional[str] = None):
        self.path = path
        self._schema = schema
        self.key_column = key_column
        self.features = None

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        ds = Dataset.from_parquet(self.path, schema=self._schema)
        return _with_key_column(ds, self.key_column)


def _group_events(records: Iterable[Mapping[str, Any]],
                  key_fn: Callable, time_fn: Callable
                  ) -> Dict[str, List[Any]]:
    groups: Dict[str, List[Any]] = {}
    for rec in records:
        groups.setdefault(str(key_fn(rec)), []).append(
            (int(time_fn(rec)), rec))
    return groups


def _aggregate_groups(groups: Dict[str, List[Any]], raw_features: Sequence,
                      cutoffs: Mapping[str, Optional[CutOffTime]],
                      response_window_ms: Optional[int] = None,
                      predictor_window_ms: Optional[int] = None) -> Dataset:
    """Fold each key's event list through every raw feature's aggregator
    (DataReader.scala:229-330: groupBy key → monoid fold per feature).
    Reader-level windows apply when a feature has no aggregate window of
    its own (FeatureAggregator.scala specialTimeWindow.orElse)."""
    rows: List[Dict[str, Any]] = []
    schema: Dict[str, type] = {KEY_COLUMN: T.ID}
    for f in raw_features:
        schema[f.name] = f.ftype
    for key in groups:
        events_rec = groups[key]
        row: Dict[str, Any] = {KEY_COLUMN: key}
        for f in raw_features:
            stage = f.origin_stage
            agg: Optional[MonoidAggregator] = stage.params.get("aggregator")
            window = stage.params.get("aggregate_window")
            events = [Event(t, _record_value(stage, rec))
                      for t, rec in events_rec]
            row[f.name] = aggregate_events(
                events, f.ftype, aggregator=agg, cutoff=cutoffs.get(key),
                is_response=f.is_response, window_ms=window,
                response_window_ms=response_window_ms,
                predictor_window_ms=predictor_window_ms)
        rows.append(row)
    return _mark_pre_extracted(Dataset.from_rows(rows, schema=schema),
                               [f.name for f in raw_features])


def _columnar_result(cols: Dict[str, List[Any]], keys: np.ndarray,
                     raw_features: Sequence,
                     keep: Optional[np.ndarray] = None) -> Dataset:
    schema: Dict[str, type] = {KEY_COLUMN: T.ID}
    rows: List[Dict[str, Any]] = []
    for f in raw_features:
        schema[f.name] = f.ftype
    idxs = range(len(keys)) if keep is None else np.flatnonzero(keep)
    for i in idxs:
        row: Dict[str, Any] = {KEY_COLUMN: str(keys[i])}
        for f in raw_features:
            row[f.name] = cols[f.name][i]
        rows.append(row)
    return _mark_pre_extracted(Dataset.from_rows(rows, schema=schema),
                               [f.name for f in raw_features])


class AggregateDataReader(Reader):
    """Event-time aggregating reader (DataReaders.Aggregate,
    DataReader.scala:216-300): group records by key, fold each feature's
    events through its monoid with a global `CutOffTime` — predictors see
    pre-cutoff events, responses post-cutoff.

    Two cores: the per-record Python fold (`records` = row mappings with
    `key_fn`/`time_fn` — the semantic oracle), and a VECTORIZED groupby
    (`records` = a columnar `Dataset` with `key_column`/`time_column` —
    one lexsort + per-feature reduceat, `readers/columnar_agg.py`) that
    aggregates ~1M events in under a second (VERDICT r2 #7; scale parity
    with DataReader.scala's cluster groupBy)."""

    def __init__(self, records,
                 key_fn: Optional[Callable[[Mapping[str, Any]], str]] = None,
                 time_fn: Optional[Callable[[Mapping[str, Any]], int]] = None,
                 cutoff: Optional[CutOffTime] = None,
                 features: Optional[Sequence] = None,
                 key_column: Optional[str] = None,
                 time_column: Optional[str] = None):
        self.records = records
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.cutoff = cutoff or CutOffTime.no_cutoff()
        self.features = features  # allowlist when joined with other readers
        self.key_column = key_column
        self.time_column = time_column
        if self._columnar() and (key_column is None or time_column is None):
            raise ValueError("columnar Dataset records need key_column "
                             "and time_column")
        if not self._columnar() and (key_fn is None or time_fn is None):
            raise ValueError("row records need key_fn and time_fn")

    def _columnar(self) -> bool:
        return isinstance(self.records, Dataset)

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raw_features = _own_features(self, raw_features or [])
        if not raw_features:
            raise ValueError(
                "AggregateDataReader needs the workflow's raw features to "
                "aggregate (call through Workflow, or pass raw_features)")
        if self._columnar():
            from transmogrifai_tpu.readers.columnar_agg import (
                aggregate_columnar)
            ts = self.cutoff.timestamp
            v = np.nan if ts is None else float(ts)
            cols, keys = aggregate_columnar(
                self.records, self.key_column, self.time_column,
                raw_features,
                lambda g: np.full(g.n_groups, v, np.float64))
            return _columnar_result(cols, keys, raw_features)
        groups = _group_events(self.records, self.key_fn, self.time_fn)
        cutoffs = {k: self.cutoff for k in groups}
        return _aggregate_groups(groups, raw_features, cutoffs)

    def surviving_keys(self) -> List[str]:
        """Keys this reader would emit (all of them — no row-dropping)."""
        if self._columnar():
            return sorted({str(k)
                           for k in self.records.column(self.key_column)})
        return sorted({str(self.key_fn(r)) for r in self.records})


_WEEK_MS = 7 * 24 * 3600 * 1000  # reference default response/predictor window


class ConditionalDataReader(Reader):
    """Per-key dynamic cutoff (DataReaders.Conditional,
    DataReader.scala:303-367): each key's cutoff is chosen among the times
    of its records satisfying `target_condition` — "simulate the state at
    the moment event X happened". Reference-parity defaults
    (ConditionalParams, DataReader.scala:369-375): unmatched keys are KEPT
    (`drop_if_not_met=False`), `time_stamp_to_keep="random"` (seeded here,
    unlike the reference's unseeded Random), and 7-day response/predictor
    windows. Unmatched kept keys aggregate every event as predictor via an
    infinite-future cutoff (deterministic, where the reference anchors at
    wall-clock now())."""

    def __init__(self, records,
                 key_fn: Optional[Callable[[Mapping[str, Any]], str]] = None,
                 time_fn: Optional[Callable[[Mapping[str, Any]], int]] = None,
                 target_condition: Optional[
                     Callable[[Mapping[str, Any]], bool]] = None,
                 drop_if_not_met: bool = False,
                 time_stamp_to_keep: str = "random",
                 response_window_ms: Optional[int] = _WEEK_MS,
                 predictor_window_ms: Optional[int] = _WEEK_MS,
                 seed: int = 42,
                 features: Optional[Sequence] = None,
                 key_column: Optional[str] = None,
                 time_column: Optional[str] = None,
                 condition_column: Optional[str] = None):
        if time_stamp_to_keep not in ("min", "max", "random"):
            raise ValueError(
                f"time_stamp_to_keep must be min/max/random, "
                f"got {time_stamp_to_keep!r}")
        self.records = records
        self.key_fn = key_fn
        self.time_fn = time_fn
        self.target_condition = target_condition
        self.drop_if_not_met = drop_if_not_met
        self.time_stamp_to_keep = time_stamp_to_keep
        self.response_window_ms = response_window_ms
        self.predictor_window_ms = predictor_window_ms
        self.seed = seed
        self.features = features
        self.key_column = key_column
        self.time_column = time_column
        self.condition_column = condition_column
        if self._columnar():
            if key_column is None or time_column is None \
                    or condition_column is None:
                raise ValueError(
                    "columnar Dataset records need key_column, time_column "
                    "and condition_column")
        elif key_fn is None or time_fn is None or target_condition is None:
            raise ValueError(
                "row records need key_fn, time_fn and target_condition")

    def _columnar(self) -> bool:
        return isinstance(self.records, Dataset)

    def _columnar_cutoffs(self, g) -> np.ndarray:
        """Per-group cutoff timestamps (float64; +inf = unmatched key kept
        as all-predictor): same sorted-key iteration and seeded draws as
        the row path, so 'random' picks identical timestamps."""
        cond = np.asarray(
            self.records.column(self.condition_column)).astype(bool)
        cond_s = cond[g.order]
        rng = np.random.default_rng(self.seed)
        ends = np.r_[g.starts[1:], len(g.times)]
        out = np.full(g.n_groups, np.inf, np.float64)
        for i, (s, e) in enumerate(zip(g.starts, ends)):
            match = g.times[s:e][cond_s[s:e]]  # ascending within group
            if len(match):
                if self.time_stamp_to_keep == "min":
                    out[i] = match[0]
                elif self.time_stamp_to_keep == "max":
                    out[i] = match[-1]
                else:
                    out[i] = match[int(rng.integers(len(match)))]
        return out

    def _read_columnar(self, raw_features) -> Dataset:
        from transmogrifai_tpu.readers.columnar_agg import aggregate_columnar
        holder: Dict[str, np.ndarray] = {}

        def cutoffs(g):
            holder["cut"] = self._columnar_cutoffs(g)
            return holder["cut"]

        cols, keys = aggregate_columnar(
            self.records, self.key_column, self.time_column, raw_features,
            cutoffs, response_window_ms=self.response_window_ms,
            predictor_window_ms=self.predictor_window_ms)
        keep = None
        if self.drop_if_not_met:
            keep = np.isfinite(holder["cut"])
        return _columnar_result(cols, keys, raw_features, keep)

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raw_features = _own_features(self, raw_features or [])
        if not raw_features:
            raise ValueError("ConditionalDataReader needs raw features")
        if self._columnar():
            return self._read_columnar(raw_features)
        groups = _group_events(self.records, self.key_fn, self.time_fn)
        rng = np.random.default_rng(self.seed)
        cutoffs: Dict[str, Optional[CutOffTime]] = {}
        # sorted iteration: the per-key random draw must not depend on
        # record order
        for key in sorted(groups):
            match = [t for t, rec in groups[key] if self.target_condition(rec)]
            if match:
                if self.time_stamp_to_keep == "min":
                    ts = min(match)
                elif self.time_stamp_to_keep == "max":
                    ts = max(match)
                else:  # draw from sorted times: independent of record order
                    ts = sorted(match)[int(rng.integers(len(match)))]
                cutoffs[key] = CutOffTime.unix_epoch(ts)
            elif self.drop_if_not_met:
                del groups[key]
            else:
                # unmatched keys: all events are predictors, responses stay
                # empty (an infinite-future cutoff — nothing is ever at/after)
                cutoffs[key] = CutOffTime.infinite_future()
        return _aggregate_groups(
            groups, raw_features, cutoffs,
            response_window_ms=self.response_window_ms,
            predictor_window_ms=self.predictor_window_ms)

    def surviving_keys(self) -> List[str]:
        """Keys this reader would emit — honors target_condition +
        drop_if_not_met (keys a read() would drop must not reappear when a
        join uses this side for keys only)."""
        if self._columnar():
            keys = np.asarray(self.records.column(self.key_column)) \
                .astype(str)
            if not self.drop_if_not_met:
                return sorted(set(keys))
            cond = np.asarray(
                self.records.column(self.condition_column)).astype(bool)
            return sorted(set(keys[cond]))
        groups = _group_events(self.records, self.key_fn, self.time_fn)
        out = []
        for key, evs in groups.items():
            if (not self.drop_if_not_met
                    or any(self.target_condition(rec) for _, rec in evs)):
                out.append(key)
        return sorted(out)


class JoinedDataReader(Reader):
    """Key-based join of two readers (JoinedDataReader.scala:119-356):
    both sides are read (each producing a keyed Dataset), then joined on
    `key`. `with_secondary_aggregation` folds duplicate right-side rows per
    key through type-default monoids (the post-join aggregation stage)."""

    def __init__(self, left: Reader, right: Reader, how: str = "left"):
        if how not in ("inner", "left", "outer"):
            raise ValueError(f"Unsupported join type {how!r}")
        self.left = left
        self.right = right
        self.how = how
        self._secondary = False

    def with_secondary_aggregation(self) -> "JoinedDataReader":
        """Fold duplicate right-side rows per key through type-default
        monoids. (Time-windowed post-join filtering belongs in the child
        reader's own CutOffTime — joined rows no longer carry event times.)"""
        self._secondary = True
        return self

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        raw_features = list(raw_features or [])
        aggregating = (AggregateDataReader, ConditionalDataReader)
        if (isinstance(self.left, aggregating) and self.left.features is None
                and isinstance(self.right, aggregating)
                and self.right.features is None):
            raise ValueError(
                "Joining two aggregating readers requires each to declare "
                "its own features= allowlist, otherwise both sides "
                "aggregate every raw feature and shadow each other")
        def read_side(side) -> Dataset:
            # an aggregating reader without an allowlist must not aggregate
            # raw features it cannot derive (extract fns over the wrong
            # records yield None/garbage, and the pre_extracted marking
            # would then shadow the other side's real columns) — restrict
            # it to features probed derivable from its own records; with
            # none derivable it contributes join keys only
            if isinstance(side, aggregating) and side.features is None:
                feats = _derivable_features(side, raw_features)
                if not feats:
                    # surviving_keys honors the reader's own row-dropping
                    # semantics (conditional target_condition etc.)
                    return Dataset(
                        {KEY_COLUMN: np.array(side.surviving_keys(),
                                              dtype=object)},
                        {KEY_COLUMN: T.ID})
                return side.read(feats)
            return side.read(raw_features)

        left_ds = read_side(self.left)
        right_ds = read_side(self.right)
        for side, ds in (("left", left_ds), ("right", right_ds)):
            if KEY_COLUMN not in ds.columns:
                raise ValueError(
                    f"JoinedDataReader: {side} reader produced no "
                    f"{KEY_COLUMN!r} column (give it a key_fn)")

        lrows = left_ds.to_rows()
        rrows = right_ds.to_rows()
        rindex: Dict[str, List[Dict[str, Any]]] = {}
        for r in rrows:
            rindex.setdefault(str(r[KEY_COLUMN]), []).append(r)

        schema: Dict[str, type] = dict(left_ds.schema)
        for name, t in right_ds.schema.items():
            schema.setdefault(name, t)
        rcols = [c for c in right_ds.schema if c != KEY_COLUMN
                 and c not in left_ds.schema]

        ftypes = {f.name: f.ftype for f in raw_features}

        def merge(l_row: Optional[Dict], r_group: List[Dict]) -> Dict[str, Any]:
            if l_row is not None:
                row = dict(l_row)
                copy_cols = rcols  # left values win on shared names
            else:  # right-only row: every right column carries over
                row = {KEY_COLUMN: r_group[0][KEY_COLUMN]}
                copy_cols = [c for c in right_ds.schema if c != KEY_COLUMN]
            if not r_group:
                for c in copy_cols:
                    row.setdefault(c, None)
            elif len(r_group) == 1 or not self._secondary:
                for c in copy_cols:
                    row[c] = r_group[0].get(c)
            else:  # secondary aggregation of duplicate child rows
                for c in copy_cols:
                    ftype = ftypes.get(c) or right_ds.schema.get(c, T.Text)
                    events = [Event(0, g.get(c)) for g in r_group]
                    row[c] = default_aggregator(ftype)(events)
            return row

        out: List[Dict[str, Any]] = []
        seen_keys = set()
        for l_row in lrows:
            k = str(l_row[KEY_COLUMN])
            seen_keys.add(k)
            group = rindex.get(k, [])
            if group and not self._secondary and len(group) > 1:
                # no secondary aggregation: one output row per child match
                for g in group:
                    out.append(merge(l_row, [g]))
            elif group:
                out.append(merge(l_row, group))
            elif self.how in ("left", "outer"):
                out.append(merge(l_row, []))
        if self.how == "outer":
            for k, group in rindex.items():
                if k in seen_keys:
                    continue
                if not self._secondary and len(group) > 1:
                    for g in group:  # same per-child expansion as left matches
                        out.append(merge(None, [g]))
                else:
                    out.append(merge(None, group))
        ds = Dataset.from_rows(out, schema=schema)
        pre = set(getattr(left_ds, "pre_extracted", ()) or ()) | \
            set(getattr(right_ds, "pre_extracted", ()) or ())
        if pre:
            _mark_pre_extracted(ds, pre & set(ds.columns))
        return ds


class StreamingReader(Reader):
    """Micro-batch streaming source (StreamingReader.scala:54): yields
    Datasets of up to `batch_size` records for the runner's streaming-score
    loop. `read()` materializes everything (the batch path)."""

    def __init__(self, records: Optional[Iterable[Mapping[str, Any]]] = None,
                 csv_path: Optional[str] = None,
                 parquet_path: Optional[str] = None, batch_size: int = 1024,
                 schema: Optional[Mapping[str, type]] = None,
                 avro_path: Optional[str] = None):
        sources = sum(x is not None
                      for x in (records, csv_path, parquet_path, avro_path))
        if sources != 1:
            raise ValueError("StreamingReader: pass exactly one of "
                             "records/csv_path/parquet_path/avro_path")
        self.records = records
        self.csv_path = csv_path
        self.parquet_path = parquet_path
        self.avro_path = avro_path
        self.batch_size = int(batch_size)
        self.schema = schema

    def _record_iter(self) -> Iterator[Mapping[str, Any]]:
        if self.records is not None:
            yield from self.records
            return
        if self.avro_path is not None:
            from transmogrifai_tpu.data.avro import (
                _Names, _decoder, avro_ftype, read_container)
            avsc, recs = read_container(self.avro_path)
            if self.schema is None and isinstance(avsc, dict) \
                    and avsc.get("type") == "record":
                names = _Names()
                _decoder(avsc, names)
                self.schema = {f["name"]: avro_ftype(f["type"], names)
                               for f in avsc["fields"]}
            yield from recs
            return
        # parse CSV cells with the same typed inference as Dataset.from_csv
        # so the streaming path matches DataReaders.csv on the same file
        from transmogrifai_tpu.data.dataset import _infer_ftype, _parse_cell
        with open(self.csv_path, "r", newline="") as f:
            reader = _csv.DictReader(f)
            rows = list(reader)
        if self.schema is None:
            fields = rows[0].keys() if rows else ()
            self.schema = {
                name: _infer_ftype([r.get(name) or None for r in rows])
                for name in fields}
        for r in rows:
            yield {k: _parse_cell(v, self.schema.get(k, T.Text))
                   for k, v in r.items()}

    def stream(self) -> Iterator[Dataset]:
        if self.parquet_path is not None:
            # columnar batch path: row groups stream straight to typed
            # columns, no python row dicts (the 1B-row scoring path)
            import pyarrow as pa
            import pyarrow.parquet as pq
            pf = pq.ParquetFile(self.parquet_path)
            for batch in pf.iter_batches(batch_size=self.batch_size):
                yield Dataset.from_arrow(
                    pa.Table.from_batches([batch]), schema=self.schema)
            return
        buf: List[Mapping[str, Any]] = []
        for rec in self._record_iter():
            buf.append(rec)
            if len(buf) >= self.batch_size:
                yield Dataset.from_rows(buf, schema=self.schema)
                buf = []
        if buf:
            yield Dataset.from_rows(buf, schema=self.schema)

    def read(self, raw_features: Optional[Sequence] = None) -> Dataset:
        if self.parquet_path is not None:
            return Dataset.from_parquet(self.parquet_path, schema=self.schema)
        return Dataset.from_rows(list(self._record_iter()), schema=self.schema)


class DataReaders:
    """Factory namespace mirroring `DataReaders.Simple/Aggregate/Conditional`
    (DataReaders.scala:44-290)."""

    @staticmethod
    def simple(records=None, dataset=None, key_fn=None, schema=None) -> SimpleReader:
        return SimpleReader(records=records, dataset=dataset, key_fn=key_fn,
                            schema=schema)

    @staticmethod
    def csv(path, schema=None, key_column=None, delimiter=",") -> CSVReader:
        return CSVReader(path, schema=schema, key_column=key_column,
                         delimiter=delimiter)

    @staticmethod
    def parquet(path, schema=None, key_column=None) -> "ParquetReader":
        return ParquetReader(path, schema=schema, key_column=key_column)

    @staticmethod
    def avro(path, schema=None, key_column=None) -> "AvroReader":
        return AvroReader(path, schema=schema, key_column=key_column)

    @staticmethod
    def aggregate(records, key_fn=None, time_fn=None, cutoff=None,
                  features=None, key_column=None,
                  time_column=None) -> AggregateDataReader:
        """Row records + key_fn/time_fn = the Python monoid fold;
        a columnar `Dataset` + key_column/time_column = the vectorized
        groupby core (readers/columnar_agg.py)."""
        return AggregateDataReader(records, key_fn, time_fn, cutoff=cutoff,
                                   features=features, key_column=key_column,
                                   time_column=time_column)

    @staticmethod
    def conditional(records, key_fn=None, time_fn=None, target_condition=None,
                    drop_if_not_met=False, time_stamp_to_keep="random",
                    response_window_ms=_WEEK_MS, predictor_window_ms=_WEEK_MS,
                    seed=42, features=None, key_column=None,
                    time_column=None,
                    condition_column=None) -> ConditionalDataReader:
        return ConditionalDataReader(records, key_fn, time_fn,
                                     target_condition,
                                     drop_if_not_met=drop_if_not_met,
                                     time_stamp_to_keep=time_stamp_to_keep,
                                     response_window_ms=response_window_ms,
                                     predictor_window_ms=predictor_window_ms,
                                     seed=seed, features=features,
                                     key_column=key_column,
                                     time_column=time_column,
                                     condition_column=condition_column)

    @staticmethod
    def stream(records=None, csv_path=None, parquet_path=None,
               batch_size=1024, schema=None, avro_path=None) -> StreamingReader:
        return StreamingReader(records=records, csv_path=csv_path,
                               parquet_path=parquet_path,
                               batch_size=batch_size, schema=schema,
                               avro_path=avro_path)
