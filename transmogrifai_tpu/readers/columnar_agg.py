"""Vectorized event-time aggregation: the scale half of the aggregating
readers (VERDICT r2 #7).

Reference parity: `DataReader.scala:216-330` — Spark groups events by key
with a cluster shuffle and folds each feature's monoid per key. The
per-record Python fold in `readers.py` (`_aggregate_groups`) matches the
semantics but walks records in the interpreter; this module computes the
same result with ONE `np.lexsort` + per-feature masked
`ufunc.reduceat` group reductions — ~1M events in well under a second
for numeric monoids. The Python fold stays as the semantic oracle in
tests (`tests/test_columnar_agg.py`) and as the fallback for monoids with
no vectorized form (mode, concat, lists/sets/maps/geo).

Supported vectorized monoids (by `MonoidAggregator.name` prefix):
Sum*, Mean*, Min*, Max*, MaxDate, LogicalOr, LogicalAnd — every default
numeric/Binary/Date aggregator (`MonoidAggregatorDefaults.scala:52-120`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from transmogrifai_tpu import types as T
from transmogrifai_tpu.aggregators import CutOffTime, Event, aggregate_events

_VEC_OPS: Dict[str, Tuple[np.ufunc, float]] = {
    # ufunc, identity fill for masked-out events
    "Sum": (np.add, 0.0),
    "Mean": (np.add, 0.0),           # sum/count presented later
    "Min": (np.minimum, np.inf),
    "Max": (np.maximum, -np.inf),
    "LogicalOr": (np.maximum, 0.0),  # bools as 0/1
    "LogicalAnd": (np.minimum, 1.0),
}


def vector_op_of(agg_name: str) -> Optional[Tuple[str, np.ufunc, float]]:
    for prefix, (ufunc, fill) in _VEC_OPS.items():
        if agg_name.startswith(prefix):
            return prefix, ufunc, fill
    return None


class GroupedEvents:
    """Events sorted by (key, time) + group boundaries — built once per
    read, shared by every feature's reduction."""

    def __init__(self, keys: np.ndarray, times: np.ndarray):
        keys = np.asarray(keys).astype(str)
        times = np.asarray(times, dtype=np.int64)
        self.order = np.lexsort((times, keys))
        keys_s = keys[self.order]
        self.times = times[self.order]
        new_group = np.empty(len(keys_s), dtype=bool)
        if len(keys_s):
            new_group[0] = True
            new_group[1:] = keys_s[1:] != keys_s[:-1]
        self.starts = np.flatnonzero(new_group)
        self.group_keys = keys_s[self.starts]

    @property
    def n_groups(self) -> int:
        return len(self.starts)

    def group_slices(self):
        ends = np.r_[self.starts[1:], len(self.times)]
        return zip(self.group_keys, self.starts, ends)


def _masked_reduceat(values: np.ndarray, mask: np.ndarray,
                     starts: np.ndarray, ufunc: np.ufunc, fill: float
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-group ufunc reduction of `values` where `mask`; returns
    (reduced, valid_count). Masked-out slots carry the identity fill."""
    filled = np.where(mask, values, fill)
    out = ufunc.reduceat(filled, starts) if len(values) else \
        np.empty(0, values.dtype)
    counts = np.add.reduceat(mask.astype(np.int64), starts) if len(values) \
        else np.empty(0, np.int64)
    return out, counts


def _event_mask(times: np.ndarray, cut_ts: np.ndarray, is_response: bool,
                window_ms: Optional[int]) -> np.ndarray:
    """The reference's cutoff/window filter, vectorized
    (`FeatureAggregator.scala` filterByDateWithCutoff semantics, matching
    `aggregators.aggregate_events`): predictors strictly before the
    cutoff (window back), responses at/after it (window forward,
    inclusive). Conventions in `cut_ts`: NaN = no cutoff (keep all for
    both roles), +inf = infinite-future cutoff (all predictor, no
    response); an infinite cutoff disables the predictor window."""
    nocut = np.isnan(cut_ts)
    if is_response:
        m = times >= cut_ts
        if window_ms is not None:
            m &= times <= cut_ts + window_ms
        return m | nocut
    with np.errstate(invalid="ignore"):
        m = times < cut_ts
        if window_ms is not None:
            finite = np.isfinite(cut_ts)
            m &= ~finite | (times >= cut_ts - window_ms)
    return m | nocut


def aggregate_columnar(dataset, key_column: str, time_column: str,
                       raw_features: Sequence,
                       cutoff_ts_per_group: Callable[[np.ndarray],
                                                     np.ndarray],
                       response_window_ms: Optional[int] = None,
                       predictor_window_ms: Optional[int] = None):
    """Columnar group-aggregate: returns ({feature_name: list}, group
    keys). `cutoff_ts_per_group(group_index_of_event) -> (n_groups,)
    float64 cutoff timestamps` (inf = no cutoff).

    Features whose aggregator has a vectorized form reduce via reduceat;
    the rest fold through the Python oracle per group slice."""
    from transmogrifai_tpu.aggregators import default_aggregator

    g = GroupedEvents(np.asarray(dataset.column(key_column)),
                      np.asarray(dataset.column(time_column)))
    n_groups = g.n_groups
    ends = np.r_[g.starts[1:], len(g.times)]
    group_of = np.repeat(np.arange(n_groups), ends - g.starts)
    cut_ts = np.asarray(cutoff_ts_per_group(g), dtype=np.float64)
    cut_per_event = cut_ts[group_of]

    out: Dict[str, List[Any]] = {}
    slow_cols: Dict[str, np.ndarray] = {}
    rows_cache: List = []  # materialized once, shared by extract features
    for f in raw_features:
        stage = f.origin_stage
        agg = stage.params.get("aggregator") or default_aggregator(f.ftype)
        window = stage.params.get("aggregate_window")
        if window is None:
            window = (response_window_ms if f.is_response
                      else predictor_window_ms)
        vec = vector_op_of(agg.name) if stage.extract is None else None
        integral = issubclass(f.ftype, (T.Integral, T.Date, T.DateTime)) \
            and not issubclass(f.ftype, T.Binary)
        nn_zero = issubclass(f.ftype, T.NonNullable) and \
            issubclass(f.ftype, T.OPNumeric)

        if vec is not None and stage.column in dataset.columns:
            raw = dataset.column(stage.column)
            if raw.dtype == object:
                vals = np.array([np.nan if v is None else float(v)
                                 for v in raw], np.float64)
            else:
                vals = raw.astype(np.float64)
            vals = vals[g.order]
            mask = _event_mask(g.times, cut_per_event, f.is_response,
                               window) & ~np.isnan(vals)
            prefix, ufunc, fill = vec
            red, counts = _masked_reduceat(vals, mask, g.starts, ufunc,
                                           fill)
            if prefix == "Mean":
                with np.errstate(invalid="ignore"):
                    red = red / counts
            col: List[Any] = []
            for i in range(n_groups):
                if counts[i] == 0:
                    col.append(0.0 if nn_zero else None)
                elif prefix in ("LogicalOr", "LogicalAnd"):
                    col.append(bool(red[i]))
                elif integral:
                    col.append(int(red[i]))
                else:
                    col.append(float(red[i]))
            out[f.name] = col
        else:
            # oracle fallback per group slice (mode/concat/list/map/geo
            # monoids, extract-fn features)
            if f.name not in slow_cols:
                if stage.extract is not None:
                    if not rows_cache:
                        rows_cache.append(dataset.to_rows())
                    slow_cols[f.name] = np.array(
                        [stage.extract(r) for r in rows_cache[0]],
                        dtype=object)
                else:
                    raw = np.asarray(dataset.column(stage.column))
                    slow_cols[f.name] = raw
            vals_o = slow_cols[f.name][g.order]
            col = []
            for gi, (key, s, e) in enumerate(g.group_slices()):
                events = [Event(int(t), None if _is_missing(v) else v)
                          for t, v in zip(g.times[s:e], vals_o[s:e])]
                ts = cut_ts[gi]
                if np.isnan(ts):
                    cut = CutOffTime.no_cutoff()
                elif np.isinf(ts):
                    cut = CutOffTime.infinite_future()
                else:
                    cut = CutOffTime.unix_epoch(int(ts))
                col.append(aggregate_events(
                    events, f.ftype,
                    aggregator=stage.params.get("aggregator"),
                    cutoff=cut, is_response=f.is_response,
                    window_ms=stage.params.get("aggregate_window"),
                    response_window_ms=response_window_ms,
                    predictor_window_ms=predictor_window_ms))
            out[f.name] = col
    return out, g.group_keys


def _is_missing(v) -> bool:
    if v is None:
        return True
    return isinstance(v, float) and np.isnan(v)
