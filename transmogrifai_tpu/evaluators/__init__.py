from transmogrifai_tpu.evaluators.metrics import (
    BinaryClassificationMetrics, MultiClassificationMetrics, RegressionMetrics,
    binary_metrics, multiclass_metrics, regression_metrics,
)
from transmogrifai_tpu.evaluators.evaluators import (
    Evaluator, BinaryClassificationEvaluator, MultiClassificationEvaluator,
    RegressionEvaluator,
)

__all__ = [
    "BinaryClassificationMetrics", "MultiClassificationMetrics",
    "RegressionMetrics", "binary_metrics", "multiclass_metrics",
    "regression_metrics", "Evaluator", "BinaryClassificationEvaluator",
    "MultiClassificationEvaluator", "RegressionEvaluator",
]
