from transmogrifai_tpu.evaluators.metrics import (
    BinaryClassificationMetrics, MultiClassificationMetrics, RegressionMetrics,
    BinaryThresholdMetrics, MulticlassThresholdMetrics, BinScoreMetrics,
    ForecastMetrics,
    binary_metrics, multiclass_metrics, regression_metrics,
    binary_threshold_metrics, multiclass_threshold_metrics,
    misclassifications_per_category, bin_score_metrics, forecast_metrics,
)
from transmogrifai_tpu.evaluators.evaluators import (
    Evaluator, Evaluators, BinaryClassificationEvaluator,
    MultiClassificationEvaluator, RegressionEvaluator, BinScoreEvaluator,
    ForecastEvaluator, LambdaEvaluator,
)

__all__ = [
    "BinaryClassificationMetrics", "MultiClassificationMetrics",
    "RegressionMetrics", "BinaryThresholdMetrics", "MulticlassThresholdMetrics",
    "BinScoreMetrics", "ForecastMetrics",
    "binary_metrics", "multiclass_metrics", "regression_metrics",
    "binary_threshold_metrics", "multiclass_threshold_metrics",
    "misclassifications_per_category", "bin_score_metrics", "forecast_metrics",
    "Evaluator", "Evaluators", "BinaryClassificationEvaluator",
    "MultiClassificationEvaluator", "RegressionEvaluator", "BinScoreEvaluator",
    "ForecastEvaluator", "LambdaEvaluator",
]
