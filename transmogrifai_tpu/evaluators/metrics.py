"""Metric computations for binary / multiclass / regression problems.

Reference parity: `core/.../evaluators/OpBinaryClassificationEvaluator.scala:56-206`
(Precision/Recall/F1/AuROC/AuPR/Error/TP-TN-FP-FN),
`OpMultiClassificationEvaluator.scala:59-400`, `OpRegressionEvaluator.scala`.

AuROC uses the exact Mann-Whitney rank statistic with tie correction; AuPR is
the trapezoid area over the tie-grouped PR curve — matching Spark's
`BinaryClassificationMetrics` (which TransmogrifAI calls) on untied data and
handling ties deterministically. Host numpy: metric arrays are tiny relative
to scoring; the expensive parts (scores) were already produced on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np


# --------------------------------------------------------------------------- #
# binary                                                                      #
# --------------------------------------------------------------------------- #

@dataclass
class BinaryClassificationMetrics:
    precision: float
    recall: float
    f1: float
    auroc: float
    aupr: float
    error: float
    tp: int
    tn: int
    fp: int
    fn: int

    def to_json(self) -> Dict:
        return {
            "Precision": self.precision, "Recall": self.recall, "F1": self.f1,
            "AuROC": self.auroc, "AuPR": self.aupr, "Error": self.error,
            "TP": self.tp, "TN": self.tn, "FP": self.fp, "FN": self.fn,
        }


def auroc_score(y: np.ndarray, scores: np.ndarray) -> float:
    """Exact AuROC via rank statistic with average ranks for ties."""
    n_pos = float(y.sum())
    n_neg = float(len(y) - n_pos)
    if n_pos == 0 or n_neg == 0:
        return 0.0
    order = np.argsort(scores, kind="mergesort")
    s_sorted = scores[order]
    ranks = np.empty(len(scores), dtype=np.float64)
    i = 0
    while i < len(s_sorted):
        j = i
        while j + 1 < len(s_sorted) and s_sorted[j + 1] == s_sorted[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0  # average rank, 1-based
        i = j + 1
    r_pos = ranks[y > 0.5].sum()
    return float((r_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def aupr_score(y: np.ndarray, scores: np.ndarray) -> float:
    """Trapezoid area under the tie-grouped PR curve, with the (r=0, p=1)
    starting point (Spark BinaryClassificationMetrics convention)."""
    n_pos = float(y.sum())
    if n_pos == 0:
        return 0.0
    order = np.argsort(-scores, kind="mergesort")
    y_sorted = y[order]
    s_sorted = scores[order]
    # group ties: indices where the threshold changes
    boundaries = np.nonzero(np.diff(s_sorted))[0]
    idx = np.concatenate([boundaries, [len(s_sorted) - 1]])
    tp = np.cumsum(y_sorted)[idx]
    n_at = idx + 1.0
    precision = tp / n_at
    recall = tp / n_pos
    r = np.concatenate([[0.0], recall])
    p = np.concatenate([[1.0], precision])
    return float(np.sum((r[1:] - r[:-1]) * (p[1:] + p[:-1]) / 2.0))


def binary_metrics(y_true, scores, threshold: float = 0.5) -> BinaryClassificationMetrics:
    y = np.asarray(y_true, dtype=np.float64).ravel()
    s = np.asarray(scores, dtype=np.float64).ravel()
    pred = (s >= threshold).astype(np.float64)
    tp = int(((pred == 1) & (y == 1)).sum())
    tn = int(((pred == 0) & (y == 0)).sum())
    fp = int(((pred == 1) & (y == 0)).sum())
    fn = int(((pred == 0) & (y == 1)).sum())
    precision = tp / (tp + fp) if tp + fp > 0 else 0.0
    recall = tp / (tp + fn) if tp + fn > 0 else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall > 0 else 0.0)
    error = (fp + fn) / max(len(y), 1)
    return BinaryClassificationMetrics(
        precision=precision, recall=recall, f1=f1,
        auroc=auroc_score(y, s), aupr=aupr_score(y, s), error=error,
        tp=tp, tn=tn, fp=fp, fn=fn)


# --------------------------------------------------------------------------- #
# multiclass                                                                  #
# --------------------------------------------------------------------------- #

@dataclass
class MultiClassificationMetrics:
    precision: float   # weighted
    recall: float      # weighted
    f1: float          # weighted
    error: float
    confusion: List[List[int]]

    def to_json(self) -> Dict:
        return {"Precision": self.precision, "Recall": self.recall,
                "F1": self.f1, "Error": self.error, "Confusion": self.confusion}


def multiclass_metrics(y_true, y_pred, n_classes: Optional[int] = None
                       ) -> MultiClassificationMetrics:
    y = np.asarray(y_true, dtype=np.int64).ravel()
    p = np.asarray(y_pred, dtype=np.int64).ravel()
    k = n_classes or int(max(y.max(initial=0), p.max(initial=0))) + 1
    conf = np.zeros((k, k), dtype=np.int64)
    np.add.at(conf, (y, p), 1)
    tp = np.diag(conf).astype(np.float64)
    support = conf.sum(axis=1).astype(np.float64)
    pred_count = conf.sum(axis=0).astype(np.float64)
    prec_c = np.divide(tp, pred_count, out=np.zeros(k), where=pred_count > 0)
    rec_c = np.divide(tp, support, out=np.zeros(k), where=support > 0)
    f1_c = np.divide(2 * prec_c * rec_c, prec_c + rec_c,
                     out=np.zeros(k), where=(prec_c + rec_c) > 0)
    w = support / max(support.sum(), 1.0)
    err = 1.0 - tp.sum() / max(len(y), 1)
    return MultiClassificationMetrics(
        precision=float((prec_c * w).sum()), recall=float((rec_c * w).sum()),
        f1=float((f1_c * w).sum()), error=float(err), confusion=conf.tolist())


# --------------------------------------------------------------------------- #
# regression                                                                  #
# --------------------------------------------------------------------------- #

@dataclass
class RegressionMetrics:
    rmse: float
    mse: float
    mae: float
    r2: float
    signed_percentage_errors: List[int] = field(default_factory=list)

    def to_json(self) -> Dict:
        return {"RMSE": self.rmse, "MSE": self.mse, "MAE": self.mae,
                "R2": self.r2,
                "SignedPercentageErrorHistogram": self.signed_percentage_errors}


_SPE_BINS = np.array([-np.inf, -100, -50, -25, -10, -5, 0, 5, 10, 25, 50, 100, np.inf])


def regression_metrics(y_true, y_pred) -> RegressionMetrics:
    y = np.asarray(y_true, dtype=np.float64).ravel()
    p = np.asarray(y_pred, dtype=np.float64).ravel()
    err = p - y
    mse = float(np.mean(err ** 2)) if len(y) else 0.0
    mae = float(np.mean(np.abs(err))) if len(y) else 0.0
    ss_res = float((err ** 2).sum())
    ss_tot = float(((y - y.mean()) ** 2).sum()) if len(y) else 0.0
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0
    with np.errstate(divide="ignore", invalid="ignore"):
        spe = np.where(y != 0, 100.0 * err / np.abs(y), np.sign(err) * np.inf)
    hist = np.histogram(spe[np.isfinite(spe)], bins=_SPE_BINS)[0]
    return RegressionMetrics(
        rmse=float(np.sqrt(mse)), mse=mse, mae=mae, r2=r2,
        signed_percentage_errors=hist.tolist())


# --------------------------------------------------------------------------- #
# binary threshold curves (BinaryThresholdMetrics, OpBinaryClassification    #
# Evaluator.scala:223)                                                        #
# --------------------------------------------------------------------------- #

@dataclass
class BinaryThresholdMetrics:
    thresholds: List[float]
    precision_by_threshold: List[float]
    recall_by_threshold: List[float]
    false_positive_rate_by_threshold: List[float]

    def to_json(self) -> Dict:
        return {"thresholds": self.thresholds,
                "precisionByThreshold": self.precision_by_threshold,
                "recallByThreshold": self.recall_by_threshold,
                "falsePositiveRateByThreshold": self.false_positive_rate_by_threshold}


def binary_threshold_metrics(y_true, scores, num_bins: int = 100
                             ) -> BinaryThresholdMetrics:
    """PR/ROC curves over up-to-`num_bins` tie-grouped score thresholds
    (Spark downsamples the curve the same way)."""
    y = np.asarray(y_true, dtype=np.float64).ravel()
    s = np.asarray(scores, dtype=np.float64).ravel()
    n_pos = float(y.sum())
    n_neg = float(len(y) - n_pos)
    order = np.argsort(-s, kind="mergesort")
    ys, ss = y[order], s[order]
    boundaries = np.nonzero(np.diff(ss))[0]
    idx = np.concatenate([boundaries, [len(ss) - 1]]) if len(ss) else np.array([], np.int64)
    if len(idx) > num_bins:
        idx = idx[np.linspace(0, len(idx) - 1, num_bins).astype(np.int64)]
    tp = np.cumsum(ys)[idx]
    n_at = idx + 1.0
    fp = n_at - tp
    precision = np.divide(tp, n_at, out=np.zeros_like(tp), where=n_at > 0)
    recall = tp / n_pos if n_pos > 0 else np.zeros_like(tp)
    fpr = fp / n_neg if n_neg > 0 else np.zeros_like(fp)
    return BinaryThresholdMetrics(
        thresholds=s[order][idx].tolist(),
        precision_by_threshold=precision.tolist(),
        recall_by_threshold=recall.tolist(),
        false_positive_rate_by_threshold=fpr.tolist())


# --------------------------------------------------------------------------- #
# multiclass topN / topK threshold metrics                                    #
# (OpMultiClassificationEvaluator.scala:59-400)                               #
# --------------------------------------------------------------------------- #

@dataclass
class MulticlassThresholdMetrics:
    top_ns: List[int]
    thresholds: List[float]
    correct_counts: Dict[int, List[int]]
    incorrect_counts: Dict[int, List[int]]
    no_prediction_counts: Dict[int, List[int]]

    def to_json(self) -> Dict:
        return {"topNs": self.top_ns, "thresholds": self.thresholds,
                "correctCounts": {str(k): v for k, v in self.correct_counts.items()},
                "incorrectCounts": {str(k): v for k, v in self.incorrect_counts.items()},
                "noPredictionCounts": {str(k): v for k, v in self.no_prediction_counts.items()}}


def multiclass_threshold_metrics(y_true, probabilities,
                                 top_ns=(1, 3), n_thresholds: int = 10
                                 ) -> MulticlassThresholdMetrics:
    """For each topN and confidence threshold: counts of rows whose true
    label is in the topN classes AND max prob ≥ threshold (correct), in the
    topN but below threshold (noPrediction), or not in topN (incorrect —
    threshold-gated like the reference)."""
    y = np.asarray(y_true, dtype=np.int64).ravel()
    p = np.asarray(probabilities, dtype=np.float64)
    n = len(y)
    thresholds = np.linspace(0.0, 0.9, n_thresholds)
    maxp = p.max(axis=1) if n else np.array([])
    order = np.argsort(-p, axis=1)
    correct, incorrect, nopred = {}, {}, {}
    for topn in top_ns:
        in_topn = (order[:, :topn] == y[:, None]).any(axis=1) if n else np.array([], bool)
        c_list, i_list, np_list = [], [], []
        for thr in thresholds:
            confident = maxp >= thr
            c_list.append(int((in_topn & confident).sum()))
            i_list.append(int((~in_topn & confident).sum()))
            np_list.append(int((~confident).sum()))
        correct[topn], incorrect[topn], nopred[topn] = c_list, i_list, np_list
    return MulticlassThresholdMetrics(
        top_ns=list(top_ns), thresholds=thresholds.tolist(),
        correct_counts=correct, incorrect_counts=incorrect,
        no_prediction_counts=nopred)


def misclassifications_per_category(y_true, y_pred, min_support: int = 10,
                                    max_categories: int = 100) -> List[Dict]:
    """Per true-class error breakdown (reference's
    `misclassificationsPerCategory` with minSupport filtering)."""
    y = np.asarray(y_true, dtype=np.int64).ravel()
    p = np.asarray(y_pred, dtype=np.int64).ravel()
    out = []
    classes, counts = np.unique(y, return_counts=True)
    keep = classes[counts >= min_support][:max_categories]
    for c in keep:
        sel = y == c
        wrong = p[sel][p[sel] != c]
        wrong_classes, wrong_counts = np.unique(wrong, return_counts=True)
        out.append({
            "category": int(c), "support": int(sel.sum()),
            "error": float(len(wrong)) / max(int(sel.sum()), 1),
            "misclassifiedTo": {int(w): int(k) for w, k in
                                zip(wrong_classes, wrong_counts)}})
    return out


# --------------------------------------------------------------------------- #
# bin score / calibration (OpBinScoreEvaluator.scala:53)                      #
# --------------------------------------------------------------------------- #

@dataclass
class BinScoreMetrics:
    bin_centers: List[float]
    number_of_data_points: List[int]
    average_score: List[float]
    average_conversion_rate: List[float]
    brier_score: float

    def to_json(self) -> Dict:
        return {"binCenters": self.bin_centers,
                "numberOfDataPoints": self.number_of_data_points,
                "averageScore": self.average_score,
                "averageConversionRate": self.average_conversion_rate,
                "BrierScore": self.brier_score}


def bin_score_metrics(y_true, scores, num_bins: int = 10) -> BinScoreMetrics:
    y = np.asarray(y_true, dtype=np.float64).ravel()
    s = np.asarray(scores, dtype=np.float64).ravel()
    edges = np.linspace(0.0, 1.0, num_bins + 1)
    which = np.clip(np.digitize(s, edges[1:-1]), 0, num_bins - 1)
    counts = np.bincount(which, minlength=num_bins)
    sum_s = np.bincount(which, weights=s, minlength=num_bins)
    sum_y = np.bincount(which, weights=y, minlength=num_bins)
    nz = np.maximum(counts, 1)
    brier = float(np.mean((s - y) ** 2)) if len(y) else 0.0
    return BinScoreMetrics(
        bin_centers=((edges[:-1] + edges[1:]) / 2).tolist(),
        number_of_data_points=counts.tolist(),
        average_score=(sum_s / nz).tolist(),
        average_conversion_rate=(sum_y / nz).tolist(),
        brier_score=brier)


# --------------------------------------------------------------------------- #
# forecast (OpForecastEvaluator.scala:59)                                     #
# --------------------------------------------------------------------------- #

@dataclass
class ForecastMetrics:
    smape: float
    seasonal_error: float
    mase: float

    def to_json(self) -> Dict:
        return {"SMAPE": self.smape, "SeasonalError": self.seasonal_error,
                "MASE": self.mase}


def forecast_metrics(y_true, y_pred, seasonal_window: int = 1) -> ForecastMetrics:
    """SMAPE + seasonal naive error + MASE over a time-ordered series."""
    y = np.asarray(y_true, dtype=np.float64).ravel()
    p = np.asarray(y_pred, dtype=np.float64).ravel()
    denom = np.abs(y) + np.abs(p)
    smape = float(2.0 * np.mean(
        np.divide(np.abs(p - y), denom, out=np.zeros_like(denom), where=denom > 0)))
    m = seasonal_window
    if len(y) > m:
        seasonal_err = float(np.mean(np.abs(y[m:] - y[:-m])))
    else:
        seasonal_err = 0.0
    mae = float(np.mean(np.abs(p - y))) if len(y) else 0.0
    mase = mae / seasonal_err if seasonal_err > 0 else 0.0
    return ForecastMetrics(smape=smape, seasonal_error=seasonal_err, mase=mase)
