"""Masked, jit-safe metric kernels for the batched sweep engine.

Reference parity: the metric *values* match `evaluators/metrics.py` (which
itself mirrors `core/.../evaluators/OpBinaryClassificationEvaluator.scala`
etc.), but these run ON DEVICE inside the fused sweep program: folds are
0/1 row masks over the fixed training matrix, so fit → predict → metric for
every grid×fold executes as one XLA computation with no host round-trip
(the reference evaluates each fit's metrics in a separate Spark job —
`OpValidator.scala:318-340`).

Masked-row semantics: a row with mask 0 contributes zero weight everywhere.
In the rank-based metrics (AuROC/AuPR) masked rows still occupy slots in
the sorted arrays but with zero weight they only create duplicated curve
points whose trapezoid contribution is exactly zero, so the result equals
the host metric computed on the unmasked subset (ties included).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _masked_sum(x, mask):
    return (x * mask).sum()


def auroc_dev(y: jnp.ndarray, scores: jnp.ndarray, mask: jnp.ndarray):
    """Tie-averaged Mann-Whitney AuROC over masked rows (auroc_score parity)."""
    wpos = mask * y
    wneg = mask * (1.0 - y)
    order = jnp.argsort(scores)
    s = scores[order]
    wp = wpos[order]
    wn = wneg[order]
    cumn = jnp.concatenate([jnp.zeros(1, s.dtype), jnp.cumsum(wn)])
    left = jnp.searchsorted(s, s, side="left")
    right = jnp.searchsorted(s, s, side="right")
    below = cumn[left]
    tied = cumn[right] - cumn[left]
    num = (wp * (below + 0.5 * tied)).sum()
    n_pos = wpos.sum()
    n_neg = wneg.sum()
    ok = (n_pos > 0) & (n_neg > 0)
    return jnp.where(ok, num / jnp.maximum(n_pos * n_neg, 1e-30), 0.0)


def aupr_dev(y: jnp.ndarray, scores: jnp.ndarray, mask: jnp.ndarray):
    """Trapezoid area under the tie-grouped PR curve with the (r=0, p=1)
    start point (aupr_score / Spark BinaryClassificationMetrics parity)."""
    wpos = mask * y
    neg_s = -scores
    order = jnp.argsort(neg_s)
    s_asc = neg_s[order]            # ascending == scores descending
    wp = wpos[order]
    w = mask[order]
    cum_tp = jnp.cumsum(wp)
    cum_n = jnp.cumsum(w)
    # map every index to its tie-group END (last index with an equal score)
    right = jnp.searchsorted(s_asc, s_asc, side="right") - 1
    tp = cum_tp[right]
    n_at = cum_n[right]
    n_pos = wpos.sum()
    prec = jnp.where(n_at > 0, tp / jnp.maximum(n_at, 1e-30), 1.0)
    rec = tp / jnp.maximum(n_pos, 1e-30)
    r = jnp.concatenate([jnp.zeros(1, rec.dtype), rec])
    p = jnp.concatenate([jnp.ones(1, prec.dtype), prec])
    area = ((r[1:] - r[:-1]) * (p[1:] + p[:-1]) * 0.5).sum()
    return jnp.where(n_pos > 0, area, 0.0)


def aupr_binned_dev(y: jnp.ndarray, scores: jnp.ndarray, mask: jnp.ndarray,
                    n_bins: int = 4096):
    """Sort-free AuPR for out-of-core row counts: scores quantize to
    `n_bins` buckets, positive/total weights histogram via one-hot
    matmuls (MXU — `argsort` + `searchsorted` in `aupr_dev` SERIALIZE on
    TPU and take minutes at 10M rows), then the tie-grouped PR trapezoid
    runs over the 4096 bucket boundaries. Equivalent to `aupr_dev` with
    scores rounded to 1/n_bins — at 10M rows every bucket holds thousands
    of samples, so the quantization error is far below fold noise."""
    s = jnp.clip(scores, 0.0, 1.0)
    b = jnp.minimum((s * n_bins).astype(jnp.int32), n_bins - 1)
    n = b.shape[0]
    wpos = (mask * y).astype(jnp.bfloat16)
    wall = mask.astype(jnp.bfloat16)
    # chunked histogram: a full (n, bins) one-hot would be 84 GB at 10M
    # rows; scan row chunks, each chunk's one-hot contracted immediately
    chunk = 65_536
    pad = (-n) % chunk
    if pad:
        b = jnp.concatenate([b, jnp.zeros(pad, jnp.int32)])
        wpos = jnp.concatenate([wpos, jnp.zeros(pad, jnp.bfloat16)])
        wall = jnp.concatenate([wall, jnp.zeros(pad, jnp.bfloat16)])
    n_chunks = (n + pad) // chunk

    def body(acc, args):
        b_c, wp_c, wa_c = args
        B = jax.nn.one_hot(b_c, n_bins, dtype=jnp.bfloat16)
        h = jnp.matmul(jnp.stack([wp_c, wa_c]), B,
                       preferred_element_type=jnp.float32)  # (2, bins)
        return acc + h, None

    acc, _ = jax.lax.scan(
        body, jnp.zeros((2, n_bins), jnp.float32),
        (b.reshape(n_chunks, chunk), wpos.reshape(n_chunks, chunk),
         wall.reshape(n_chunks, chunk)))
    hp, ha = acc[0], acc[1]
    # descending-score cumulative = reversed cumsum
    tp = jnp.cumsum(hp[::-1])
    n_at = jnp.cumsum(ha[::-1])
    n_pos = tp[-1]
    prec = jnp.where(n_at > 0, tp / jnp.maximum(n_at, 1e-30), 1.0)
    rec = tp / jnp.maximum(n_pos, 1e-30)
    r = jnp.concatenate([jnp.zeros(1, rec.dtype), rec])
    p = jnp.concatenate([jnp.ones(1, prec.dtype), prec])
    area = ((r[1:] - r[:-1]) * (p[1:] + p[:-1]) * 0.5).sum()
    return jnp.where(n_pos > 0, area, 0.0)


def binary_confusion_dev(y, scores, mask, threshold: float = 0.5):
    """Weighted TP/TN/FP/FN and the derived point metrics at `threshold`."""
    pred = (scores >= threshold).astype(scores.dtype)
    pos = (y > 0.5).astype(scores.dtype)
    tp = _masked_sum(pred * pos, mask)
    fp = _masked_sum(pred * (1 - pos), mask)
    fn = _masked_sum((1 - pred) * pos, mask)
    tn = _masked_sum((1 - pred) * (1 - pos), mask)
    n = jnp.maximum(mask.sum(), 1.0)
    precision = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1e-30), 0.0)
    recall = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1e-30), 0.0)
    f1 = jnp.where(precision + recall > 0,
                   2 * precision * recall
                   / jnp.maximum(precision + recall, 1e-30), 0.0)
    error = (fp + fn) / n
    return {"Precision": precision, "Recall": recall, "F1": f1,
            "Error": error, "TP": tp, "TN": tn, "FP": fp, "FN": fn}


def multiclass_dev(y, pred, mask, n_classes: int):
    """Weighted-average Precision/Recall/F1 + Error over a masked confusion
    matrix (multiclass_metrics parity; `n_classes` static — extra empty
    classes carry zero support weight so any upper bound is exact)."""
    yi = jnp.clip(y.astype(jnp.int32), 0, n_classes - 1)
    pi = jnp.clip(pred.astype(jnp.int32), 0, n_classes - 1)
    conf = jnp.zeros((n_classes, n_classes), jnp.float32).at[yi, pi].add(mask)
    tp = jnp.diagonal(conf)
    support = conf.sum(axis=1)
    pred_count = conf.sum(axis=0)
    prec_c = jnp.where(pred_count > 0, tp / jnp.maximum(pred_count, 1e-30), 0.0)
    rec_c = jnp.where(support > 0, tp / jnp.maximum(support, 1e-30), 0.0)
    f1_c = jnp.where(prec_c + rec_c > 0,
                     2 * prec_c * rec_c / jnp.maximum(prec_c + rec_c, 1e-30), 0.0)
    w = support / jnp.maximum(support.sum(), 1.0)
    err = 1.0 - tp.sum() / jnp.maximum(mask.sum(), 1.0)
    return {"Precision": (prec_c * w).sum(), "Recall": (rec_c * w).sum(),
            "F1": (f1_c * w).sum(), "Error": err}


def regression_dev(y, pred, mask):
    """Weighted RMSE/MSE/MAE/R2 (regression_metrics parity)."""
    n = jnp.maximum(mask.sum(), 1.0)
    err = (pred - y) * mask
    mse = (err ** 2).sum() / n
    mae = jnp.abs(err).sum() / n
    y_mean = _masked_sum(y, mask) / n
    ss_tot = _masked_sum((y - y_mean) ** 2, mask)
    ss_res = (err ** 2).sum()
    r2 = jnp.where(ss_tot > 0, 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30), 0.0)
    return {"RMSE": jnp.sqrt(mse), "MSE": mse, "MAE": mae, "R2": r2}


def _binary_scores(pred: dict) -> jnp.ndarray:
    prob = pred.get("probability")
    if prob is not None and prob.ndim == 2 and prob.shape[1] >= 2:
        return prob[:, 1]
    return pred["prediction"]


def make_device_metric(evaluator, n_classes: int | None = None):
    """metric_fn(y, pred_dict, val_mask) -> scalar for the sweep program, or
    None when `evaluator` has no device kernel (LambdaEvaluator etc. fall
    back to the host path in parallel/sweep.py)."""
    from transmogrifai_tpu.evaluators.evaluators import (
        BinaryClassificationEvaluator, MultiClassificationEvaluator,
        RegressionEvaluator)

    metric = evaluator.default_metric

    if isinstance(evaluator, BinaryClassificationEvaluator):
        threshold = evaluator.threshold

        def fn(y, pred, mask):
            s = _binary_scores(pred)
            if metric == "AuPR":
                return aupr_dev(y, s, mask)
            if metric == "AuROC":
                return auroc_dev(y, s, mask)
            return binary_confusion_dev(y, s, mask, threshold)[metric]
        return fn

    if isinstance(evaluator, MultiClassificationEvaluator):
        if n_classes is None:
            return None

        def fn(y, pred, mask):
            return multiclass_dev(y, pred["prediction"], mask, n_classes)[metric]
        return fn

    if isinstance(evaluator, RegressionEvaluator):
        def fn(y, pred, mask):
            return regression_dev(y, pred["prediction"], mask)[metric]
        return fn

    return None
