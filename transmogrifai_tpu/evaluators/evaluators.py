"""Evaluator stages: score a Prediction column against a label column.

Reference parity: `core/.../evaluators/OpEvaluatorBase.scala`,
`Evaluators.scala:40-316` thin factories. An Evaluator is not a DAG stage;
it consumes (label Column, prediction Column) and returns a metrics
dataclass. `default_metric` names the value used for model selection
(larger-is-better handled via `is_larger_better`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators.metrics import (
    binary_metrics, multiclass_metrics, regression_metrics)


class Evaluator:
    name: str = "evaluator"
    default_metric: str = ""
    is_larger_better: bool = True

    def evaluate(self, label: Column, prediction: Column):
        raise NotImplementedError

    def metric_value(self, label: Column, prediction: Column) -> float:
        m = self.evaluate(label, prediction).to_json()
        return float(m[self.default_metric])


def _label_array(label: Column) -> np.ndarray:
    return np.asarray(label.data["value"], dtype=np.float64)


class BinaryClassificationEvaluator(Evaluator):
    """AuPR default, matching BinaryClassificationModelSelector's default."""

    name = "binEval"
    default_metric = "AuPR"

    def __init__(self, metric: str = "AuPR", threshold: float = 0.5):
        self.default_metric = metric
        self.threshold = threshold
        self.is_larger_better = metric not in ("Error",)

    def evaluate(self, label: Column, prediction: Column):
        y = _label_array(label)
        prob = np.asarray(prediction.data["probability"])
        if prob.ndim == 2 and prob.shape[1] >= 2:
            scores = prob[:, 1]
        else:
            scores = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return binary_metrics(y, scores, self.threshold)


class MultiClassificationEvaluator(Evaluator):
    """F1 default (OpMultiClassificationEvaluator)."""

    name = "multiEval"
    default_metric = "F1"

    def __init__(self, metric: str = "F1"):
        self.default_metric = metric
        self.is_larger_better = metric not in ("Error",)

    def evaluate(self, label: Column, prediction: Column):
        y = _label_array(label)
        pred = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return multiclass_metrics(y, pred)


class RegressionEvaluator(Evaluator):
    """RMSE default, smaller is better (OpRegressionEvaluator)."""

    name = "regEval"
    default_metric = "RMSE"
    is_larger_better = False

    def __init__(self, metric: str = "RMSE"):
        self.default_metric = metric
        self.is_larger_better = metric in ("R2",)

    def evaluate(self, label: Column, prediction: Column):
        y = _label_array(label)
        pred = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return regression_metrics(y, pred)


class BinScoreEvaluator(Evaluator):
    """Score-decile calibration (`OpBinScoreEvaluator.scala:53`)."""

    name = "binScoreEval"
    default_metric = "BrierScore"
    is_larger_better = False

    def __init__(self, num_bins: int = 10):
        self.num_bins = num_bins

    def evaluate(self, label: Column, prediction: Column):
        from transmogrifai_tpu.evaluators.metrics import bin_score_metrics
        y = _label_array(label)
        prob = np.asarray(prediction.data["probability"])
        scores = (prob[:, 1] if prob.ndim == 2 and prob.shape[1] >= 2
                  else np.asarray(prediction.data["prediction"], dtype=np.float64))
        return bin_score_metrics(y, scores, self.num_bins)


class ForecastEvaluator(Evaluator):
    """SMAPE/seasonal-error metrics (`OpForecastEvaluator.scala:59`)."""

    name = "forecastEval"
    default_metric = "SMAPE"
    is_larger_better = False

    def __init__(self, seasonal_window: int = 1):
        self.seasonal_window = seasonal_window

    def evaluate(self, label: Column, prediction: Column):
        from transmogrifai_tpu.evaluators.metrics import forecast_metrics
        y = _label_array(label)
        pred = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return forecast_metrics(y, pred, self.seasonal_window)


class LambdaEvaluator(Evaluator):
    """Custom-metric evaluator (`Evaluators.scala` custom lambda factories)."""

    def __init__(self, name: str, fn, is_larger_better: bool = True):
        self.name = name
        self.default_metric = name
        self.fn = fn
        self.is_larger_better = is_larger_better

    def evaluate(self, label: Column, prediction: Column):
        value = float(self.fn(label, prediction))
        metric_name = self.default_metric

        class _M:
            def to_json(self) -> dict:
                return {metric_name: value}

        return _M()


class Evaluators:
    """Thin factories mirroring `Evaluators.scala:40-316`:
    `Evaluators.BinaryClassification.au_pr()` etc."""

    class BinaryClassification:
        @staticmethod
        def au_pr():
            return BinaryClassificationEvaluator(metric="AuPR")

        @staticmethod
        def au_roc():
            return BinaryClassificationEvaluator(metric="AuROC")

        @staticmethod
        def precision():
            return BinaryClassificationEvaluator(metric="Precision")

        @staticmethod
        def recall():
            return BinaryClassificationEvaluator(metric="Recall")

        @staticmethod
        def f1():
            return BinaryClassificationEvaluator(metric="F1")

        @staticmethod
        def error():
            return BinaryClassificationEvaluator(metric="Error")

        @staticmethod
        def brier_score():
            return BinScoreEvaluator()

        @staticmethod
        def custom(metric_name: str, fn, is_larger_better: bool = True):
            return LambdaEvaluator(metric_name, fn, is_larger_better)

    class MultiClassification:
        @staticmethod
        def f1():
            return MultiClassificationEvaluator(metric="F1")

        @staticmethod
        def precision():
            return MultiClassificationEvaluator(metric="Precision")

        @staticmethod
        def recall():
            return MultiClassificationEvaluator(metric="Recall")

        @staticmethod
        def error():
            return MultiClassificationEvaluator(metric="Error")

        @staticmethod
        def custom(metric_name: str, fn, is_larger_better: bool = True):
            return LambdaEvaluator(metric_name, fn, is_larger_better)

    class Regression:
        @staticmethod
        def rmse():
            return RegressionEvaluator(metric="RMSE")

        @staticmethod
        def mse():
            return RegressionEvaluator(metric="MSE")

        @staticmethod
        def mae():
            return RegressionEvaluator(metric="MAE")

        @staticmethod
        def r2():
            return RegressionEvaluator(metric="R2")

        @staticmethod
        def custom(metric_name: str, fn, is_larger_better: bool = True):
            return LambdaEvaluator(metric_name, fn, is_larger_better)

    class Forecast:
        @staticmethod
        def smape(seasonal_window: int = 1):
            return ForecastEvaluator(seasonal_window)
