"""Evaluator stages: score a Prediction column against a label column.

Reference parity: `core/.../evaluators/OpEvaluatorBase.scala`,
`Evaluators.scala:40-316` thin factories. An Evaluator is not a DAG stage;
it consumes (label Column, prediction Column) and returns a metrics
dataclass. `default_metric` names the value used for model selection
(larger-is-better handled via `is_larger_better`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators.metrics import (
    binary_metrics, multiclass_metrics, regression_metrics)


class Evaluator:
    name: str = "evaluator"
    default_metric: str = ""
    is_larger_better: bool = True

    def evaluate(self, label: Column, prediction: Column):
        raise NotImplementedError

    def metric_value(self, label: Column, prediction: Column) -> float:
        m = self.evaluate(label, prediction).to_json()
        return float(m[self.default_metric])


def _label_array(label: Column) -> np.ndarray:
    return np.asarray(label.data["value"], dtype=np.float64)


class BinaryClassificationEvaluator(Evaluator):
    """AuPR default, matching BinaryClassificationModelSelector's default."""

    name = "binEval"
    default_metric = "AuPR"

    def __init__(self, metric: str = "AuPR", threshold: float = 0.5):
        self.default_metric = metric
        self.threshold = threshold
        self.is_larger_better = metric not in ("Error",)

    def evaluate(self, label: Column, prediction: Column):
        y = _label_array(label)
        prob = np.asarray(prediction.data["probability"])
        if prob.ndim == 2 and prob.shape[1] >= 2:
            scores = prob[:, 1]
        else:
            scores = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return binary_metrics(y, scores, self.threshold)


class MultiClassificationEvaluator(Evaluator):
    """F1 default (OpMultiClassificationEvaluator)."""

    name = "multiEval"
    default_metric = "F1"

    def __init__(self, metric: str = "F1"):
        self.default_metric = metric
        self.is_larger_better = metric not in ("Error",)

    def evaluate(self, label: Column, prediction: Column):
        y = _label_array(label)
        pred = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return multiclass_metrics(y, pred)


class RegressionEvaluator(Evaluator):
    """RMSE default, smaller is better (OpRegressionEvaluator)."""

    name = "regEval"
    default_metric = "RMSE"
    is_larger_better = False

    def __init__(self, metric: str = "RMSE"):
        self.default_metric = metric
        self.is_larger_better = metric in ("R2",)

    def evaluate(self, label: Column, prediction: Column):
        y = _label_array(label)
        pred = np.asarray(prediction.data["prediction"], dtype=np.float64)
        return regression_metrics(y, pred)
