"""Process-wide runtime metrics registry (promoted from serving/metrics.py).

The ML Goodput line of work (PAPERS.md) argues that fleet efficiency is
lost to UNTRACKED stalls — queueing, recompiles, shed load — not FLOPs;
this registry makes those visible. It is deliberately stdlib-only (no
prometheus_client dependency): Counter / Gauge / Histogram with labels,
exported two ways from one source of truth:

- ``registry.to_json()``  — structured dict for programmatic checks and
  the runner's metrics files;
- ``registry.to_prometheus()`` — the Prometheus text exposition format
  (``# HELP`` / ``# TYPE`` headers, cumulative ``_bucket{le=...}``
  series, label values escaped per the spec), scrapeable from the HTTP
  frontend's ``/metrics``.

Originally this lived in ``serving/`` and only serving counters reached
the ``/metrics`` surface; train-time ingest uploads, retry pressure, and
fit counts were invisible to the same scrape. It now lives in ``obs/``
with a process-global default instance (`REGISTRY` / `get_registry()`)
that train/ingest/runtime paths register into, and the serving frontend
exposes alongside each service's own registry. ``serving.metrics``
re-exports everything for compatibility.

Histograms use fixed log-spaced buckets so p50/p95/p99 estimates are
O(buckets) with bounded memory — no reservoir, safe under sustained
traffic. Quantiles interpolate linearly inside the winning bucket.

Histograms also carry TRACE-ID EXEMPLARS (one per bucket,
last-write-wins): ``observe(v, exemplar=trace_id)`` pins the id of a
concrete kept trace to the bucket the observation landed in, and the
Prometheus exposition renders it OpenMetrics-style
(``... 42 # {trace_id="..."} 0.0041 1699999999.5``) so a p99 spike on
a dashboard links straight to the request trace that lives in that
bucket. The JSON export mirrors them under ``exemplars``.

All mutation is lock-protected: the batcher thread, HTTP worker threads,
ingest workers, and scrapers hit the same registry concurrently.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# default latency ladder (seconds): 100 us .. 60 s, roughly 2-2.5x steps
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# microsecond-resolution ladder (seconds): 1 us .. 1 s. Host-side phase
# timings (request parse, pad writes, demux) run in TENS of µs — on the
# default ladder they all land in the first bucket and the interpolated
# p50 reads ~50 µs no matter what the true values are, which is exactly
# how a 3x parse win becomes invisible on a dashboard.
MICRO_LATENCY_BUCKETS = (
    1e-6, 2.5e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_LATENCY_BUCKETS", "MICRO_LATENCY_BUCKETS",
           "REGISTRY", "get_registry"]


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, double quote and
    newline must be escaped or the series line is unparseable."""
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    """# HELP lines escape backslash and newline (quotes are legal)."""
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(key: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    """Monotonic counter (requests, sheds, errors, swaps)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, in-flight batches, versions)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with interpolated quantiles.

    `bounds` are the inclusive upper edges of each bucket; an implicit
    +inf bucket catches the tail. `observe()` is O(buckets) worst case
    (linear scan — the ladders here are ~20 wide, not worth bisect).
    """

    def __init__(self, bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must be strictly increasing")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +inf bucket
        self._sum = 0.0
        self._count = 0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        # bucket index -> (exemplar_id, value, epoch_ts); last-write-wins
        self._exemplars: Dict[int, Tuple[str, float, float]] = {}

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        v = float(value)
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), v, time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0 < q < 1) from bucket counts, or None
        when empty. Interpolates within the winning bucket; the +inf
        bucket reports the observed max (the honest upper bound)."""
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile q must be in (0,1), got {q}")
        with self._lock:
            total = self._count
            if total == 0:
                return None
            rank = q * total
            cum = 0.0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = (self.bounds[i] if i < len(self.bounds)
                      else (self._max if self._max is not None else lo))
                if cum + c >= rank:
                    frac = (rank - cum) / c
                    return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                cum += c
            return self._max

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            count, total = self._count, self._sum
            mn, mx = self._min, self._max
        out: Dict[str, Any] = {
            "count": count, "sum": round(total, 6),
            "mean": round(total / count, 6) if count else None,
            "min": mn, "max": mx,
        }
        for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            v = self.quantile(q)
            out[name] = round(v, 6) if v is not None else None
        return out

    def bucket_counts(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count) pairs, Prometheus-style, ending
        with (+inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out: List[Tuple[float, int]] = []
        cum = 0
        for b, c in zip(self.bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((float("inf"), cum + counts[-1]))
        return out

    def merge_from(self, other: "Histogram") -> None:
        """Fold `other`'s observations into this histogram (the
        cross-replica /metrics merge). Bucket ladders must agree —
        merging a µs ladder into a default ladder would silently
        misplace every count."""
        if self.bounds != other.bounds:
            raise ValueError(
                f"histogram bounds differ: {len(self.bounds)} vs "
                f"{len(other.bounds)} buckets")
        with other._lock:
            counts = list(other._counts)
            total, count = other._sum, other._count
            mn, mx = other._min, other._max
            exemplars = dict(other._exemplars)
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += total
            self._count += count
            if mn is not None:
                self._min = mn if self._min is None else min(self._min, mn)
            if mx is not None:
                self._max = mx if self._max is None else max(self._max, mx)
            for i, ex in exemplars.items():
                # first writer wins: an exemplar is one concrete trace,
                # any replica's is as good as another's
                self._exemplars.setdefault(i, ex)

    def exemplars(self) -> List[Tuple[float, str, float, float]]:
        """(bucket_upper_bound, exemplar_id, value, epoch_ts) for every
        bucket holding one; the +inf bucket reports float('inf')."""
        with self._lock:
            items = sorted(self._exemplars.items())
        out: List[Tuple[float, str, float, float]] = []
        for i, (eid, v, ts) in items:
            bound = self.bounds[i] if i < len(self.bounds) else float("inf")
            out.append((bound, eid, v, ts))
        return out

    # -- full-fidelity wire form (fleet federation) -------------------------- #

    def state(self) -> Dict[str, Any]:
        """Lossless JSON-able form: raw per-bucket counts (NOT the
        cumulative export form, and not `summary()`'s quantile digests)
        plus bounds/sum/count/min/max/exemplars — exactly what
        `merge_from` needs on the other side of a file."""
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "sum": self._sum, "count": self._count,
                "min": self._min, "max": self._max,
                "exemplars": [[i, eid, v, ts]
                              for i, (eid, v, ts)
                              in sorted(self._exemplars.items())],
            }

    @classmethod
    def from_state(cls, d: Dict[str, Any]) -> "Histogram":
        """Inverse of `state()`. Raises ValueError on malformed input
        (wrong counts length, bad bounds) — a corrupt snapshot must not
        silently misplace buckets."""
        h = cls(bounds=tuple(float(b) for b in d["bounds"]))
        counts = [int(c) for c in d["counts"]]
        if len(counts) != len(h.bounds) + 1:
            raise ValueError(
                f"histogram state has {len(counts)} counts for "
                f"{len(h.bounds)} bounds")
        with h._lock:
            h._counts = counts
            h._sum = float(d.get("sum") or 0.0)
            h._count = int(d.get("count") or 0)
            mn, mx = d.get("min"), d.get("max")
            h._min = float(mn) if mn is not None else None
            h._max = float(mx) if mx is not None else None
            for ex in d.get("exemplars") or []:
                i, eid, v, ts = ex
                h._exemplars[int(i)] = (str(eid), float(v), float(ts))
        return h


class MetricsRegistry:
    """Named, labeled metric families with dual JSON/Prometheus export."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> {"type", "help", "series": {label_key: metric}}
        self._families: Dict[str, Dict[str, Any]] = {}

    def _get(self, name: str, mtype: str, help_: str, labels: Dict[str, str],
             factory):
        key = _label_key(labels or {})
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = {"type": mtype, "help": help_, "series": {}}
                self._families[name] = fam
            elif fam["type"] != mtype:
                raise ValueError(
                    f"metric {name!r} already registered as {fam['type']}")
            metric = fam["series"].get(key)
            if metric is None:
                metric = factory()
                fam["series"][key] = metric
            return metric

    def counter(self, name: str, help: str = "",
                **labels: Any) -> Counter:
        return self._get(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels: Any) -> Gauge:
        return self._get(name, "gauge", help, labels, Gauge)

    def histogram(self, name: str, help: str = "",
                  bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  **labels: Any) -> Histogram:
        return self._get(name, "histogram", help, labels,
                         lambda: Histogram(bounds))

    # -- read-side lookups (SLO engine, tests) ------------------------------ #

    def find(self, name: str, **labels: Any):
        """The live metric object for (name, labels), or None — a READ
        that never mints a series (the SLO engine polls families that
        may not exist yet)."""
        key = _label_key({str(k): v for k, v in labels.items()})
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam["series"].get(key)

    def find_all(self, name: str, **label_filter: Any) -> List[Any]:
        """Every live metric of a family whose labels match each (k, v)
        in `label_filter` (empty filter = all series) — how the SLO
        latency source aggregates a per-tenant-labeled histogram family
        without knowing the tenant set."""
        want = {str(k): str(v) for k, v in label_filter.items()}
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return []
            series = dict(fam["series"])
        return [metric for key, metric in series.items()
                if all(dict(key).get(k) == v for k, v in want.items())]

    def sum_family(self, name: str, **label_filter: Any) -> float:
        """Sum of a family's series values, optionally restricted to
        series whose labels match every (k, v) in `label_filter` —
        how the SLO engine reads 'total errors for tenant=gold' off
        labeled counters without enumerating reasons/codes."""
        want = {str(k): str(v) for k, v in label_filter.items()}
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return 0.0
            series = dict(fam["series"])
        total = 0.0
        for key, metric in series.items():
            labels = dict(key)
            if all(labels.get(k) == v for k, v in want.items()):
                total += metric.value if hasattr(metric, "value") else 0.0
        return total

    # -- cross-replica aggregation ----------------------------------------- #

    def merge(self, other: "MetricsRegistry",
              **extra_labels: Any) -> "MetricsRegistry":
        """Fold `other`'s families into this registry — how the fleet
        router's /metrics exposes a FLEET-WIDE view over K per-replica
        registries. Semantics per metric type:

        - counters SUM into the same-labeled series (fleet totals);
        - gauges keep per-replica identity: `extra_labels` (e.g.
          ``replica="r1"``) are added so two replicas' queue depths
          never average into a number nobody measured;
        - histograms merge bucket counts/sums when ladders agree; a
          ladder mismatch falls back to a separate `extra_labels`
          series instead of corrupting the buckets.

        Returns self, so K registries chain:
        ``m.merge(a.registry, replica="a").merge(b.registry, ...)``.
        A family whose TYPE conflicts with an existing name is skipped
        (scrapes must never 500 over one bad series).
        """
        with other._lock:
            families = {n: (f["type"], f["help"], dict(f["series"]))
                        for n, f in other._families.items()}
        for name, (mtype, help_, series) in families.items():
            for key, metric in series.items():
                labels = dict(key)
                try:
                    if mtype == "counter":
                        self.counter(name, help_, **labels).inc(
                            metric.value)
                    elif mtype == "gauge":
                        self.gauge(name, help_,
                                   **{**labels, **extra_labels}).set(
                            metric.value)
                    else:
                        target = self.histogram(
                            name, help_, bounds=metric.bounds, **labels)
                        try:
                            target.merge_from(metric)
                        except ValueError:
                            self.histogram(
                                name, help_, bounds=metric.bounds,
                                **{**labels, **extra_labels},
                            ).merge_from(metric)
                except ValueError:
                    # type conflict across registries: keep the scrape
                    # alive, drop the conflicting series
                    continue
        return self

    # -- full-fidelity wire form (fleet federation) ------------------------- #

    def snapshot(self) -> Dict[str, Any]:
        """Lossless JSON-able form of every family, for cross-process
        publication. Unlike `to_json()` (quantile digests, no raw
        buckets) a snapshot round-trips through `from_snapshot` and
        merges bucket-exact on the reader side."""
        with self._lock:
            families = {n: (f["type"], f["help"], dict(f["series"]))
                        for n, f in self._families.items()}
        out: Dict[str, Any] = {}
        for name, (mtype, help_, series) in sorted(families.items()):
            entries = []
            for key, metric in sorted(series.items()):
                entry: Dict[str, Any] = {"labels": dict(key)}
                if mtype == "histogram":
                    entry["state"] = metric.state()
                else:
                    entry["value"] = metric.value
                entries.append(entry)
            out[name] = {"type": mtype, "help": help_, "series": entries}
        return out

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "MetricsRegistry":
        """Rebuild a registry from `snapshot()` output. Malformed
        series are skipped (a reader aggregating K replica files must
        survive one bad snapshot), malformed top-level shapes yield an
        empty registry."""
        reg = cls()
        if not isinstance(snap, dict):
            return reg
        for name, fam in snap.items():
            if not isinstance(fam, dict):
                continue
            mtype = fam.get("type")
            help_ = str(fam.get("help") or "")
            for entry in fam.get("series") or []:
                try:
                    labels = dict(entry.get("labels") or {})
                    if mtype == "counter":
                        reg.counter(name, help_, **labels).inc(
                            float(entry["value"]))
                    elif mtype == "gauge":
                        reg.gauge(name, help_, **labels).set(
                            float(entry["value"]))
                    elif mtype == "histogram":
                        h = Histogram.from_state(entry["state"])
                        reg.histogram(name, help_, bounds=h.bounds,
                                      **labels).merge_from(h)
                    else:
                        continue
                except (KeyError, TypeError, ValueError):
                    continue
        return reg

    # -- export ----------------------------------------------------------- #

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            families = {n: (f["type"], f["help"], dict(f["series"]))
                        for n, f in self._families.items()}
        out: Dict[str, Any] = {}
        for name, (mtype, help_, series) in sorted(families.items()):
            entries = []
            for key, metric in sorted(series.items()):
                labels = dict(key)
                if mtype == "histogram":
                    entry: Dict[str, Any] = {"labels": labels,
                                             **metric.summary()}
                    ex = metric.exemplars()
                    if ex:
                        entry["exemplars"] = [
                            {"le": ("+Inf" if b == float("inf") else b),
                             "trace_id": eid, "value": v,
                             "ts": round(ts, 3)}
                            for b, eid, v, ts in ex]
                else:
                    entry = {"labels": labels, "value": metric.value}
                entries.append(entry)
            out[name] = {"type": mtype, "help": help_, "series": entries}
        return out

    def to_prometheus(self) -> str:
        with self._lock:
            families = {n: (f["type"], f["help"], dict(f["series"]))
                        for n, f in self._families.items()}
        lines: List[str] = []
        for name, (mtype, help_, series) in sorted(families.items()):
            if help_:
                lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {mtype}")
            for key, metric in sorted(series.items()):
                if mtype == "histogram":
                    # per-bucket trace-id exemplars, OpenMetrics syntax
                    # (` # {trace_id="..."} value ts` after the bucket
                    # sample) — we control both ends of this scrape
                    ex = {b: (eid, v, ts)
                          for b, eid, v, ts in metric.exemplars()}
                    for bound, cum in metric.bucket_counts():
                        le = "+Inf" if bound == float("inf") else repr(bound)
                        le_label = 'le="%s"' % le
                        line = (f"{name}_bucket"
                                f"{_fmt_labels(key, le_label)} {cum}")
                        if bound in ex:
                            eid, v, ts = ex[bound]
                            line += (f' # {{trace_id='
                                     f'"{_escape_label_value(eid)}"}} '
                                     f"{v} {round(ts, 3)}")
                        lines.append(line)
                    lines.append(
                        f"{name}_sum{_fmt_labels(key)} {metric.sum}")
                    lines.append(
                        f"{name}_count{_fmt_labels(key)} {metric.count}")
                else:
                    lines.append(
                        f"{name}{_fmt_labels(key)} {metric.value}")
        return "\n".join(lines) + "\n"


# -- process-global registry -------------------------------------------------- #

# The single process-wide surface train/ingest/runtime counters land on.
# Serving keeps per-service registries (isolated hot paths, testable in
# parallel) and the HTTP frontend exposes BOTH on /metrics.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
