"""`make slo-smoke`: the observability plane's end-to-end acceptance gate.

One scripted scenario over a real served model with ONE injected
device-error storm, asserting the four contracts this plane exists for:

1. **traceparent roundtrip** — a caller-supplied W3C ``traceparent``
   comes back in the response headers with the SAME trace id, and the
   process trace contains that request's queue-wait / assemble (with a
   nonzero ``parse`` child) / pad / device-dispatch spans parented
   under the request root;
2. **tail sampling** — after a burst of healthy traffic plus the storm,
   the sampler KEPT every error trace and DROPPED head-sampled
   successes (kept < sent, dropped > 0, all error traces present);
3. **flight recorder** — the breaker-open dump exists, is a VALID
   Chrome trace (`validate_chrome_trace`), and contains the failing
   dispatch spans;
4. **SLO burn rate** — the availability SLO's multi-window alert FIRES
   during the storm and CLEARS after recovery.

Run: ``JAX_PLATFORMS=cpu python -m transmogrifai_tpu.obs.slo_smoke``
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from typing import Any, Dict, List

D = 3
ROW = {f"x{j}": 0.2 * (j + 1) for j in range(D)}


def _train(tmp: str) -> str:
    import numpy as np

    import transmogrifai_tpu.types as t
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.ops.numeric import RealVectorizer
    from transmogrifai_tpu.workflow import Workflow

    rng = np.random.default_rng(31)
    n = 160
    X = rng.normal(size=(n, D))
    beta = rng.normal(size=D)
    ds = Dataset({**{f"x{j}": X[:, j] for j in range(D)},
                  "y": (X @ beta > 0).astype(np.float64)},
                 {**{f"x{j}": t.Real for j in range(D)},
                  "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    pred = OpLogisticRegression(max_iter=40).set_input(
        label, vec).get_output()
    Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train().save(f"{tmp}/model")
    return f"{tmp}/model"


def _post_score(port: int, headers: Dict[str, str]):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/score",
        data=json.dumps({"rows": [dict(ROW)],
                         "deadline_ms": 10_000}).encode(),
        headers={"Content-Type": "application/json", **headers})
    return urllib.request.urlopen(req, timeout=30)


def main() -> int:  # noqa: C901 (one linear acceptance script)
    os.environ.setdefault("TRANSMOGRIFAI_PERF_MODEL", "0")
    from transmogrifai_tpu.obs import flight
    from transmogrifai_tpu.obs.export import validate_chrome_trace
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.runtime.faults import (
        SITE_DEVICE_DISPATCH, FaultPlan, FaultSpec)
    from transmogrifai_tpu.serving.http import serve
    from transmogrifai_tpu.serving.service import (
        ScoringService, ServingConfig)

    with tempfile.TemporaryDirectory(prefix="slo-smoke-") as tmp:
        model_dir = _train(tmp)
        flight.get_recorder().configure(
            dump_dir=os.path.join(tmp, "flight"), min_interval_s=0.0)
        svc = ScoringService.from_path(model_dir, config=ServingConfig(
            max_batch=4, batch_wait_ms=1.0, max_queue=256,
            resilience={"window": 32, "min_window": 8,
                        "breaker_failures": 3,
                        "half_open_after_s": 0.25, "probe_successes": 1,
                        "watchdog_period_s": 0.05,
                        "watchdog_stall_s": 2.0},
            tracing={"head_sample_every": 16,
                     "min_latency_samples": 10_000},
            slo={"slos": [{"name": "availability",
                           "kind": "availability",
                           "objective": 0.999}],
                 "windows": [[2.4, 1.2, 2.0, "page"]],
                 "eval_period_s": 0.05}))
        svc.start()
        server, thread = serve(svc, block=False)
        port = server.port
        failures: List[str] = []

        def check(ok: bool, msg: str) -> None:
            if not ok:
                failures.append(msg)

        try:
            # -- 1. traceparent roundtrip --------------------------------- #
            caller_tp = ("00-" + "ab" * 16 + "-" + "cd" * 8 + "-01")
            resp = _post_score(port, {"traceparent": caller_tp})
            echo = resp.headers.get("traceparent") or ""
            body = json.loads(resp.read())
            check(echo.split("-")[1] == "ab" * 16,
                  f"traceparent echo lost the caller's trace id: {echo}")
            check(body.get("trace_id") == "ab" * 16,
                  f"body trace_id mismatch: {body.get('trace_id')}")
            spans = TRACER.trace_spans("ab" * 16)
            names = {sp.name for sp in spans}
            want = {"serving:request", "serving:assemble", "serving:parse",
                    "serving:queue_wait", "serving:pad",
                    "serving:device_dispatch", "serving:demux"}
            check(want <= names,
                  f"request trace missing phases: {sorted(want - names)}")
            root = next(sp for sp in spans
                        if sp.name == "serving:request")
            parse = next(sp for sp in spans if sp.name == "serving:parse")
            check(parse.duration_s > 0, "parse child has zero duration")
            by_id = {sp.span_id: sp for sp in spans}
            for sp in spans:
                if sp is root:
                    continue
                anc = sp
                while anc.parent_id is not None and anc.parent_id in by_id:
                    anc = by_id[anc.parent_id]
                check(anc is root,
                      f"{sp.name} not parented under the request root")

            # -- 2. healthy burst + storm --------------------------------- #
            sampler = svc.sampler
            kept0, dropped0 = sampler.kept, sampler.dropped
            for _ in range(48):
                _post_score(port, {})
            check(sampler.dropped > dropped0,
                  "tail sampler dropped no head-sampled successes")
            kept_healthy = sampler.kept - kept0

            stop = threading.Event()
            pump_errors = [0]

            def pump() -> None:
                while not stop.is_set():
                    try:
                        _post_score(port, {})
                    except Exception:
                        # storm errors are the point: count them so the
                        # SLO has bad samples to judge
                        pump_errors[0] += 1
                    time.sleep(0.004)

            pumper = threading.Thread(target=pump, name="slo-smoke-load",
                                      daemon=True)
            pumper.start()
            storm = FaultPlan([FaultSpec(site=SITE_DEVICE_DISPATCH, at=1,
                                         times=8, kind="error")], seed=0)
            t_storm = time.perf_counter()
            fired_s = cleared_s = None
            with storm.active():
                while time.perf_counter() - t_storm < 10.0:
                    if "availability" in svc.slo_engine.firing():
                        fired_s = time.perf_counter() - t_storm
                        break
                    time.sleep(0.02)
                # wait out the storm (breaker opens, probes recover)
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < 10.0 and storm.fired \
                        and len(storm.fired) < 8:
                    time.sleep(0.02)
            t_clear = time.perf_counter()
            while time.perf_counter() - t_clear < 15.0:
                if "availability" not in svc.slo_engine.firing():
                    cleared_s = time.perf_counter() - t_clear
                    break
                time.sleep(0.02)
            stop.set()
            pumper.join(timeout=5)

            check(fired_s is not None,
                  "availability SLO alert never fired during the storm")
            check(cleared_s is not None,
                  "availability SLO alert never cleared after recovery")

            # -- 3. tail sampling kept the error traces ------------------- #
            err_traces = [sp for sp in TRACER.spans()
                          if sp.name == "serving:request"
                          and sp.error is not None]
            check(len(err_traces) >= 1,
                  "no error request trace survived tail sampling")
            kept_reasons = {sp.attributes.get("sampled")
                            for sp in TRACER.spans()
                            if sp.name == "serving:request"}
            check("error" in kept_reasons,
                  f"no trace kept for reason=error: {kept_reasons}")
            check(kept_healthy < 48,
                  f"head sampling kept every success ({kept_healthy}/48)")

            # -- 4. breaker-open flight dump ------------------------------ #
            breaker_dumps = [d for d in flight.get_recorder().dumps
                             if d.endswith("breaker_open")]
            check(bool(breaker_dumps),
                  "breaker open produced no flight dump")
            if breaker_dumps:
                with open(os.path.join(breaker_dumps[0], "trace.json"),
                          encoding="utf-8") as fh:
                    trace = json.load(fh)
                problems = validate_chrome_trace(trace)
                check(not problems,
                      f"flight dump invalid: {problems[:3]}")
                failing = [ev for ev in trace["traceEvents"]
                           if ev.get("ph") == "X"
                           and ev.get("name") == "serving:device_dispatch"
                           and ev.get("args", {}).get("error")]
                check(len(failing) >= 1,
                      "flight dump has no failing dispatch spans")
        finally:
            server.shutdown()
            server.server_close()
            svc.stop()

        if failures:
            for f in failures:
                print(f"slo-smoke FAILED: {f}", file=sys.stderr)
            return 1
        print(f"slo-smoke OK: traceparent roundtrip + full phase tree "
              f"(parse {parse.duration_s * 1e6:.0f}us); sampler kept "
              f"{sampler.kept}/{sampler.kept + sampler.dropped} traces "
              f"(errors always, successes head-sampled); SLO alert "
              f"fired {fired_s:.3f}s into the storm, cleared "
              f"{cleared_s:.3f}s after recovery; breaker flight dump "
              f"valid with {len(failing)} failing dispatch span(s)")
        return 0


if __name__ == "__main__":
    sys.exit(main())
