"""Crash flight recorder: a bounded ring of recent spans/events/metric
deltas, dumped atomically to a post-mortem artifact when something dies.

The serving plane's incidents (breaker trips, watchdog restarts, shed
storms — PR 12) and the fleet goodput methodology's demand that badput
be ATTRIBUTED (arxiv 2502.06982) both need the same thing at 3am: "what
happened in the 30 seconds before the incident", as one file, written
by the process that was there. Post-hoc log scraping can't answer that
— the interesting spans were tail-sampled into the process ring and the
process may be about to die. So this module keeps a fixed-size,
lock-free ring (CPython ``deque(maxlen=...)`` appends are atomic — no
lock on the hot path) of compact records fed by:

- every FINISHED span on the global tracer (a `Tracer` sink installed
  by `enable()`), which includes every kept request trace and every
  serving batch span;
- every `record_event` emission (retries, faults, breaker transitions,
  SLO alerts) whether or not a span/log was open;
- optional metric-delta notes (`note_metric`) from subsystems that want
  a counter movement in the post-mortem timeline.

`dump(reason)` stages the artifact in a temp sibling and commits it via
`runtime/integrity.commit_staged_dir` — the same crash-consistency
protocol model saves use — so a dump racing a SIGKILL never leaves a
torn half-artifact. The artifact is three files:

- ``trace.json``  — a VALID Chrome/Perfetto trace (own pid +
  process_name metadata, so it merges with other processes' traces and
  passes `validate_chrome_trace`);
- ``events.jsonl`` — the ring's event/metric tail, one JSON per line;
- ``meta.json``   — reason, timestamps, ring occupancy, drop counts.

Dump triggers (wired in `serving/`): watchdog restart, breaker open,
quarantine entry, unhandled scoring-thread death (the watchdog's
``dead`` verdict), SIGTERM (cli `serve`), and on demand via the HTTP
``/debug/dump`` route. Dumps are debounced (`min_interval_s`) so an
error storm produces ONE artifact per window, not one per failure.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from transmogrifai_tpu.obs.trace import TRACER, Span, now_s

log = logging.getLogger(__name__)

__all__ = ["FlightRecorder", "RECORDER", "get_recorder", "enable",
           "disable", "note_event", "note_metric", "request_dump"]

DEFAULT_CAPACITY = 4096
DEFAULT_MIN_INTERVAL_S = 5.0


def default_dump_dir() -> str:
    return os.environ.get(
        "TRANSMOGRIFAI_FLIGHT_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "transmogrifai_tpu", "flight"))


class FlightRecorder:
    """See module docstring. One per process (`RECORDER`); tests build
    their own. `enabled` gates the ring feed so an idle (non-serving)
    process pays a single attribute check per span."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: Optional[str] = None,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S):
        # deque(maxlen) appends/iteration are atomic under the GIL: the
        # scoring thread, HTTP workers, and the watchdog all feed this
        # ring without a lock on the record path
        self._ring: deque = deque(maxlen=int(capacity))
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.min_interval_s = float(min_interval_s)
        self.enabled = False
        self.records_seen = 0          # monotonic; seen - len(ring) = dropped
        self.dumps: List[str] = []     # committed artifact paths
        self.dump_failures = 0
        self._last_dump_s: Optional[float] = None
        self._dump_lock = threading.Lock()  # dumps only — never the feed
        self._seq = 0
        # called (reason, committed_path) after every successful dump;
        # the fleet incident coordinator hooks here. Exceptions eaten —
        # a bad hook must not fail the artifact that already committed.
        self.on_dump: List[Any] = []

    def configure(self, dump_dir: Optional[str] = None,
                  capacity: Optional[int] = None,
                  min_interval_s: Optional[float] = None
                  ) -> "FlightRecorder":
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if min_interval_s is not None:
            self.min_interval_s = float(min_interval_s)
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = int(capacity)
            self._ring = deque(self._ring, maxlen=self.capacity)
        return self

    # -- feed (hot path: no locks) ------------------------------------------ #

    def note_span(self, sp: Span) -> None:
        if not self.enabled:
            return
        self.records_seen += 1
        self._ring.append(("span", sp))

    def note_event(self, name: str,
                   attrs: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:
            return
        self.records_seen += 1
        self._ring.append(("event", (name, now_s(), dict(attrs or {}))))

    def note_metric(self, name: str, value: float,
                    **labels: Any) -> None:
        if not self.enabled:
            return
        self.records_seen += 1
        self._ring.append(
            ("metric", (name, now_s(), float(value), dict(labels))))

    # -- dump ---------------------------------------------------------------- #

    def snapshot(self) -> List[Any]:
        """A consistent-enough copy of the ring (atomic list() under the
        GIL), oldest first."""
        return list(self._ring)

    def dump(self, reason: str, out_dir: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write one post-mortem artifact; returns its committed path,
        or None when debounced/disabled/failed (a flight recorder must
        never take down the thing it is recording). `force` skips the
        debounce (the on-demand /debug/dump route)."""
        if not self.enabled and not force:
            return None
        with self._dump_lock:
            now = time.perf_counter()
            if not force and self._last_dump_s is not None and \
                    now - self._last_dump_s < self.min_interval_s:
                return None
            self._last_dump_s = now
            self._seq += 1
            seq = self._seq
        records = self.snapshot()
        base = out_dir or self.dump_dir or default_dump_dir()
        try:
            return self._write(records, reason, base, seq)
        except Exception:
            self.dump_failures += 1
            log.warning("flight: dump (%s) failed", reason, exc_info=True)
            return None

    def _write(self, records: List[Any], reason: str, base: str,
               seq: int) -> str:
        from transmogrifai_tpu.obs.export import chrome_trace
        from transmogrifai_tpu.runtime.integrity import commit_staged_dir
        os.makedirs(base, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        final = os.path.join(base, f"flight-{stamp}-{seq:03d}-{reason}")
        staged = tempfile.mkdtemp(prefix=".flight-staging-", dir=base)
        try:
            spans = [rec for kind, rec in records if kind == "span"]
            # the ring's loose events render as instants on a synthetic
            # recorder span so the Chrome trace stays fully parented
            carrier = Span("flight:events", category="flight")
            for kind, rec in records:
                if kind == "event":
                    name, t_s, attrs = rec
                    carrier.events.append((name, t_s, attrs))
                elif kind == "metric":
                    name, t_s, value, labels = rec
                    carrier.events.append(
                        (name, t_s, {"value": value, **labels}))
            if carrier.events:
                carrier.start_s = min(t for _, t, _ in carrier.events)
                carrier.end()
                carrier.end_s = max(
                    carrier.end_s or 0.0,
                    max(t for _, t, _ in carrier.events))
                spans = spans + [carrier]
            trace = chrome_trace(
                spans, process_name=f"flight:{reason}", pid=os.getpid())
            # a ring SNAPSHOT is not a full trace: a span's parent may
            # still be open (never finished -> never in the ring) or
            # already scrolled out. Orphaned parent references are
            # detached (original id kept as `orphaned_parent`) so the
            # dump stays a VALID Chrome trace per validate_chrome_trace
            present = {ev["args"]["span_id"]
                       for ev in trace["traceEvents"]
                       if ev.get("ph") == "X"
                       and isinstance(ev.get("args", {}).get("span_id"),
                                      int)}
            for ev in trace["traceEvents"]:
                if ev.get("ph") != "X":
                    continue
                parent = ev.get("args", {}).get("parent_id")
                if parent is not None and parent not in present:
                    ev["args"]["orphaned_parent"] = parent
                    ev["args"]["parent_id"] = None
            with open(os.path.join(staged, "trace.json"), "w",
                      encoding="utf-8") as fh:
                json.dump(trace, fh)
            with open(os.path.join(staged, "events.jsonl"), "w",
                      encoding="utf-8") as fh:
                for kind, rec in records:
                    if kind == "span":
                        fh.write(json.dumps(
                            {"kind": "span", **rec.to_json()},
                            default=repr) + "\n")
                    elif kind == "event":
                        name, t_s, attrs = rec
                        fh.write(json.dumps(
                            {"kind": "event", "name": name,
                             "ts_s": round(t_s, 6), **attrs},
                            default=repr) + "\n")
                    else:
                        name, t_s, value, labels = rec
                        fh.write(json.dumps(
                            {"kind": "metric", "name": name,
                             "ts_s": round(t_s, 6), "value": value,
                             **labels}, default=repr) + "\n")
            with open(os.path.join(staged, "meta.json"), "w",
                      encoding="utf-8") as fh:
                from transmogrifai_tpu.obs import trace as _trace_mod
                json.dump({
                    "reason": reason, "at": time.time(), "pid": os.getpid(),
                    "records": len(records),
                    "capacity": self.capacity,
                    "records_seen": self.records_seen,
                    "dropped": max(0, self.records_seen - len(records)),
                    # clock anchors: this process's wall epoch and the
                    # perf-clock zero all ts_s offsets count from — the
                    # cross-host incident merge shifts every dump onto
                    # one fleet timeline with these
                    "epoch_time": _trace_mod._EPOCH_TIME,
                    "epoch_perf": _trace_mod._EPOCH_PERF,
                }, fh)
            commit_staged_dir(staged, final)
        except BaseException:
            shutil.rmtree(staged, ignore_errors=True)
            raise
        self.dumps.append(final)
        log.warning("flight: dumped %d record(s) to %s (reason: %s)",
                    len(records), final, reason)
        for hook in list(self.on_dump):
            try:
                hook(reason, final)
            except Exception:
                log.debug("flight: on_dump hook failed", exc_info=True)
        try:
            from transmogrifai_tpu.obs.export import emit_event
            emit_event("flight_dump", reason=reason, path=final,
                       records=len(records))
        except Exception:  # best-effort breadcrumb
            log.debug("flight_dump event emission failed", exc_info=True)
        return final

    def reset(self) -> None:
        self._ring.clear()
        self.records_seen = 0
        self.dumps = []
        self.dump_failures = 0
        # the debounce anchor is read twice under _dump_lock in dump()
        # (`is not None`, then the subtraction) — nulling it bare from
        # another thread can land between the two reads and crash the
        # dump path; every _last_dump_s write takes the lock
        with self._dump_lock:
            self._last_dump_s = None


RECORDER = FlightRecorder()


def get_recorder() -> FlightRecorder:
    return RECORDER


def enable(dump_dir: Optional[str] = None,
           capacity: Optional[int] = None,
           min_interval_s: Optional[float] = None) -> FlightRecorder:
    """Turn the process recorder on and hook it to the global tracer
    (idempotent — serving services call this at construction)."""
    RECORDER.configure(dump_dir=dump_dir, capacity=capacity,
                       min_interval_s=min_interval_s)
    RECORDER.enabled = True
    TRACER.add_sink(RECORDER.note_span)
    return RECORDER


def disable() -> None:
    RECORDER.enabled = False
    TRACER.remove_sink(RECORDER.note_span)


def note_event(name: str, attrs: Optional[Dict[str, Any]] = None) -> None:
    """Module-level feed used by `obs.export.record_event` (cheap no-op
    while the recorder is disabled)."""
    RECORDER.note_event(name, attrs)


def note_metric(name: str, value: float, **labels: Any) -> None:
    RECORDER.note_metric(name, value, **labels)


def request_dump(reason: str, out_dir: Optional[str] = None,
                 force: bool = False) -> Optional[str]:
    """Best-effort dump trigger for incident paths (breaker open,
    quarantine, watchdog restart, SIGTERM): never raises."""
    try:
        return RECORDER.dump(reason, out_dir=out_dir, force=force)
    except Exception:
        log.debug("flight: request_dump(%s) failed", reason, exc_info=True)
        return None
