"""Fleet-wide observability federation over the shared ``store/`` tier.

PR-14 built a per-process observability plane (traceparent tracing,
``/metrics``, the SLO engine, the flight recorder); PRs 17-19 made the
system a fleet. This module federates the plane through the same
no-leader shared-directory pattern the lease scheduler and perf corpus
already use — no collector daemon, no push gateway, just crash-
consistent files under ``<store>/obs/`` plus CAS ``StateCell``s:

- **Trace stitching** (`TraceShardWriter` / `merge_fleet_trace`): every
  process appends the completed spans of *kept* request traces to a
  host-qualified JSONL shard (torn-tail-tolerant, same discipline as
  the pod journal shards). A reader assembles ONE
  `validate_chrome_trace`-clean Perfetto trace for a trace id across
  frontend, replica scoring threads, and sweep lanes, normalizing
  clock skew from each shard's (epoch-wall, epoch-perf) anchor pair.
- **Metrics federation** (`MetricsPublisher` /
  `aggregate_fleet_metrics`): replicas publish full-fidelity
  `MetricsRegistry` snapshots (counters, gauges, mergeable histogram
  buckets) on a cadence; the frontend serves the merged registry on
  ``/metrics/fleet``.
- **Incident correlation** (`IncidentCoordinator` / `merge_incident`):
  a flight-recorder trigger on any member publishes an incident id
  through a `StateCell`; peers that see it within the capture window
  dump their rings keyed by that id, and `merge_incident` emits one
  cross-host Chrome trace from all contributed dumps.
- **Fleet alert dedup** (`FleetAlertLatch`): a CAS latch so the
  fleet-level SLO alert is emitted by exactly one replica per
  transition, not K times.

`FleetObs` bundles writer + publisher + incident coordinator behind
one start()/stop() pair for `serving/fleet.py`.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from transmogrifai_tpu.obs import trace as trace_mod
from transmogrifai_tpu.obs.export import (chrome_trace, merge_chrome_traces,
                                          validate_chrome_trace)
from transmogrifai_tpu.obs.metrics import MetricsRegistry
from transmogrifai_tpu.obs.trace import Span, TRACER
from transmogrifai_tpu.store.state import StateCell

log = logging.getLogger(__name__)

# Request traces (RequestTrace roots and their children) carry 32-hex
# uuid trace ids; ambient TRACER.span() spans carry 12-hex run ids. The
# shard writer keys on this: only spans of kept REQUEST traces — the
# ones the tail sampler decided to publish via Tracer.collect() — match.
_REQUEST_TRACE_RE = re.compile(r"^[0-9a-f]{32}$")

_HOST_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def _safe_host(host: str) -> str:
    if not _HOST_RE.match(host or ""):
        raise ValueError(f"host name {host!r} is not path-safe")
    return host


def _obs_dir(root: str, *parts: str) -> str:
    path = os.path.join(root, "obs", *parts)
    os.makedirs(path, exist_ok=True)
    return path


# --------------------------------------------------------------------------- #
# Trace shards: crash-consistent span publishing                              #
# --------------------------------------------------------------------------- #

class TraceShardWriter:
    """A `Tracer` sink appending kept-trace spans to this host's shard.

    The shard is a JSONL file ``<root>/obs/trace/shard-<host>.jsonl``:
    a header line carrying the host's clock anchors (wall epoch + perf
    epoch taken at the same instant, so readers can shift every span
    onto one fleet timeline), then one record per finished span. Writes
    are append+flush per record under a lock; fsync happens on a
    background syncer thread (at most ~2/s) so span collection on the
    request path never stalls on disk latency — traces are best-effort
    diagnostics, unlike the completion journal, and the torn-tail
    reader drops a half-written last line the same way the journal
    reader does.
    """

    FSYNC_INTERVAL_S = 0.5

    def __init__(self, root: str, host: str):
        self.root = str(root)
        self.host = _safe_host(host)
        self.path = os.path.join(_obs_dir(self.root, "trace"),
                                 f"shard-{self.host}.jsonl")
        self._lock = threading.Lock()
        self._fh = None            # guarded-by: _lock
        self._dirty = False        # guarded-by: _lock
        self._syncer = None        # guarded-by: _lock
        self._stop = threading.Event()
        self.published = 0         # guarded-by: _lock
        self.skipped = 0           # guarded-by: _lock
        self.errors = 0            # guarded-by: _lock

    # -- sink protocol ------------------------------------------------------ #

    def __call__(self, span: Span) -> None:
        """Tracer sink: called for every finished span, outside the
        tracer's lock. Filters to completed spans of request traces."""
        tid = getattr(span, "trace_id", None)
        if not (isinstance(tid, str) and _REQUEST_TRACE_RE.match(tid)) \
                or span.end_s is None:
            with self._lock:
                self.skipped += 1
            return
        rec = _span_record(span)
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            try:
                # single-owner append file: the open happens once per
                # process and every writer must serialize on it anyway
                fh = self._ensure_open()  # conc-ok: C003
                fh.write(line)
                fh.flush()
                self._dirty = True
                self.published += 1
            except Exception:
                self.errors += 1
                log.debug("federate: trace shard write failed",
                          exc_info=True)

    def _ensure_open(self):
        # guarded-by: _lock (callers hold it)
        if self._fh is None:
            fresh = not os.path.exists(self.path) or \
                os.path.getsize(self.path) == 0
            self._fh = open(self.path, "a",  # guarded-by: _lock
                            encoding="utf-8")
            if fresh:
                header = {"traceshard": 1, "host": self.host,
                          "pid": os.getpid(),
                          "epoch_time": trace_mod._EPOCH_TIME,
                          "epoch_perf": trace_mod._EPOCH_PERF}
                self._fh.write(json.dumps(header) + "\n")
                self._fh.flush()
                os.fsync(self._fh.fileno())
            if self._syncer is None:
                self._syncer = threading.Thread(
                    target=self._sync_loop,
                    name=f"traceshard-sync-{self.host}", daemon=True)
                self._syncer.start()
        return self._fh

    def _sync_loop(self) -> None:
        # background durability: writers only write+flush; this thread
        # pays the fsync so sampled requests never stall on disk
        while not self._stop.wait(self.FSYNC_INTERVAL_S):
            self._sync_once()
        self._sync_once()

    def _sync_once(self) -> None:
        with self._lock:
            if not self._dirty or self._fh is None:
                return
            try:
                # off the request path: only the syncer thread blocks
                os.fsync(self._fh.fileno())  # conc-ok: C003
                self._dirty = False
            except OSError:
                self.errors += 1
                log.debug("federate: shard fsync failed", exc_info=True)

    # -- lifecycle ---------------------------------------------------------- #

    def install(self) -> None:
        TRACER.add_sink(self)

    def close(self) -> None:
        TRACER.remove_sink(self)
        self._stop.set()
        syncer = self._syncer
        if syncer is not None and syncer is not threading.current_thread():
            syncer.join(timeout=2.0)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    # final durability point of a single-owner shard
                    # file; nothing else contends
                    os.fsync(self._fh.fileno())  # conc-ok: C003
                    self._fh.close()
                except OSError:
                    log.debug("federate: shard close failed",
                              exc_info=True)
                self._fh = None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"published": self.published, "skipped": self.skipped,
                    "errors": self.errors}


def _span_record(span: Span) -> Dict[str, Any]:
    """Wire form of a finished span: perf-clock offsets (shiftable by
    the shard's anchors), never the derived wall strings."""
    return {
        "name": span.name, "category": span.category,
        "span_id": span.span_id, "parent_id": span.parent_id,
        "trace_id": span.trace_id,
        "start_s": span.start_s, "end_s": span.end_s,
        "thread_id": span.thread_id, "thread_name": span.thread_name,
        "attributes": dict(span.attributes),
        "events": [[n, t, dict(a)] for (n, t, a) in span.events],
        "error": span.error,
    }


def read_trace_shard(path: str
                     ) -> Tuple[Optional[Dict[str, Any]],
                                List[Dict[str, Any]], bool]:
    """Torn-tail-tolerant shard read (the journal idiom): a record
    counts only if it is newline-terminated AND parses; reading stops
    at the first bad line. Returns (header, records, torn) — header is
    None when even the first line is unusable."""
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError:
        return None, [], True
    header: Optional[Dict[str, Any]] = None
    records: List[Dict[str, Any]] = []
    torn = False
    for i, line in enumerate(raw.splitlines(keepends=True)):
        if not line.endswith(b"\n"):
            torn = True
            break
        try:
            rec = json.loads(line)
        except (json.JSONDecodeError, UnicodeDecodeError):
            torn = True
            break
        if not isinstance(rec, dict):
            torn = True
            break
        if i == 0:
            if rec.get("traceshard") != 1:
                return None, [], True
            header = rec
        else:
            records.append(rec)
    return header, records, torn


def list_trace_shards(root: str) -> Dict[str, str]:
    """{host: shard path} for every shard under the store root."""
    d = os.path.join(root, "obs", "trace")
    out: Dict[str, str] = {}
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if name.startswith("shard-") and name.endswith(".jsonl"):
            out[name[len("shard-"):-len(".jsonl")]] = os.path.join(d, name)
    return out


def _span_from_record(rec: Dict[str, Any], shift_s: float) -> Optional[Span]:
    """Reconstruct a Span from a shard record, shifting its perf-clock
    offsets by the shard's skew onto the fleet timeline."""
    try:
        sp = Span(str(rec["name"]),
                  category=str(rec.get("category") or "span"),
                  trace_id=str(rec.get("trace_id") or ""))
        sp.span_id = int(rec["span_id"])
        pid = rec.get("parent_id")
        sp.parent_id = int(pid) if pid is not None else None
        sp.start_s = float(rec["start_s"]) + shift_s
        sp.end_s = float(rec["end_s"]) + shift_s
        sp.thread_id = int(rec.get("thread_id") or 0)
        sp.thread_name = str(rec.get("thread_name") or "thread")
        attrs = rec.get("attributes")
        sp.attributes = dict(attrs) if isinstance(attrs, dict) else {}
        sp.events = []
        for ev in rec.get("events") or []:
            try:
                name, t, a = ev
                sp.events.append((str(name), float(t) + shift_s,
                                  dict(a) if isinstance(a, dict) else {}))
            except (TypeError, ValueError):
                continue
        err = rec.get("error")
        sp.error = str(err) if err is not None else None
        return sp
    except (KeyError, TypeError, ValueError):
        return None


def merge_fleet_trace(trace_id: str, root: str,
                      expect_hosts: Optional[List[str]] = None
                      ) -> Dict[str, Any]:
    """Assemble ONE Chrome trace for ``trace_id`` from every host shard
    under ``root``.

    Pure file reads over whatever shards exist right now — a missing or
    unreadable host shard degrades the result (named in
    ``missing_shards``), it never blocks or hangs. Clock skew is
    normalized from each shard's (epoch_time, epoch_perf) anchors: the
    earliest-booted host is the reference, every other shard's spans
    shift by its wall-epoch delta. Each host becomes its own Perfetto
    process (pid = shard index), so duplicate span ids across hosts
    cannot collide (span ids are per-pid in the validator); duplicate
    ids WITHIN a shard (a crash-replayed tail) keep the first record.
    Spans whose parent did not land in the same shard are detached and
    marked ``orphaned_parent`` — cross-process causality stays visible
    through the shared trace id and the ``parent_traceparent``
    attribute the wire hop stamps on the remote root.
    """
    shards = list_trace_shards(root)
    hosts_found: List[str] = []
    torn_shards: List[str] = []
    per_host: List[Tuple[str, List[Span]]] = []
    skew: Dict[str, float] = {}

    anchors: Dict[str, Tuple[Dict[str, Any], List[Dict[str, Any]]]] = {}
    for host, path in shards.items():
        header, records, torn = read_trace_shard(path)
        if torn:
            torn_shards.append(host)
        if header is None:
            continue
        matching = [r for r in records if r.get("trace_id") == trace_id]
        if matching:
            anchors[host] = (header, matching)

    ref_epoch: Optional[float] = None
    for host, (header, _) in anchors.items():
        try:
            e = float(header["epoch_time"])
        except (KeyError, TypeError, ValueError):
            continue
        ref_epoch = e if ref_epoch is None else min(ref_epoch, e)

    for host in sorted(anchors):
        header, matching = anchors[host]
        try:
            shift = float(header["epoch_time"]) - (ref_epoch or 0.0)
        except (KeyError, TypeError, ValueError):
            shift = 0.0
        skew[host] = shift
        seen_ids: set = set()
        spans: List[Span] = []
        for rec in matching:
            sp = _span_from_record(rec, shift)
            if sp is None or sp.span_id in seen_ids:
                continue
            seen_ids.add(sp.span_id)
            spans.append(sp)
        # detach parents that never landed in THIS shard — the
        # validator requires same-pid parents, and cross-host links
        # ride the trace id, not the span tree
        for sp in spans:
            if sp.parent_id is not None and sp.parent_id not in seen_ids:
                sp.attributes = dict(sp.attributes)
                sp.attributes["orphaned_parent"] = sp.parent_id
                sp.parent_id = None
        if spans:
            hosts_found.append(host)
            per_host.append((host, spans))

    traces = [chrome_trace(spans, process_name=f"host:{host}", pid=i)
              for i, (host, spans) in enumerate(per_host)]
    merged = merge_chrome_traces(*traces) if traces else {"traceEvents": []}
    missing = sorted(set(expect_hosts or []) - set(hosts_found))
    return {
        "trace_id": trace_id,
        "trace": merged,
        "hosts": hosts_found,
        "spans": sum(len(s) for _, s in per_host),
        "missing_shards": missing,
        "torn_shards": sorted(torn_shards),
        "skew_s": skew,
        "problems": validate_chrome_trace(merged),
    }


# --------------------------------------------------------------------------- #
# Metrics federation                                                          #
# --------------------------------------------------------------------------- #

class MetricsPublisher:
    """Periodic full-fidelity `MetricsRegistry` snapshots to the store.

    One JSON file per replica under ``<root>/obs/metrics/``, replaced
    atomically (tmp + ``os.replace``) so readers never see a torn
    snapshot. ``snapshot_fn`` returns the registry (or an already-built
    snapshot dict) to publish — evaluated on the publisher thread, so
    it must be cheap and lock-clean."""

    def __init__(self, root: str, replica: str,
                 snapshot_fn: Callable[[], Any],
                 period_s: float = 1.0):
        self.root = str(root)
        self.replica = _safe_host(replica)
        self.snapshot_fn = snapshot_fn
        self.period_s = max(0.05, float(period_s))
        self.path = os.path.join(_obs_dir(self.root, "metrics"),
                                 f"{self.replica}.json")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.publishes = 0   # publisher-thread only
        self.errors = 0      # publisher-thread only

    def publish_once(self) -> bool:
        try:
            snap = self.snapshot_fn()
            if isinstance(snap, MetricsRegistry):
                snap = snap.snapshot()
            doc = {"replica": self.replica, "ts": time.time(),
                   "pid": os.getpid(), "registry": snap}
            tmp = self.path + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, default=str)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
            self.publishes += 1
            return True
        except Exception:
            self.errors += 1
            log.debug("federate: metrics publish failed", exc_info=True)
            return False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="metrics-publisher",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            self._thread = None
        self.publish_once()  # final snapshot so a clean stop is current

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            self.publish_once()


def read_metrics_snapshots(root: str) -> List[Dict[str, Any]]:
    """Every replica's last-published snapshot doc (unparseable or
    half-written files are skipped — `os.replace` makes those rare)."""
    d = os.path.join(root, "obs", "metrics")
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json") or ".tmp." in name:
            continue
        try:
            with open(os.path.join(d, name), "r", encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("registry"), dict):
            out.append(doc)
    return out


def aggregate_fleet_metrics(root: str,
                            base: Optional[MetricsRegistry] = None
                            ) -> Tuple[MetricsRegistry, Dict[str, Any]]:
    """Merge every published replica snapshot into one registry.

    Counters with identical labels sum; histograms merge bucket-exact
    (same bounds) or keep replica-labeled series (different bounds);
    gauges stay replica-labeled — a mean of gauges is a lie. Returns
    the merged registry plus {replica: publish wall-ts} provenance."""
    merged = MetricsRegistry()
    if base is not None:
        merged.merge(base)
    info: Dict[str, Any] = {}
    for doc in read_metrics_snapshots(root):
        replica = str(doc.get("replica") or "unknown")
        restored = MetricsRegistry.from_snapshot(doc["registry"])
        merged.merge(restored, replica=replica)
        info[replica] = doc.get("ts")
    return merged, info


# --------------------------------------------------------------------------- #
# Fleet alert latch: one transition, one emitter                              #
# --------------------------------------------------------------------------- #

class FleetAlertLatch:
    """CAS latch deduplicating fleet-level SLO alert emissions.

    Every replica evaluates the same fleet-folded burn state, so on a
    threshold crossing K replicas want to fire the same alert. The
    latch is one `StateCell` holding per-SLO {state, owner, ts, fired}:
    `transition` returns claimed=True for exactly the replica whose CAS
    write moved the recorded state — only the claimant emits the alert
    event / flight dump; the rest keep their local bookkeeping quiet.
    """

    def __init__(self, root: str, name: str = "default"):
        self.cell = StateCell(root, f"slo-fleet-alert-{name}")

    def transition(self, slo: str, state: str, owner: str
                   ) -> Tuple[bool, int]:
        """Record `slo` entering `state`. Returns (claimed, fired_count)
        — claimed iff THIS call moved the recorded state. The CAS
        transform may run multiple times on contention; the last
        invocation's view is the committed one, so a peer winning the
        same transition mid-retry correctly yields claimed=False."""
        claim = {"claimed": False, "fired": 0}

        def put(cur):
            cur = dict(cur) if isinstance(cur, dict) else {}
            slos = dict(cur.get("slos") or {})
            rec = dict(slos.get(slo) or {})
            claim["claimed"] = rec.get("state") != state
            if claim["claimed"]:
                rec["state"] = state
                rec["owner"] = owner
                rec["ts"] = time.time()
                if state == "firing":
                    rec["fired"] = int(rec.get("fired") or 0) + 1
            claim["fired"] = int(rec.get("fired") or 0)
            slos[slo] = rec
            cur["slos"] = slos
            return cur

        try:
            self.cell.update(put)
        except Exception:
            log.debug("federate: alert latch CAS failed", exc_info=True)
            return False, claim["fired"]
        return claim["claimed"], claim["fired"]

    def counts(self) -> Dict[str, Dict[str, Any]]:
        _, value = self.cell.read()
        slos = (value or {}).get("slos") if isinstance(value, dict) else None
        return dict(slos or {})


# --------------------------------------------------------------------------- #
# Incident correlation                                                        #
# --------------------------------------------------------------------------- #

_INCIDENT_REASON_RE = re.compile(r"[^A-Za-z0-9_-]+")


class IncidentCoordinator:
    """One incident id, K ring dumps, one merged artifact.

    A flight-recorder trigger anywhere in the fleet calls `publish`:
    the CAS cell either opens a new incident (fresh id) or joins the
    currently-open one (within `capture_window_s` — a storm tripping K
    breakers is ONE incident, not K). Every member then dumps its ring
    under ``<root>/obs/incidents/<id>/<host>/``; a watcher thread makes
    members that did NOT trip contribute their rings too, as long as
    they notice within the window."""

    def __init__(self, root: str, host: str,
                 capture_window_s: float = 10.0,
                 recorder=None, poll_s: float = 0.5):
        self.root = str(root)
        self.host = _safe_host(host)
        self.capture_window_s = float(capture_window_s)
        self.poll_s = max(0.05, float(poll_s))
        if recorder is None:
            from transmogrifai_tpu.obs.flight import RECORDER
            recorder = RECORDER
        self.recorder = recorder
        self.cell = StateCell(self.root, "obs-incident")
        self._lock = threading.Lock()
        self._contributed: set = set()   # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- publishing --------------------------------------------------------- #

    def publish(self, reason: str) -> Optional[str]:
        """Open (or join) an incident and contribute this host's ring.
        Returns the incident id, or None when coordination failed."""
        safe_reason = _INCIDENT_REASON_RE.sub("_", str(reason))[:48] or "x"
        fresh_id = uuid.uuid4().hex[:12]
        out = {"id": None}

        def put(cur):
            cur = dict(cur) if isinstance(cur, dict) else {}
            inc = cur.get("incident")
            now = time.time()
            if isinstance(inc, dict) and inc.get("id") and \
                    now - float(inc.get("ts") or 0.0) < self.capture_window_s:
                out["id"] = str(inc["id"])   # join the open incident
                return cur
            out["id"] = fresh_id
            cur["incident"] = {"id": fresh_id, "reason": safe_reason,
                               "host": self.host, "ts": now,
                               "seq": int(cur.get("seq") or 0) + 1}
            cur["seq"] = int(cur.get("seq") or 0) + 1
            return cur

        try:
            self.cell.update(put)
        except Exception:
            log.debug("federate: incident publish failed", exc_info=True)
            return None
        incident_id = out["id"]
        if incident_id:
            self._contribute(incident_id, safe_reason)
        return incident_id

    def _contribute(self, incident_id: str, reason: str) -> None:
        with self._lock:
            if incident_id in self._contributed:
                return
            self._contributed.add(incident_id)
        out_dir = os.path.join(_obs_dir(self.root, "incidents",
                                        incident_id), self.host)
        try:
            self.recorder.dump(reason=f"incident-{reason}",
                               out_dir=out_dir, force=True)
        except Exception:
            log.debug("federate: incident ring dump failed", exc_info=True)

    # -- the flight-recorder hook ------------------------------------------- #

    def on_flight_dump(self, reason: str, path: str) -> None:
        """`FlightRecorder.on_dump` hook: any organic dump (breaker
        open, watchdog restart, SLO alert, SIGTERM) ALSO opens/joins a
        fleet incident — except dumps this coordinator itself asked
        for, which would recurse."""
        if str(reason).startswith("incident"):
            return
        self.publish(reason)

    # -- the peer watcher --------------------------------------------------- #

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch,
                                        name="incident-watcher",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                _, value = self.cell.read()
            except (OSError, ValueError):
                log.debug("federate: incident cell read failed",
                          exc_info=True)
                continue
            inc = (value or {}).get("incident") \
                if isinstance(value, dict) else None
            if not isinstance(inc, dict) or not inc.get("id"):
                continue
            # cross-process age: the publisher's epoch stamp against
            # our epoch clock — wall time is the only shared clock
            wall_now = time.time()
            if wall_now - float(inc.get("ts") or 0.0) \
                    >= self.capture_window_s:
                continue
            self._contribute(str(inc["id"]),
                             str(inc.get("reason") or "peer"))


def merge_incident(incident_id: str, root: str) -> Dict[str, Any]:
    """One cross-host Chrome trace from every ring dump contributed
    under ``<root>/obs/incidents/<incident_id>/``.

    Each host's flight dump already validates standalone; the merge
    re-pids them (one Perfetto process per dump) and shifts every
    timestamp by the dump's wall-epoch anchor delta so the fleet shares
    one timeline. Pure file reads — missing or torn dumps are skipped
    and named, never waited on."""
    base = os.path.join(root, "obs", "incidents", str(incident_id))
    dumps: List[Tuple[str, str, Dict[str, Any], Dict[str, Any]]] = []
    problems_reading: List[str] = []
    try:
        host_names = sorted(os.listdir(base))
    except OSError:
        host_names = []
    for host in host_names:
        host_dir = os.path.join(base, host)
        if not os.path.isdir(host_dir):
            continue
        for dump_name in sorted(os.listdir(host_dir)):
            dump_dir = os.path.join(host_dir, dump_name)
            try:
                with open(os.path.join(dump_dir, "trace.json"),
                          "r", encoding="utf-8") as fh:
                    tr = json.load(fh)
                with open(os.path.join(dump_dir, "meta.json"),
                          "r", encoding="utf-8") as fh:
                    meta = json.load(fh)
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                problems_reading.append(f"{host}/{dump_name}")
                continue
            if isinstance(tr, dict) and isinstance(meta, dict):
                dumps.append((host, dump_name, tr, meta))

    ref_epoch: Optional[float] = None
    for _, _, _, meta in dumps:
        try:
            e = float(meta["epoch_time"])
        except (KeyError, TypeError, ValueError):
            continue
        ref_epoch = e if ref_epoch is None else min(ref_epoch, e)

    shifted: List[Dict[str, Any]] = []
    hosts: List[str] = []
    for i, (host, dump_name, tr, meta) in enumerate(dumps):
        try:
            shift_us = int((float(meta["epoch_time"]) -
                            (ref_epoch or 0.0)) * 1e6)
        except (KeyError, TypeError, ValueError):
            shift_us = 0
        events = []
        for ev in tr.get("traceEvents") or []:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = i
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = int(ev["ts"]) + shift_us
            events.append(ev)
        # re-name the process row for the merged view
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                args = dict(ev.get("args") or {})
                args["name"] = f"{host}:{args.get('name', dump_name)}"
                ev["args"] = args
        shifted.append({"traceEvents": events})
        if host not in hosts:
            hosts.append(host)

    merged = merge_chrome_traces(*shifted) if shifted \
        else {"traceEvents": []}
    return {
        "incident_id": str(incident_id),
        "trace": merged,
        "hosts": hosts,
        "dumps": [f"{h}/{d}" for h, d, _, _ in dumps],
        "unreadable": problems_reading,
        "problems": validate_chrome_trace(merged),
    }


# --------------------------------------------------------------------------- #
# The bundle                                                                  #
# --------------------------------------------------------------------------- #

class FleetObs:
    """Writer + publisher + incident coordinator behind one switch.

    `serving/fleet.py` owns one of these per process when a store dir
    is configured: `start()` installs the trace-shard sink on the
    global tracer, starts the metrics publisher thread, hooks the
    flight recorder's dump callback into the incident cell, and starts
    the peer watcher; `stop()` unwinds all of it in reverse."""

    def __init__(self, root: str, host: str,
                 snapshot_fn: Callable[[], Any],
                 metrics_period_s: float = 1.0,
                 capture_window_s: float = 10.0,
                 recorder=None):
        self.root = str(root)
        self.host = _safe_host(host)
        self.writer = TraceShardWriter(self.root, self.host)
        self.publisher = MetricsPublisher(self.root, self.host,
                                          snapshot_fn,
                                          period_s=metrics_period_s)
        self.incidents = IncidentCoordinator(
            self.root, self.host, capture_window_s=capture_window_s,
            recorder=recorder)
        self._started = False

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self.writer.install()
        self.publisher.start()
        rec = self.incidents.recorder
        hooks = getattr(rec, "on_dump", None)
        if isinstance(hooks, list) and \
                self.incidents.on_flight_dump not in hooks:
            hooks.append(self.incidents.on_flight_dump)
        self.incidents.start()

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        self.incidents.stop()
        rec = self.incidents.recorder
        hooks = getattr(rec, "on_dump", None)
        if isinstance(hooks, list):
            try:
                hooks.remove(self.incidents.on_flight_dump)
            except ValueError:
                pass
        self.publisher.stop()
        self.writer.close()

    def stats(self) -> Dict[str, Any]:
        return {"host": self.host,
                "trace": self.writer.stats(),
                "metrics_publishes": self.publisher.publishes,
                "metrics_errors": self.publisher.errors}
