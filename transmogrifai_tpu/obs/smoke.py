"""trace-smoke: a tiny train+score through the runner with ``--trace-out``,
validating the whole observability path end to end (`make trace-smoke`).

Asserted properties — the same contract `tests/test_obs.py` checks piecewise:

1. the Perfetto/Chrome-trace JSON loads, is structurally well-formed
   (`obs.export.validate_chrome_trace`: required keys, non-negative
   monotonic-clock timestamps, every parent present, children inside
   their parent's interval);
2. the run ROOT span exists and the runner phases + per-stage DAG spans
   parent (transitively) under it;
3. the `GoodputReport` buckets sum to the root span's wall time (the
   decomposition is a decomposition, not a sampling);
4. the JSONL event log exists and every record carries the run's
   correlation id.

Run: ``python -m transmogrifai_tpu.obs.smoke`` (CPU-friendly).
"""

from __future__ import annotations

import json
import math
import tempfile

import numpy as np


def _write_csv(path: str, n: int = 96, seed: int = 7) -> None:
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    y = (a + 0.5 * b + rng.normal(0, 0.4, n) > 0).astype(int)
    with open(path, "w") as f:
        f.write("a,b,label\n")
        for i in range(n):
            f.write(f"{a[i]:.6f},{b[i]:.6f},{y[i]}\n")


def _runner(csv_path: str):
    from transmogrifai_tpu.automl import transmogrify
    from transmogrifai_tpu.data.dataset import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.readers import DataReaders
    from transmogrifai_tpu.workflow import Workflow
    from transmogrifai_tpu.workflow.runner import WorkflowRunner

    template = Dataset.from_csv(csv_path)
    preds, label = FeatureBuilder.from_dataset(template, response="label")
    vec = transmogrify(preds)
    pred = OpLogisticRegression(max_iter=8).set_input(
        label, vec).get_output()
    wf = Workflow().set_result_features(pred, label)
    return WorkflowRunner(wf, train_reader=DataReaders.csv(csv_path),
                          score_reader=DataReaders.csv(csv_path))


def _validate_trace(trace_path: str, run_type: str, run_id: str) -> dict:
    from transmogrifai_tpu.obs.export import validate_chrome_trace

    with open(trace_path) as f:
        obj = json.load(f)
    problems = validate_chrome_trace(obj)
    assert not problems, f"trace {trace_path} invalid: {problems}"
    xs = [e for e in obj["traceEvents"] if e.get("ph") == "X"]
    roots = [e for e in xs if e["name"] == f"run:{run_type}"]
    assert len(roots) == 1, f"expected one run root, got {len(roots)}"
    root = roots[0]
    assert root["args"]["parent_id"] is None
    assert root["args"]["run_id"] == run_id
    # ONE correlation id: the trace id IS the profile/event-log run id
    assert root["args"]["trace_id"] == run_id
    # every span in the file reaches the root through parent links
    by_id = {e["args"]["span_id"]: e for e in xs}
    rid = root["args"]["span_id"]

    def _reaches_root(e) -> bool:
        seen = set()
        while e is not None:
            sid = e["args"]["span_id"]
            if sid == rid:
                return True
            if sid in seen:
                return False
            seen.add(sid)
            e = by_id.get(e["args"]["parent_id"])
        return False

    orphans = [e["name"] for e in xs if not _reaches_root(e)]
    assert not orphans, f"spans not under the run root: {orphans}"
    phases = [e["name"] for e in xs if e["cat"] == "phase"]
    assert phases, "no runner phase spans in the trace"
    return {"spans": len(xs), "phases": sorted(set(phases))}


def _validate_goodput(profile: dict) -> dict:
    gp = profile.get("goodput")
    assert gp, "profile missing the goodput report"
    buckets = gp["buckets"]
    total = sum(buckets.values())
    wall = gp["wall_s"]
    assert math.isclose(total, wall, rel_tol=0.02, abs_tol=0.05), \
        f"goodput buckets sum {total} != wall {wall}"
    assert 0.0 <= gp["goodput_frac"] <= 1.0
    return {"goodput_frac": gp["goodput_frac"], "wall_s": wall}


def _validate_events(events_path: str, run_id: str) -> int:
    n = 0
    with open(events_path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            assert rec["run_id"] == run_id, \
                f"event correlation id {rec['run_id']} != run {run_id}"
            n += 1
    assert n >= 2, "event log missing run_start/run_end markers"
    return n


def _smoke() -> int:
    from transmogrifai_tpu.workflow.params import OpParams

    payload = {}
    with tempfile.TemporaryDirectory(prefix="trace-smoke-") as tmp:
        csv_path = f"{tmp}/data.csv"
        _write_csv(csv_path)
        runner = _runner(csv_path)

        train_trace = f"{tmp}/train-trace.json"
        params = OpParams.from_json({
            "model_location": f"{tmp}/model",
            "trace_location": train_trace,
        })
        result = runner.run("train", params)
        run_id = result.profile["run_id"]
        payload["train"] = {
            **_validate_trace(train_trace, "train", run_id),
            **_validate_goodput(result.profile),
            "events": _validate_events(
                train_trace + ".events.jsonl", run_id),
        }

        score_trace = f"{tmp}/score-trace.json"
        params.trace_location = score_trace
        result = runner.run("score", params)
        run_id = result.profile["run_id"]
        payload["score"] = {
            **_validate_trace(score_trace, "score", run_id),
            **_validate_goodput(result.profile),
        }
    print(json.dumps({"trace_smoke": "ok", **payload}))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_smoke())
