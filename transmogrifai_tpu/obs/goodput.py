"""Goodput accounting: roll a span tree into useful-work vs badput buckets.

"ML Productivity Goodput" (arxiv 2502.06982, PAPERS.md) argues the
metric that matters for accelerator fleets is not FLOPs but the fraction
of wall-clock spent on USEFUL work — everything else (recompiles,
retries, redone work, input stalls) is badput that per-op profilers
never attribute. This module computes that rollup from the spans and
events the rest of the codebase already emits:

- ``retry_backoff_s``  — time slept between retry attempts
  (`runtime/retry.py` opens a ``retry:<site>`` span around each
  backoff);
- ``recompile_s``      — time spent re-tracing jitted programs
  (`analysis/retrace.py` emits a ``recompile`` event with the measured
  trace duration on every jit cache miss);
- ``ingest_wait_s``    — main-thread time blocked on device completion
  tokens during pipelined ingest (the `IngestStats.upload_wait_s`
  attribute on each ingest span);
- ``oom_redo_s``       — wall time wasted on sweep blocks that died of
  device OOM before the halved retry succeeded (``oom_redo`` events
  from `parallel/sweep.py`);
- ``fault_redo_s``     — wall time of failed attempts that a
  `RetryPolicy` subsequently retried (``fault_redo`` events: the work
  is redone, distinct from the backoff sleep);
- ``productive_s``     — the remainder. Buckets sum to the root span's
  wall time BY CONSTRUCTION, so "what fraction was useful" is always
  answerable.

Savings are tracked separately (they are not part of the wall-time
decomposition): ``resume_saved_s`` sums the journaled durations of
sweep blocks a resumed run skipped (``journal_resume`` events), and
``cache_saved_s`` sums the upload seconds feature-cache hits avoided —
each artifact records its cold build's wall time, so a warm replay
reports cold-minus-warm as recovered ingest badput (``cache_hit``
events from `parallel/bigdata.py`); ``compile_cache_saved_s`` sums the
warmup seconds serving's persistent XLA compile cache recovered vs each
model's recorded cold warmup (``compile_cache_saved`` events from
`serving/service.py`).

A ``mesh`` section (present when a distributed sweep ran) rolls up the
scheduler's ``mesh_utilization`` events: the fraction of workers × wall
the mesh lanes spent executing grid blocks, plus steal/requeue/idle
counters — the measured packing efficiency behind any pod-scale
extrapolation (`parallel/scheduler.py`).

The report lands in `RunProfile.to_json()["goodput"]`, bench payloads,
and beside the CLI's ``--trace-out`` trace.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Optional

from transmogrifai_tpu.obs.trace import Span

__all__ = ["GoodputReport", "build_report", "fleet_mesh_rollup",
           "BADPUT_BUCKETS"]

BADPUT_BUCKETS = ("retry_backoff_s", "recompile_s", "ingest_wait_s",
                  "oom_redo_s", "fault_redo_s")


@dataclass
class GoodputReport:
    """Wall-time decomposition of one trace (one run)."""

    wall_s: float = 0.0
    trace_id: Optional[str] = None
    buckets: Dict[str, float] = field(default_factory=dict)
    savings: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)
    # distributed-sweep packing: rolled up from the scheduler's
    # ``mesh_utilization`` events (parallel/scheduler.py) — how much of
    # workers × wall the mesh lanes spent executing blocks, plus
    # steal/requeue/straggler counters. Empty when no schedule ran.
    mesh: Dict[str, Any] = field(default_factory=dict)
    # continual-training accounting: rolled up from the loop's
    # ``continual_cycle`` summary events (continual/loop.py) — cycles by
    # outcome, refit wall time, and append-to-fresh-model staleness.
    # Empty when no continual loop ran in this trace.
    continual: Dict[str, Any] = field(default_factory=dict)
    # learned-cost-model scorecard: rolled up from ``perf_residual``
    # events (one per consumer decision a prediction backed) — how many
    # predictions this run made and how far off they were. Empty when
    # the model was cold/disabled.
    perf: Dict[str, Any] = field(default_factory=dict)
    # fleet-serving accounting: rolled up from ``fleet_swap`` events
    # (one per rolling model swap — wall time plus the PER-TENANT
    # traffic served/shed during the swap window, the goodput-under-
    # rolling-swaps number of serving/fleet.py) and ``tenant_shed``
    # admission events. Empty when no fleet ran in this trace.
    fleet: Dict[str, Any] = field(default_factory=dict)
    # fleet-frontend routing accounting: rolled up from
    # ``router_route`` events (serving/frontend.py emits one per routed
    # request) — requests per replica, warm vs cold routing decisions,
    # wire split (json vs binary), and error outcomes. Empty when no
    # frontend routed in this trace.
    router: Dict[str, Any] = field(default_factory=dict)
    # compiled-scoring accounting: rolled up from ``device_dispatch``
    # events (CompiledScorer._dispatch emits one per XLA program launch
    # with the bytes shipped in and returned) — dispatch counts prove
    # whole-pipeline fusion held (one per score call on fused plans) and
    # the byte totals are the numerator of the achieved-bandwidth
    # roofline bench reports as `scoring_hbm_frac`. Empty when no
    # compiled scoring ran inside a span.
    scoring: Dict[str, Any] = field(default_factory=dict)
    # serving-resilience accounting (serving/resilience.py): breaker
    # open/close transitions, quarantine entries and recoveries with
    # the measured MTTR (mean/max seconds from outage start to the
    # HEALTHY transition), degraded-fallback traffic served by the
    # resident previous version, and watchdog thread restarts — the
    # availability story of a run that survived injected (or real)
    # serving faults. Empty when nothing tripped.
    resilience: Dict[str, Any] = field(default_factory=dict)
    # SLO accounting (obs/slo.py): rolled from ``slo_alert`` events —
    # alerts fired/resolved per SLO name with total measured
    # time-in-alert seconds. A run whose chaos storm fired and cleared
    # an availability alert reports it here. Empty when no SLO engine
    # ran (or nothing fired).
    slo: Dict[str, Any] = field(default_factory=dict)

    @property
    def badput_s(self) -> float:
        return sum(v for k, v in self.buckets.items()
                   if k != "productive_s")

    @property
    def goodput_frac(self) -> float:
        if self.wall_s <= 0.0:
            return 1.0
        return max(0.0, min(1.0, self.buckets.get("productive_s", 0.0)
                            / self.wall_s))

    def to_json(self) -> Dict[str, Any]:
        out = {
            "wall_s": round(self.wall_s, 6),
            "trace_id": self.trace_id,
            "goodput_frac": round(self.goodput_frac, 4),
            "buckets": {k: round(v, 6)
                        for k, v in sorted(self.buckets.items())},
            "savings": {k: round(v, 6)
                        for k, v in sorted(self.savings.items())},
            "counts": dict(sorted(self.counts.items())),
        }
        if self.mesh:
            out["mesh"] = dict(sorted(self.mesh.items()))
        if self.continual:
            out["continual"] = dict(sorted(self.continual.items()))
        if self.perf:
            out["perf"] = dict(sorted(self.perf.items()))
        if self.fleet:
            out["fleet"] = dict(sorted(self.fleet.items()))
        if self.router:
            out["router"] = dict(sorted(self.router.items()))
        if self.scoring:
            out["scoring"] = dict(sorted(self.scoring.items()))
        if self.resilience:
            out["resilience"] = dict(sorted(self.resilience.items()))
        if self.slo:
            out["slo"] = dict(sorted(self.slo.items()))
        return out

    def pretty(self) -> str:
        lines = [f"goodput: {self.goodput_frac:.1%} of "
                 f"{self.wall_s:.2f}s wall"]
        for k, v in sorted(self.buckets.items()):
            lines.append(f"  {k}: {v:.3f}s")
        for k, v in sorted(self.savings.items()):
            lines.append(f"  (saved) {k}: {v:.3f}s")
        return "\n".join(lines)


def fleet_mesh_rollup(
        host_meshes: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll per-host ``GoodputReport.mesh`` sections into one fleet
    view.

    ``mesh_utilization_frac`` is weighted by each host's workers × wall
    (the ``worker_wall_s`` accumulator `build_report` stamps), so a
    host with 8 busy lanes counts 8× a host with one — the same math
    `build_report` uses within a host, lifted across the pod. Worker
    counts and block/steal/requeue counters sum; hosts without a mesh
    section (no distributed sweep ran there) are skipped. Hosts that
    only report ``utilization_frac`` (pre-accumulator payloads) fall
    back to an unweighted wall of 1.0 so old reports still merge.
    """
    out: Dict[str, Any] = {"hosts": 0}
    wall = busy = 0.0
    for m in host_meshes:
        if not m:
            continue
        out["hosts"] += 1
        w = float(m.get("worker_wall_s", 0.0) or 0.0)
        if w <= 0.0:
            w = 1.0
            b = float(m.get("utilization_frac", 0.0) or 0.0)
        else:
            b = float(m.get("busy_s", 0.0) or 0.0)
        wall += w
        busy += b
        out["workers"] = out.get("workers", 0) + int(
            m.get("workers", 0) or 0)
        for key in ("schedules", "steals", "requeues", "blocks"):
            out[key] = out.get(key, 0) + int(m.get(key, 0) or 0)
        out["idle_s"] = round(out.get("idle_s", 0.0)
                              + float(m.get("idle_s", 0.0) or 0.0), 6)
    out["worker_wall_s"] = round(wall, 6)
    out["busy_s"] = round(busy, 6)
    out["mesh_utilization_frac"] = round(
        busy / wall, 4) if wall > 0 else 0.0
    return out


def build_report(root: Span, spans: Iterable[Span]) -> GoodputReport:
    """Classify `spans` (one trace, root included or not) into goodput
    buckets against `root`'s wall clock.

    Badput assignment is exclusive by source: a retry span counts its
    own duration once even when nested inside an ingest worker span
    (the ingest bucket reads the stats attribute, not span wall time),
    so buckets never double-count one second of badput."""
    report = GoodputReport(wall_s=root.duration_s, trace_id=root.trace_id)
    b = {k: 0.0 for k in BADPUT_BUCKETS}
    counts = {"retries": 0, "recompiles": 0, "oom_redos": 0,
              "resumed_blocks": 0, "faults_injected": 0,
              "cache_hits": 0, "cache_misses": 0,
              "steals": 0, "workers_retired": 0,
              "hbm_preshrinks": 0, "block_resizes": 0}
    saved = 0.0
    cache_saved = 0.0
    compile_saved = 0.0
    compile_hits = 0
    fleet: Dict[str, Any] = {}
    router: Dict[str, Any] = {}
    resilience: Dict[str, Any] = {}
    scoring: Dict[str, Any] = {}
    slo: Dict[str, Any] = {}
    mttrs: list = []
    # mesh rollup accumulators: several schedules (one per selector fit)
    # can land in one trace — utilization averages weighted by each
    # schedule's wall, counters sum
    mesh_wall = 0.0
    mesh_busy = 0.0
    mesh: Dict[str, Any] = {}
    continual: Dict[str, Any] = {}
    perf_n = 0
    perf_err_sum = 0.0
    perf_by_target: Dict[str, int] = {}
    seen: set = set()
    for sp in [root, *spans]:
        if sp.span_id in seen or sp.trace_id != root.trace_id:
            continue
        seen.add(sp.span_id)
        if sp is not root:  # the root's wall IS the denominator
            if sp.category == "retry":
                b["retry_backoff_s"] += sp.duration_s
                counts["retries"] += 1
            elif sp.category == "ingest":
                b["ingest_wait_s"] += float(
                    sp.attributes.get("upload_wait_s", 0.0) or 0.0)
        # events count wherever they landed — INCLUDING the root (a
        # sweep invoked directly under the root attaches its
        # journal_resume / oom_redo events there)
        for name, _, attrs in sp.events:
            if name == "recompile":
                b["recompile_s"] += float(attrs.get("trace_s", 0.0) or 0.0)
                counts["recompiles"] += 1
            elif name == "oom_redo":
                b["oom_redo_s"] += float(attrs.get("wasted_s", 0.0) or 0.0)
                counts["oom_redos"] += 1
            elif name == "fault_redo":
                b["fault_redo_s"] += float(attrs.get("wasted_s", 0.0) or 0.0)
            elif name == "journal_resume":
                saved += float(attrs.get("saved_s", 0.0) or 0.0)
                counts["resumed_blocks"] += int(attrs.get("blocks", 0) or 0)
            elif name == "cache_hit":
                cache_saved += float(attrs.get("saved_s", 0.0) or 0.0)
                counts["cache_hits"] += 1
            elif name == "cache_miss":
                counts["cache_misses"] += 1
            elif name == "compile_cache_saved":
                compile_saved += float(attrs.get("saved_s", 0.0) or 0.0)
                compile_hits += 1
            elif name == "fleet_swap":
                fleet["swaps"] = fleet.get("swaps", 0) + 1
                st = str(attrs.get("status") or "unknown")
                fleet[st] = fleet.get(st, 0) + 1
                fleet["swap_wall_s"] = round(
                    fleet.get("swap_wall_s", 0.0)
                    + float(attrs.get("wall_s", 0.0) or 0.0), 6)
                fleet["requests_during_swaps"] = \
                    fleet.get("requests_during_swaps", 0) + int(
                        attrs.get("requests_during_swap", 0) or 0)
                fleet["shed_during_swaps"] = \
                    fleet.get("shed_during_swaps", 0) + int(
                        attrs.get("shed_during_swap", 0) or 0)
                per_tenant = attrs.get("per_tenant") or {}
                if isinstance(per_tenant, dict):
                    tenants = fleet.setdefault("tenants", {})
                    for tname, d in per_tenant.items():
                        cur = tenants.setdefault(
                            str(tname), {"requests_during_swaps": 0,
                                         "shed_during_swaps": 0})
                        cur["requests_during_swaps"] += int(
                            (d or {}).get("requests", 0) or 0)
                        cur["shed_during_swaps"] += int(
                            (d or {}).get("shed", 0) or 0)
            elif name == "tenant_shed":
                fleet["sheds"] = fleet.get("sheds", 0) + 1
            elif name == "router_route":
                router["requests"] = router.get("requests", 0) + 1
                router["rows"] = router.get("rows", 0) + int(
                    attrs.get("rows", 0) or 0)
                if attrs.get("warm"):
                    router["warm_routes"] = router.get("warm_routes", 0) + 1
                else:
                    router["cold_routes"] = router.get("cold_routes", 0) + 1
                by_rep = router.setdefault("by_replica", {})
                rep = str(attrs.get("replica") or "unknown")
                by_rep[rep] = by_rep.get(rep, 0) + 1
                by_wire = router.setdefault("by_wire", {})
                wire = str(attrs.get("wire") or "json")
                by_wire[wire] = by_wire.get(wire, 0) + 1
                outcome = str(attrs.get("outcome") or "ok")
                if outcome != "ok":
                    errs = router.setdefault("errors", {})
                    errs[outcome] = errs.get(outcome, 0) + 1
            elif name == "device_dispatch":
                scoring["dispatches"] = scoring.get("dispatches", 0) + 1
                scoring["bytes_in"] = scoring.get("bytes_in", 0) + int(
                    attrs.get("bytes_in", 0) or 0)
                scoring["bytes_out"] = scoring.get("bytes_out", 0) + int(
                    attrs.get("bytes_out", 0) or 0)
                scoring["dispatch_s"] = round(
                    scoring.get("dispatch_s", 0.0)
                    + float(attrs.get("dispatch_s", 0.0) or 0.0), 6)
                if attrs.get("quant"):
                    scoring["quant_dispatches"] = \
                        scoring.get("quant_dispatches", 0) + 1
            elif name == "breaker_open":
                resilience["breaker_opens"] = \
                    resilience.get("breaker_opens", 0) + 1
            elif name == "breaker_close":
                resilience["breaker_closes"] = \
                    resilience.get("breaker_closes", 0) + 1
            elif name == "health_transition":
                to = str(attrs.get("to") or "")
                if to == "quarantined":
                    resilience["quarantines"] = \
                        resilience.get("quarantines", 0) + 1
                rec = attrs.get("recovery_s")
                if rec is not None:
                    resilience["recoveries"] = \
                        resilience.get("recoveries", 0) + 1
                    mttrs.append(float(rec))
            elif name == "degraded_fallback":
                resilience["fallback_batches"] = \
                    resilience.get("fallback_batches", 0) + 1
                resilience["fallback_requests"] = \
                    resilience.get("fallback_requests", 0) + int(
                        attrs.get("requests", 0) or 0)
            elif name == "watchdog_restart":
                resilience["watchdog_restarts"] = \
                    resilience.get("watchdog_restarts", 0) + 1
            elif name == "slo_alert":
                sname = str(attrs.get("slo") or "unknown")
                per = slo.setdefault("by_slo", {}).setdefault(
                    sname, {"fired": 0, "resolved": 0,
                            "alert_s": 0.0})
                state = str(attrs.get("state") or "")
                if state == "firing":
                    slo["alerts_fired"] = slo.get("alerts_fired", 0) + 1
                    per["fired"] += 1
                elif state == "resolved":
                    slo["alerts_resolved"] = \
                        slo.get("alerts_resolved", 0) + 1
                    per["resolved"] += 1
                    per["alert_s"] = round(
                        per["alert_s"]
                        + float(attrs.get("alert_s", 0.0) or 0.0), 6)
            elif name == "supervisor_restart":
                continual["supervisor_restarts"] = \
                    continual.get("supervisor_restarts", 0) + 1
            elif name == "fault":
                counts["faults_injected"] += 1
            elif name == "steal":
                counts["steals"] += 1
            elif name == "worker_retired":
                counts["workers_retired"] += 1
            elif name == "hbm_preshrink":
                counts["hbm_preshrinks"] += 1
            elif name == "block_resize":
                counts["block_resizes"] += 1
            elif name == "perf_residual":
                perf_n += 1
                perf_err_sum += float(attrs.get("abs_rel_err", 0.0) or 0.0)
                t = str(attrs.get("target") or "unknown")
                perf_by_target[t] = perf_by_target.get(t, 0) + 1
            elif name == "continual_cycle":
                continual["cycles"] = continual.get("cycles", 0) + 1
                st = attrs.get("status") or "unknown"
                continual[st] = continual.get(st, 0) + 1
                continual["cycle_wall_s"] = round(
                    continual.get("cycle_wall_s", 0.0)
                    + float(attrs.get("wall_s", 0.0) or 0.0), 6)
                stale = attrs.get("staleness_s")
                if stale is not None:
                    continual["last_staleness_s"] = round(float(stale), 6)
            elif name == "drift_detected":
                continual["drift_detected"] = \
                    continual.get("drift_detected", 0) + 1
            elif name == "mesh_utilization":
                wall = float(attrs.get("wall_s", 0.0) or 0.0)
                workers = int(attrs.get("workers", 0) or 0)
                mesh_wall += wall * max(workers, 1)
                mesh_busy += (float(attrs.get("utilization_frac", 0.0)
                                    or 0.0) * wall * max(workers, 1))
                mesh["workers"] = max(mesh.get("workers", 0), workers)
                mesh["schedules"] = mesh.get("schedules", 0) + 1
                for key in ("steals", "requeues", "blocks"):
                    mesh[key] = mesh.get(key, 0) + int(
                        attrs.get(key, 0) or 0)
                mesh["idle_s"] = round(mesh.get("idle_s", 0.0) + float(
                    attrs.get("idle_s", 0.0) or 0.0), 6)
    # badput cannot exceed wall (overlapped worker backoffs can): clamp
    # proportionally so the decomposition stays a decomposition
    total_bad = sum(b.values())
    if total_bad > report.wall_s > 0.0:
        scale = report.wall_s / total_bad
        b = {k: v * scale for k, v in b.items()}
        total_bad = report.wall_s
    b["productive_s"] = max(0.0, report.wall_s - total_bad)
    report.buckets = b
    if saved > 0.0 or counts["resumed_blocks"]:
        report.savings["resume_saved_s"] = saved
    if cache_saved > 0.0 or counts["cache_hits"]:
        report.savings["cache_saved_s"] = cache_saved
    if compile_hits:
        report.savings["compile_cache_saved_s"] = compile_saved
        counts["compile_cache_hits"] = compile_hits
    if fleet:
        report.fleet = fleet
    if router:
        report.router = router
    if scoring:
        report.scoring = scoring
    if resilience:
        if mttrs:
            resilience["mean_mttr_s"] = round(sum(mttrs) / len(mttrs), 6)
            resilience["max_mttr_s"] = round(max(mttrs), 6)
        report.resilience = resilience
    if slo:
        report.slo = slo
    if mesh:
        mesh["utilization_frac"] = round(
            mesh_busy / mesh_wall, 4) if mesh_wall > 0 else 0.0
        # raw accumulators so fleet_mesh_rollup can re-weight across
        # hosts without re-walking each host's trace
        mesh["worker_wall_s"] = round(mesh_wall, 6)
        mesh["busy_s"] = round(mesh_busy, 6)
        report.mesh = mesh
    if continual:
        report.continual = continual
    if perf_n:
        report.perf = {
            "predictions": perf_n,
            "mean_abs_rel_err": round(perf_err_sum / perf_n, 4),
            "by_target": dict(sorted(perf_by_target.items()))}
    report.counts = {k: v for k, v in counts.items() if v}
    return report
