"""SLO burn-rate engine: declarative objectives over the live registries,
multi-window multi-burn-rate alerting, budget gauges, alert events.

The goodput line of work (arxiv 2502.06982) argues a fleet is managed by
its SERVICE objectives, not its raw counters; the serving plane (PRs
10-13) already exports every counter an SLO needs but nothing judges
them. This module closes that: an `SLOEngine` evaluates a list of
declarative `SLO`\\ s against `MetricsRegistry` counters/histograms/
gauges and raises/clears alerts with the standard multi-window
multi-burn-rate recipe (Google SRE workbook): an alert fires only when
the error-budget burn rate exceeds a threshold over BOTH a long window
(enough budget burned to matter) and a short window (it is still
happening now), which keeps pages fast on cliffs and quiet on blips.

Every SLO reduces to a GOOD/TOTAL pair sampled from cumulative
counters, so one burn-rate implementation serves all three kinds:

- ``availability``: good = total - errors - sheds (client-visible
  failures count against the budget);
- ``latency``: good = requests under ``threshold_s``, read from the
  cumulative bucket counts of a latency histogram (the le-bucket at or
  above the threshold) — the standard counter-ization of a latency SLO;
- ``staleness``: a time-slice SLO — each evaluation tick contributes
  one good/bad sample depending on whether the current staleness gauge
  is under ``threshold_s`` (the continual loop maintains the gauge).

Surfaces: ``/slo`` (HTTP JSON), ``slo_burn_rate{slo,window}`` /
``slo_budget_remaining{slo}`` / ``slo_alert_active{slo}`` gauges,
``slo_alert`` events in the trace timeline + JSONL log + flight
recorder (fired AND resolved, with measured time-in-alert), and a
GoodputReport ``slo`` section rolled from those events. The chaos
harness (`serving/chaos.py`) proves the loop: a seeded device-error
storm must fire the availability alert during the storm and clear it
after recovery.

Burn state is FLEET-WIDE, not per-replica: with `attach_fleet` each
replica CAS-publishes its cumulative good/total per SLO through a
`StateCell`, folds the cross-replica sum into a second sample ring, and
JUDGES that fleet ring with the same multi-window recipe — so a split
overload (each replica under threshold, the fleet over it) still pages.
The fleet alert is deduplicated through a CAS latch
(`obs.federate.FleetAlertLatch`): K replicas all see the crossing, ONE
emits the ``slo_alert`` event/flight dump. A stale cell (no recent
replica publishes) marks the fleet view not-fresh — consumers like the
autopilot fall back to LOCAL burn rather than reading silence as
health.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

__all__ = ["SLO", "SLOParams", "SLOEngine", "BurnWindow",
           "maybe_attach_fleet"]

# opt-in fleet burn sharing: the shared store dir + this replica's name
ENV_FLEET_DIR = "TRANSMOGRIFAI_SLO_FLEET_DIR"
ENV_REPLICA = "TRANSMOGRIFAI_SLO_REPLICA"


def maybe_attach_fleet(engine: "SLOEngine") -> bool:
    """Attach `engine` to the fleet burn cell when the env opts in
    (``TRANSMOGRIFAI_SLO_FLEET_DIR``). Replica identity comes from
    ``TRANSMOGRIFAI_SLO_REPLICA``, falling back to the perf replica
    name, falling back to the pid. Never raises."""
    root = os.environ.get(ENV_FLEET_DIR)
    if not root:
        return False
    replica = (os.environ.get(ENV_REPLICA)
               or os.environ.get("TRANSMOGRIFAI_PERF_REPLICA")
               or f"pid{os.getpid()}")
    try:
        engine.attach_fleet(root, replica)
        return True
    except Exception:
        log.debug("slo: fleet attach failed", exc_info=True)
        return False


@dataclass
class BurnWindow:
    """One (long, short, burn-threshold) alerting pair. ``burn`` is in
    budget-multiples: burn 14.4 over 1h/5m is the classic fast-page
    (2% of a 30-day budget in one hour); burn 1.0 over 3d/6h is the
    slow ticket."""

    long_s: float
    short_s: float
    burn: float
    severity: str = "page"

    def to_json(self) -> Dict[str, Any]:
        return {"long_s": self.long_s, "short_s": self.short_s,
                "burn": self.burn, "severity": self.severity}


# the standard multiwindow ladder (seconds), scaled by SLOParams.time_scale
DEFAULT_WINDOWS: Tuple[Tuple[float, float, float, str], ...] = (
    (3600.0, 300.0, 14.4, "page"),        # fast: 1h / 5m
    (21600.0, 1800.0, 6.0, "page"),       # 6h / 30m
    (259200.0, 21600.0, 1.0, "ticket"),   # slow: 3d / 6h
)


@dataclass
class SLO:
    """One declarative objective. ``kind``: availability | latency |
    staleness. ``objective`` is the good-fraction target (0.999 =
    "three nines"); latency/staleness additionally carry
    ``threshold_s`` (what counts as good). ``tenant``/``model`` scope
    the metric selectors on a fleet."""

    name: str
    kind: str = "availability"
    objective: float = 0.999
    threshold_s: Optional[float] = None
    tenant: Optional[str] = None
    model: Optional[str] = None

    _FIELDS = ("name", "kind", "objective", "threshold_s", "tenant",
               "model")

    def __post_init__(self):
        if self.kind not in ("availability", "latency", "staleness"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not (0.0 < self.objective < 1.0):
            raise ValueError(
                f"objective must be in (0,1): {self.objective}")
        if self.kind in ("latency", "staleness") \
                and not self.threshold_s:
            raise ValueError(f"{self.kind} SLO needs threshold_s")

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "SLO":
        return SLO(**{k: d[k] for k in SLO._FIELDS if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS
                if getattr(self, k) is not None}

    @property
    def budget(self) -> float:
        """The error budget: the allowed bad fraction."""
        return 1.0 - self.objective


@dataclass
class SLOParams:
    """JSON-loadable engine config (``ServingConfig.slo`` /
    ``ServingParams.slo`` / ``FleetConfig.slo``)::

        {"slos": [{"name": "avail", "kind": "availability",
                   "objective": 0.999, "tenant": "gold"}],
         "time_scale": 1.0, "eval_period_s": 5.0}

    ``time_scale`` shrinks every burn window by the same factor —
    chaos/smoke runs use 0.001-ish scales so a 3-second storm exercises
    the same fast-window/slow-window machinery a real 30-minute outage
    would. An empty/absent ``slos`` list defaults to one process-wide
    99.9% availability SLO."""

    enabled: bool = True
    slos: List[Dict[str, Any]] = field(default_factory=list)
    time_scale: float = 1.0
    eval_period_s: float = 5.0
    # override the default multiwindow ladder: [[long_s, short_s, burn,
    # severity], ...] (pre-scale)
    windows: Optional[List[List[Any]]] = None

    _FIELDS = ("enabled", "slos", "time_scale", "eval_period_s",
               "windows")

    def __post_init__(self):
        if self.time_scale <= 0 or self.eval_period_s <= 0:
            raise ValueError("time_scale/eval_period_s must be > 0")

    @staticmethod
    def from_json(d: Optional[Dict[str, Any]]) -> "SLOParams":
        d = d or {}
        return SLOParams(**{k: d[k] for k in SLOParams._FIELDS if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}

    def build_slos(self) -> List[SLO]:
        if self.slos:
            return [SLO.from_json(dict(d)) for d in self.slos]
        return [SLO(name="availability", kind="availability",
                    objective=0.999)]

    def build_windows(self) -> List[BurnWindow]:
        raw = self.windows or [list(w) for w in DEFAULT_WINDOWS]
        out = []
        for w in raw:
            long_s, short_s, burn = float(w[0]), float(w[1]), float(w[2])
            sev = str(w[3]) if len(w) > 3 else "page"
            out.append(BurnWindow(long_s * self.time_scale,
                                  short_s * self.time_scale, burn, sev))
        return out


# good/total source: () -> (good, total) cumulative floats
Source = Callable[[], Tuple[float, float]]


class _SLOState:
    """Per-SLO sample ring + alert latch."""

    def __init__(self, slo: SLO, source: Source, max_window_s: float,
                 eval_period_s: float):
        self.slo = slo
        self.source = source
        # enough samples to cover the longest window at the eval cadence
        # (+ slack for jitter), bounded regardless of uptime
        n = max(16, int(max_window_s / max(eval_period_s, 1e-3)) + 8)
        self.samples: List[Tuple[float, float, float]] = []  # (t, good, tot)
        self.max_samples = min(n, 100_000)
        self.firing = False
        self.fired_at: Optional[float] = None
        self.last_change: Optional[float] = None
        self.fired_windows: List[str] = []
        self.alerts = 0
        self.replicas = 0  # fleet fold only: replicas seen last tick

    def sample(self, now: float) -> None:
        good, total = self.source()
        self.samples.append((now, float(good), float(total)))
        if len(self.samples) > self.max_samples:
            del self.samples[:len(self.samples) - self.max_samples]

    def window_rate(self, now: float, window_s: float
                    ) -> Optional[float]:
        """Bad fraction over the trailing window, from cumulative
        sample deltas; None when the window saw no traffic."""
        if not self.samples:
            return None
        cutoff = now - window_s
        # the newest sample at or before the cutoff anchors the delta
        # (so a window is never silently narrower than asked)
        anchor = self.samples[0]
        for s in self.samples:
            if s[0] <= cutoff:
                anchor = s
            else:
                break
        last = self.samples[-1]
        d_total = last[2] - anchor[2]
        if d_total <= 0:
            return None
        d_bad = max(0.0, d_total - (last[1] - anchor[1]))
        return min(1.0, d_bad / d_total)


class SLOEngine:
    """Evaluate SLOs against registries; see module docstring.

    `sources` maps SLO name -> good/total callable; `attach_*` helpers
    build the standard ones. `evaluate()` is one tick (tests and the
    serving watchdog cadence call it directly); `start()` runs it on an
    own named thread at ``eval_period_s``."""

    def __init__(self, params: Optional[SLOParams] = None,
                 registry=None):
        self.params = params or SLOParams()
        self.registry = registry
        self.windows = self.params.build_windows()
        self._lock = threading.Lock()
        self._states: Dict[str, _SLOState] = {}
        self._thread: Optional[threading.Thread] = None
        self._halt = threading.Event()
        # the span alert events attach to: the engine thread has no
        # ambient span, so the owning service pins its serving-trace
        # parent here at start() — slo_alert events then land in the
        # run's trace timeline and its GoodputReport `slo` section
        self.span = None
        # fleet burn sharing (attach_fleet): each replica publishes its
        # cumulative good/total per SLO through a StateCell; everyone
        # folds the cell's sum into a second, fleet-wide sample ring
        self._fleet_cell = None        # guarded-by: self._lock
        self._fleet_replica = ""       # guarded-by: self._lock
        self._fleet_latch = None       # guarded-by: self._lock
        self._fleet_states: Dict[str, _SLOState] = {}  # engine thread only
        # wall-clock freshness of the fleet fold: consumers must never
        # read a dead cell's frozen burn as "healthy fleet"
        self._fleet_last_fold = 0.0    # engine thread only
        self._fleet_fresh_replicas = 0  # engine thread only
        max_window = max((w.long_s for w in self.windows), default=60.0)
        self._max_window_s = max_window
        for slo in self.params.build_slos():
            self._states[slo.name] = _SLOState(
                slo, lambda: (0.0, 0.0), max_window,
                self.params.eval_period_s)

    # -- wiring -------------------------------------------------------------- #

    def slos(self) -> List[SLO]:
        with self._lock:
            return [st.slo for st in self._states.values()]

    def set_source(self, name: str, source: Source) -> None:
        with self._lock:
            if name not in self._states:
                raise KeyError(f"no SLO named {name!r}")
            self._states[name].source = source

    def add_slo(self, slo: SLO, source: Source) -> None:
        with self._lock:
            self._states[slo.name] = _SLOState(
                slo, source, self._max_window_s,
                self.params.eval_period_s)

    def attach_fleet(self, store_root: str, replica: str,
                     name: str = "default") -> "SLOEngine":
        """Share burn state across replicas through a `StateCell` on the
        shared store. Each `evaluate()` tick CAS-publishes this
        replica's cumulative good/total per SLO, folds the cell's
        cross-replica sum into a fleet sample ring, and JUDGES that
        ring with the same multi-window recipe — `/slo` (`status()`)
        reports fleet-wide burn and alert state beside the local one,
        and the fleet alert is emitted by exactly ONE replica (CAS
        latch). Cumulative sums mean a restarted replica's counter
        reset shows up as a no-delta window (no data), not a phantom
        recovery."""
        from transmogrifai_tpu.obs.federate import FleetAlertLatch
        from transmogrifai_tpu.store.state import StateCell
        with self._lock:
            self._fleet_cell = StateCell(store_root, f"slo-fleet-{name}")
            self._fleet_replica = str(replica)
            self._fleet_latch = FleetAlertLatch(store_root, name=name)
        return self

    def _fleet_tick(self, states: List["_SLOState"], now: float) -> None:
        """Publish local cumulative counters + fold the fleet sum.
        Engine-thread only (called from evaluate())."""
        with self._lock:
            cell = self._fleet_cell
            replica = self._fleet_replica
        if cell is None:
            return
        mine = {st.slo.name: [st.samples[-1][1], st.samples[-1][2]]
                for st in states if st.samples}

        def put(cur):
            cur = dict(cur or {})
            reps = dict(cur.get("replicas") or {})
            reps[replica] = {"slos": mine, "ts": time.time()}
            cur["replicas"] = reps
            return cur

        try:
            merged = cell.update(put)
        except Exception:
            log.debug("slo: fleet cell publish failed", exc_info=True)
            return
        reps = (merged or {}).get("replicas") or {}
        wall_now = time.time()
        horizon = self._fleet_fresh_horizon_s()
        self._fleet_last_fold = wall_now
        self._fleet_fresh_replicas = sum(
            1 for rep in reps.values()
            if wall_now - float(rep.get("ts") or 0.0) <= horizon)
        for st in states:
            good = total = 0.0
            n = 0
            for rep in reps.values():
                row = (rep.get("slos") or {}).get(st.slo.name)
                if row:
                    good += float(row[0])
                    total += float(row[1])
                    n += 1
            fst = self._fleet_states.get(st.slo.name)
            if fst is None:
                fst = self._fleet_states[st.slo.name] = _SLOState(
                    st.slo, lambda: (0.0, 0.0), self._max_window_s,
                    self.params.eval_period_s)
            fst.samples.append((now, good, total))
            if len(fst.samples) > fst.max_samples:
                del fst.samples[:len(fst.samples) - fst.max_samples]
            fst.replicas = n
            self._judge_fleet(fst, now)

    def _fleet_fresh_horizon_s(self) -> float:
        """How stale the fleet fold / a replica's publish may be before
        the fleet view stops counting as live."""
        return max(2.0, 10.0 * self.params.eval_period_s)

    def fleet_fresh(self) -> bool:
        """True while the fleet fold is recent AND at least one replica
        published within the horizon — the autopilot's gate for
        preferring fleet burn over local. Engine-thread state read
        without the lock (floats/ints, torn reads are benign)."""
        with self._lock:
            if self._fleet_cell is None:
                return False
        horizon = self._fleet_fresh_horizon_s()
        # cross-process freshness: the fold's epoch stamp against our
        # epoch clock — wall time is the only clock replicas share
        wall_now = time.time()
        return (wall_now - self._fleet_last_fold <= horizon
                and self._fleet_fresh_replicas >= 1)

    def _judge_fleet(self, fst: _SLOState, now: float) -> None:
        """Judge the fleet-folded ring with the same multi-window
        recipe as `_judge`, but dedupe the EMISSION through the CAS
        latch: every replica flips its local fleet bookkeeping, exactly
        one gets claimed=True per transition and emits the alert event
        + flight dump. Engine-thread only."""
        budget = fst.slo.budget
        fired: List[str] = []
        for w in self.windows:
            long_rate = fst.window_rate(now, w.long_s)
            short_rate = fst.window_rate(now, w.short_s)
            if long_rate is None or short_rate is None:
                continue
            if long_rate / budget >= w.burn \
                    and short_rate / budget >= w.burn:
                fired.append(f"{w.severity}:{w.long_s:g}s")
        was = fst.firing
        fst.fired_windows = fired
        fst.firing = bool(fired)
        if fst.firing == was:
            return
        state = "firing" if fst.firing else "resolved"
        if fst.firing:
            fst.fired_at = now
        fst.last_change = now
        with self._lock:
            latch = self._fleet_latch
            replica = self._fleet_replica
        claimed = True
        if latch is not None:
            claimed, _ = latch.transition(
                fst.slo.name, "firing" if fst.firing else "ok", replica)
        if claimed:
            if fst.firing:
                fst.alerts += 1
            self._note_alert(fst, state, now, scope="fleet")
        if self.registry is not None:
            self.registry.gauge(
                "slo_fleet_alert_active",
                "1 while the fleet-level SLO alert is firing",
                slo=fst.slo.name).set(1.0 if fst.firing else 0.0)

    # -- evaluation ---------------------------------------------------------- #

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One tick: sample every SLO, recompute burn rates per window,
        latch/unlatch alerts, refresh gauges. Returns `status()`."""
        now = time.monotonic() if now is None else now
        with self._lock:
            states = list(self._states.values())
        for st in states:
            try:
                st.sample(now)
            except Exception:
                log.debug("slo: source for %s failed", st.slo.name,
                          exc_info=True)
                continue
            self._judge(st, now)
        self._fleet_tick(states, now)
        return self.status(now=now)

    def _judge(self, st: _SLOState, now: float) -> None:
        budget = st.slo.budget
        fired: List[str] = []
        for w in self.windows:
            long_rate = st.window_rate(now, w.long_s)
            short_rate = st.window_rate(now, w.short_s)
            if long_rate is None or short_rate is None:
                continue
            if long_rate / budget >= w.burn \
                    and short_rate / budget >= w.burn:
                fired.append(f"{w.severity}:{w.long_s:g}s")
        was = st.firing
        st.fired_windows = fired
        st.firing = bool(fired)
        if st.firing and not was:
            st.fired_at = now
            st.last_change = now
            st.alerts += 1
            self._note_alert(st, "firing", now)
        elif was and not st.firing:
            st.last_change = now
            self._note_alert(st, "resolved", now)
        self._gauges(st, now)

    def _note_alert(self, st: _SLOState, state: str, now: float,
                    scope: str = "local") -> None:
        attrs: Dict[str, Any] = {
            "slo": st.slo.name, "state": state,
            "objective": st.slo.objective,
            "windows": ",".join(st.fired_windows)}
        if scope != "local":
            attrs["scope"] = scope
            attrs["replicas"] = st.replicas
        if state == "resolved" and st.fired_at is not None:
            attrs["alert_s"] = round(now - st.fired_at, 3)
        try:
            from transmogrifai_tpu.obs.export import record_event
            if self.span is not None:
                # explicit span target (the engine thread has no
                # ambient span): the event lands on the owning run's
                # trace; record_event still feeds the JSONL log +
                # flight ring
                self.span.event("slo_alert", **attrs)
            record_event("slo_alert", **attrs)
        except Exception:
            log.debug("slo_alert event emission failed", exc_info=True)
        if state == "firing":
            # an SLO alert IS an incident: snapshot the flight ring so
            # the burn's cause is in the post-mortem even if nothing
            # else (breaker, watchdog) trips
            try:
                from transmogrifai_tpu.obs import flight
                flight.request_dump("slo_alert" if scope == "local"
                                    else "fleet_slo_alert")
            except Exception:  # best-effort black box
                log.debug("flight dump on slo alert failed",
                          exc_info=True)
        log.log(logging.WARNING if state == "firing" else logging.INFO,
                "slo: %s%s %s (%s)", st.slo.name,
                "" if scope == "local" else f" [{scope}]", state,
                attrs.get("windows") or "recovered")

    def _gauges(self, st: _SLOState, now: float) -> None:
        if self.registry is None:
            return
        budget = st.slo.budget
        for w in self.windows:
            rate = st.window_rate(now, w.long_s)
            self.registry.gauge(
                "slo_burn_rate",
                "error-budget burn rate per SLO and long window",
                slo=st.slo.name, window=f"{w.long_s:g}s"
            ).set(0.0 if rate is None else rate / budget)
        slow = self.windows[-1] if self.windows else None
        remaining = 1.0
        if slow is not None:
            rate = st.window_rate(now, slow.long_s)
            if rate is not None:
                remaining = max(0.0, 1.0 - rate / budget)
        self.registry.gauge(
            "slo_budget_remaining",
            "fraction of the error budget left over the slow window",
            slo=st.slo.name).set(remaining)
        self.registry.gauge(
            "slo_alert_active", "1 while the SLO's alert is firing",
            slo=st.slo.name).set(1.0 if st.firing else 0.0)

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The `/slo` endpoint payload."""
        now = time.monotonic() if now is None else now
        with self._lock:
            states = list(self._states.values())
        slos: Dict[str, Any] = {}
        for st in states:
            budget = st.slo.budget
            burns = {}
            for w in self.windows:
                rate = st.window_rate(now, w.long_s)
                srate = st.window_rate(now, w.short_s)
                burns[f"{w.long_s:g}s/{w.short_s:g}s"] = {
                    "threshold": w.burn, "severity": w.severity,
                    "long_burn": (None if rate is None
                                  else round(rate / budget, 4)),
                    "short_burn": (None if srate is None
                                   else round(srate / budget, 4)),
                }
            slow = self.windows[-1] if self.windows else None
            remaining = None
            if slow is not None:
                rate = st.window_rate(now, slow.long_s)
                if rate is not None:
                    remaining = round(max(0.0, 1.0 - rate / budget), 4)
            slos[st.slo.name] = {
                **st.slo.to_json(),
                "state": "firing" if st.firing else "ok",
                "fired_windows": list(st.fired_windows),
                "alerts": st.alerts,
                "budget_remaining": remaining,
                "windows": burns,
                "samples": len(st.samples),
            }
            fst = self._fleet_states.get(st.slo.name)
            if fst is not None:
                fleet_burns = {}
                fleet_windows = {}
                for w in self.windows:
                    rate = fst.window_rate(now, w.long_s)
                    srate = fst.window_rate(now, w.short_s)
                    fleet_burns[f"{w.long_s:g}s"] = (
                        None if rate is None
                        else round(rate / budget, 4))
                    fleet_windows[f"{w.long_s:g}s/{w.short_s:g}s"] = {
                        "threshold": w.burn, "severity": w.severity,
                        "long_burn": (None if rate is None
                                      else round(rate / budget, 4)),
                        "short_burn": (None if srate is None
                                       else round(srate / budget, 4)),
                    }
                slos[st.slo.name]["fleet"] = {
                    "replicas": fst.replicas,
                    "burn": fleet_burns,
                    "windows": fleet_windows,
                    "state": "firing" if fst.firing else "ok",
                    "fired_windows": list(fst.fired_windows),
                    "alerts": fst.alerts,
                    "samples": len(fst.samples),
                    "fresh": self.fleet_fresh(),
                }
        out = {"slos": slos,
               "windows": [w.to_json() for w in self.windows],
               "eval_period_s": self.params.eval_period_s}
        with self._lock:
            if self._fleet_cell is not None:
                out["fleet_replica"] = self._fleet_replica
        return out

    def firing(self) -> List[str]:
        with self._lock:
            return [n for n, st in self._states.items() if st.firing]

    # -- lifecycle ------------------------------------------------------------ #

    def start(self) -> "SLOEngine":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._halt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="slo-engine", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._halt.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._halt.wait(timeout=self.params.eval_period_s):
            try:
                self.evaluate()
            except Exception:
                log.exception("slo: evaluation tick failed")


# -- standard sources --------------------------------------------------------- #

def availability_source(registry, requests_family: str,
                        error_families: Tuple[str, ...] = (),
                        shed_families: Tuple[str, ...] = (),
                        requests_count: str = "admitted",
                        **label_filter: Any) -> Source:
    """good/total from cumulative counters. `requests_count` names what
    `requests_family` actually ticks:

    - ``"admitted"``: every admitted request, errors included (the
      single-service `serving_requests_total` semantics) — good =
      requests − errors, total = requests + sheds;
    - ``"successes"``: successful requests ONLY (the fleet's
      `fleet_requests_total`, ticked in `Router.note_success`) — good =
      requests, total = requests + errors + sheds. Wiring a
      successes-only family as "admitted" makes the SLO BLIND during a
      total outage (no successes → zero denominator → no window rate →
      no alert), which is the one failure mode an availability alert
      must not have.

    Sheds are client-visible failures either way: they grow the
    denominator AND count against the budget."""
    if requests_count not in ("admitted", "successes"):
        raise ValueError(
            f"requests_count must be 'admitted' or 'successes': "
            f"{requests_count!r}")

    def src() -> Tuple[float, float]:
        requests = registry.sum_family(requests_family, **label_filter)
        errors = sum(registry.sum_family(f, **label_filter)
                     for f in error_families)
        sheds = sum(registry.sum_family(f, **label_filter)
                    for f in shed_families)
        if requests_count == "successes":
            return requests, requests + errors + sheds
        return max(0.0, requests - errors), requests + sheds
    return src


def latency_source(registry, family: str, threshold_s: float,
                   **label_filter: Any) -> Source:
    """good/total from a latency histogram family's cumulative buckets:
    good = observations at or under the smallest bucket bound >=
    threshold. Aggregates across EVERY series matching `label_filter`
    (a per-tenant-labeled family with no tenant scope sums all
    tenants) — an exact-key lookup would silently never match a
    labeled family and leave the SLO permanently "ok" with no data."""
    def src() -> Tuple[float, float]:
        good = 0.0
        total = 0.0
        for hist in registry.find_all(family, **label_filter):
            series_good = None
            series_total = 0.0
            for bound, cum in hist.bucket_counts():
                series_total = float(cum)
                if series_good is None and bound >= threshold_s:
                    series_good = float(cum)
            good += series_total if series_good is None else series_good
            total += series_total
        return good, total
    return src


def staleness_source(registry, gauge_family: str, threshold_s: float,
                     **labels: Any) -> Source:
    """Time-slice SLO: each tick contributes one sample — good while
    the current staleness gauge is under the threshold. Cumulative
    counts are synthesized on the closure so the burn-rate windows see
    a good/total stream like any other SLO.

    A MISSING gauge is no-data, not freshness: until a continual loop
    publishes it, the sample counters stay frozen, window rates return
    None, and the SLO reports no burn instead of a fraudulent "ok"."""
    state = {"good": 0.0, "total": 0.0}

    def src() -> Tuple[float, float]:
        g = registry.find(gauge_family, **labels)
        if g is not None:
            state["total"] += 1.0
            if g.value <= threshold_s:
                state["good"] += 1.0
        return state["good"], state["total"]
    return src
