"""Thread-safe hierarchical span tracer (the unified-timeline half of the
observability layer).

Every subsystem in this codebase already times itself — `RunProfile`
phases, `IngestStats` stage timers, serving latency histograms,
`RetraceMonitor` compile counts — but each island keeps its own clock
and none can be correlated into one timeline. A `Span` is the shared
currency: a named, attributed interval with a parent, so a retry
backoff inside an ingest worker inside a training run renders as ONE
nested tree (exported to Perfetto by `obs/export.py`, rolled into
goodput/badput buckets by `obs/goodput.py`).

Design constraints this module answers:

- **contextvar propagation**: the current span lives in a
  `contextvars.ContextVar`, so nesting works without threading a span
  handle through every call signature. Worker threads (ingest pool,
  serving batcher, selector families) do NOT inherit the caller's
  context — cross-thread parents are passed EXPLICITLY via
  ``tracer.span(..., parent=span)``, which also sets the contextvar in
  the worker for anything it calls (e.g. a `RetryPolicy` backoff span
  opened inside a worker chunk span).
- **two clocks**: span durations come from `time.perf_counter()`
  (monotonic — wall-clock steps must not corrupt durations; satellite
  of the same PR fixes `RunProfile` the same way), while each span also
  carries an epoch `start_at` for humans. Export timestamps derive from
  the perf clock against one process epoch, so they are monotonic and
  non-negative by construction.
- **bounded memory**: finished spans collect in a ring (default 64k);
  a long-lived serving process drops the oldest and counts the drops
  instead of growing without bound.

The tracer is always on: an un-exported span costs one object and two
clock reads, which is noise next to anything worth tracing here (file
IO, XLA dispatch, model fits).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import logging
import re
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple)

__all__ = ["Span", "Tracer", "TRACER", "get_tracer", "current_span",
           "add_event", "ambient_traceparent", "new_run_id", "now_s",
           "new_trace_id", "span_id_hex", "parse_traceparent",
           "format_traceparent", "TraceContext", "RequestTrace",
           "TracingParams", "TailSampler"]

# one process epoch for both clocks: export timestamps are
# perf_counter-relative to this origin, mapped onto the epoch origin
_EPOCH_PERF = time.perf_counter()
_EPOCH_TIME = time.time()

_span_ids = itertools.count(1)


def new_run_id() -> str:
    """Run-level correlation id: unique across processes, short enough
    to grep in a JSONL event log."""
    return uuid.uuid4().hex[:12]


def now_s() -> float:
    """Perf-clock offset from the process trace epoch — the timebase
    every span's start_s/end_s lives in. Exposed so code that measures
    a phase boundary OUTSIDE a span (e.g. the micro-batcher's enqueue
    tick) can later backdate a span to it."""
    return time.perf_counter() - _EPOCH_PERF


# -- W3C trace context (traceparent) ----------------------------------------- #

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id() -> str:
    """A W3C-shaped 32-hex trace id (uuid4 bytes)."""
    return uuid.uuid4().hex


def span_id_hex(span_id: int) -> str:
    """A span id as the 16-hex W3C parent-id field."""
    return format(span_id & 0xFFFFFFFFFFFFFFFF, "016x")


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str, bool]]:
    """Parse a W3C ``traceparent`` header into ``(trace_id,
    parent_span_id, sampled)``; None for a missing/malformed header or
    the all-zero ids the spec forbids. Unknown versions are accepted
    per spec (fields we understand are read; ff is invalid)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, parent_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return trace_id, parent_id, bool(int(flags, 16) & 0x01)


def format_traceparent(trace_id: str, span_id: Any,
                       sampled: bool = True) -> str:
    """Render a version-00 ``traceparent``. `trace_id` shorter than 32
    hex chars (internal run ids are 12) is left-padded with zeros so
    the header stays spec-shaped; `span_id` may be an int (internal
    span ids) or a 16-hex string."""
    tid = str(trace_id).lower()
    tid = ("0" * 32 + re.sub(r"[^0-9a-f]", "", tid))[-32:]
    sid = span_id_hex(span_id) if isinstance(span_id, int) \
        else str(span_id).lower()
    return f"00-{tid}-{sid}-{'01' if sampled else '00'}"


class Span:
    """One named interval in a trace tree.

    `attributes` are set at open (`tracer.span(name, key=val)`) or later
    via `set()`; `events` are point-in-time markers inside the span
    (recompiles, journal resumes, injected faults). `end()` is
    idempotent; an un-ended span exports with "now" as its end so a
    live process can still dump a coherent trace.
    """

    __slots__ = ("name", "category", "span_id", "parent_id", "trace_id",
                 "start_s", "end_s", "start_at", "attributes", "events",
                 "thread_id", "thread_name", "error")

    def __init__(self, name: str, category: str = "span",
                 parent: Optional["Span"] = None,
                 trace_id: Optional[str] = None,
                 attributes: Optional[Dict[str, Any]] = None):
        self.name = name
        self.category = category
        self.span_id = next(_span_ids)
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = trace_id or (
            parent.trace_id if parent is not None else new_run_id())
        self.start_s = time.perf_counter() - _EPOCH_PERF
        self.end_s: Optional[float] = None
        self.start_at = _EPOCH_TIME + self.start_s
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.events: List[Tuple[str, float, Dict[str, Any]]] = []
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self.error: Optional[str] = None

    # -- mutation ---------------------------------------------------------- #

    def set(self, **attributes: Any) -> "Span":
        self.attributes.update(attributes)
        return self

    def event(self, name: str, **attributes: Any) -> None:
        """Point-in-time marker inside this span (exported as a Perfetto
        instant event)."""
        self.events.append(
            (name, time.perf_counter() - _EPOCH_PERF, dict(attributes)))

    def end(self) -> None:
        if self.end_s is None:
            self.end_s = time.perf_counter() - _EPOCH_PERF

    # -- views ------------------------------------------------------------- #

    @property
    def duration_s(self) -> float:
        end = self.end_s if self.end_s is not None \
            else time.perf_counter() - _EPOCH_PERF
        return max(0.0, end - self.start_s)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name, "category": self.category,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start_at": round(self.start_at, 6),
            "duration_s": round(self.duration_s, 6),
            "thread": self.thread_name,
            "attributes": self.attributes,
            "events": [{"name": n, "offset_s": round(t - self.start_s, 6),
                        **a} for n, t, a in self.events],
            "error": self.error,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, {self.duration_s:.4f}s)")


class Tracer:
    """Process span collector + contextvar-based current-span tracking.

    One global instance (`TRACER`) serves the whole process; tests that
    need isolation construct their own or call `reset()`.
    """

    def __init__(self, max_spans: int = 65536):
        self._lock = threading.Lock()
        self._finished: deque = deque(maxlen=max_spans)
        self._live: Dict[int, Span] = {}
        self.dropped = 0
        # finished-span sinks (the flight recorder's feed): called once
        # per finished span, outside the tracer lock, exceptions eaten —
        # a broken sink must never take down a scoring thread
        self._sinks: List[Callable[[Span], None]] = []
        # NOTE: a per-Tracer ContextVar would leak on tracer churn;
        # module scope is fine because tests always reset the global.
        self._current: contextvars.ContextVar[Optional[Span]] = \
            contextvars.ContextVar(f"obs_span_{id(self)}", default=None)

    # -- span lifecycle ---------------------------------------------------- #

    @contextlib.contextmanager
    def span(self, name: str, category: str = "span",
             parent: Optional[Span] = None, new_trace: bool = False,
             trace_id: Optional[str] = None,
             **attributes: Any) -> Iterator[Span]:
        """Open a child of `parent` (explicit, for cross-thread nesting)
        or of the calling context's current span. `new_trace=True` roots
        a fresh trace — under `trace_id` when given (the runner passes
        its run correlation id, so the trace, the profile, and the JSONL
        event log all share ONE id), else a fresh one. Exceptions —
        including BaseExceptions like an injected kill — are recorded on
        the span and re-raised."""
        if parent is None and not new_trace:
            parent = self._current.get()
        sp = Span(name, category=category,
                  parent=None if new_trace else parent,
                  trace_id=(trace_id or new_run_id()) if new_trace
                  else trace_id,
                  attributes=attributes)
        with self._lock:
            self._live[sp.span_id] = sp
        token = self._current.set(sp)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._current.reset(token)
            sp.end()
            with self._lock:
                self._live.pop(sp.span_id, None)
                if len(self._finished) == self._finished.maxlen:
                    self.dropped += 1
                self._finished.append(sp)
            self._notify(sp)

    def current(self) -> Optional[Span]:
        return self._current.get()

    # -- sinks + out-of-band collection ------------------------------------- #

    def add_sink(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def _notify(self, sp: Span) -> None:
        for fn in list(self._sinks):
            try:
                fn(sp)
            except Exception:  # a broken sink must not break tracing
                logging.getLogger(__name__).debug(
                    "span sink %r failed", fn, exc_info=True)

    def collect(self, spans: Iterable[Span]) -> None:
        """Admit externally-constructed FINISHED spans into the ring
        (the tail sampler's kept request traces come through here:
        their spans are buffered per request and only land in the
        process timeline once the keep decision is made)."""
        spans = list(spans)
        with self._lock:
            for sp in spans:
                if sp.end_s is None:
                    sp.end()
                if len(self._finished) == self._finished.maxlen:
                    self.dropped += 1
                self._finished.append(sp)
        for sp in spans:
            self._notify(sp)

    # -- collection views --------------------------------------------------- #

    def spans(self) -> List[Span]:
        """Finished spans, oldest first (live spans excluded)."""
        with self._lock:
            return list(self._finished)

    def trace_spans(self, trace_id: str,
                    include_live: bool = True) -> List[Span]:
        """Every span of one trace (one runner invocation), finished and
        — by default — still-open, sorted by start time."""
        with self._lock:
            out = [s for s in self._finished if s.trace_id == trace_id]
            if include_live:
                out += [s for s in self._live.values()
                        if s.trace_id == trace_id]
        return sorted(out, key=lambda s: (s.start_s, s.span_id))

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._live.clear()
            self.dropped = 0


TRACER = Tracer()


def get_tracer() -> Tracer:
    return TRACER


def current_span() -> Optional[Span]:
    """The calling context's innermost open span on the global tracer."""
    return TRACER.current()


def add_event(name: str, **attributes: Any) -> bool:
    """Attach an instant event to the current span, if any. The no-span
    case is a cheap no-op so library code can emit unconditionally."""
    sp = TRACER.current()
    if sp is None:
        return False
    sp.event(name, **attributes)
    return True


def ambient_traceparent() -> Optional[str]:
    """The calling context's current span as a W3C ``traceparent``
    header value, or None with no span open — how out-of-band state
    (pod lease claims, published records) stamps the trace it belongs
    to without threading a span handle through every signature."""
    sp = TRACER.current()
    if sp is None:
        return None
    return format_traceparent(sp.trace_id, sp.span_id, sampled=True)


# -- request-scoped tracing --------------------------------------------------- #

@dataclass
class TraceContext:
    """Incoming trace context for one request: a W3C wire context
    (`trace_id` + `parent_hex`, from a ``traceparent`` header) or an
    in-process parent span (the continual loop parents its live
    holdout requests under the cycle span). ``sampled`` carries the
    caller's sampling decision: a sampled=01 wire context (or any
    in-process parent) is force-kept past tail sampling, so a
    distributed trace never loses its serving leg."""

    trace_id: Optional[str] = None
    parent_hex: Optional[str] = None
    parent: Optional[Span] = None
    sampled: bool = False

    @staticmethod
    def from_traceparent(header: Optional[str]) -> Optional["TraceContext"]:
        parsed = parse_traceparent(header)
        if parsed is None:
            return None
        trace_id, parent_hex, sampled = parsed
        return TraceContext(trace_id=trace_id, parent_hex=parent_hex,
                            sampled=sampled)

    @staticmethod
    def from_span(sp: Optional[Span]) -> Optional["TraceContext"]:
        if sp is None:
            return None
        return TraceContext(trace_id=sp.trace_id, parent=sp, sampled=True)


class RequestTrace:
    """One request's span buffer: a root ``serving:request`` span plus
    phase children, held OUT of the process ring until the tail sampler
    decides to keep it (`Tracer.collect`). Children may be opened live
    (`child(...)` context manager, caller thread) or BACKDATED from
    measured phase boundaries (`child_at(...)`, the scoring thread's
    per-batch timestamps replicated onto every member request)."""

    __slots__ = ("root", "spans", "forced", "enqueued_s", "_done")

    def __init__(self, name: str = "serving:request",
                 ctx: Optional[TraceContext] = None,
                 **attributes: Any):
        ctx = ctx or TraceContext()
        self.root = Span(name, category="serving",
                         parent=ctx.parent,
                         trace_id=ctx.trace_id or new_trace_id(),
                         attributes=attributes)
        if ctx.parent is None and ctx.parent_hex:
            # wire-context parent: not an in-process span, carried as an
            # attribute so the exported trace still links to the caller
            self.root.attributes["parent_traceparent"] = ctx.parent_hex
        self.forced = ctx.sampled or ctx.parent is not None
        self.spans: List[Span] = [self.root]
        self.enqueued_s: Optional[float] = None
        self._done = False

    @property
    def trace_id(self) -> str:
        return self.root.trace_id

    def traceparent(self, sampled: bool = True) -> str:
        """The response-header echo: same trace id, the request root as
        the span id."""
        return format_traceparent(self.root.trace_id, self.root.span_id,
                                  sampled=sampled)

    @contextlib.contextmanager
    def child(self, name: str, parent: Optional[Span] = None,
              **attributes: Any) -> Iterator[Span]:
        sp = Span(name, category="serving", parent=parent or self.root,
                  trace_id=self.root.trace_id, attributes=attributes)
        self.spans.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            sp.end()

    def child_at(self, name: str, start_s: float, end_s: float,
                 error: Optional[str] = None, **attributes: Any) -> Span:
        """Backdated phase child from measured boundaries (perf offsets
        from `now_s()`): how the scoring thread attributes one batch's
        pad/dispatch/demux wall to every request it carried."""
        sp = Span(name, category="serving", parent=self.root,
                  trace_id=self.root.trace_id, attributes=attributes)
        sp.start_s = float(start_s)
        sp.start_at = _EPOCH_TIME + sp.start_s
        sp.end_s = max(float(end_s), sp.start_s)
        sp.error = error
        self.spans.append(sp)
        return sp

    def phase_durations(self) -> Dict[str, float]:
        """phase suffix -> seconds, for the ``serving_phase_seconds``
        histograms (span names are ``serving:<phase>``)."""
        out: Dict[str, float] = {}
        for sp in self.spans[1:]:
            phase = sp.name.rsplit(":", 1)[-1]
            out[phase] = out.get(phase, 0.0) + sp.duration_s
        return out

    def finish(self, error: Optional[str] = None) -> Span:
        """Idempotently end the root (phase children were ended by
        their own scopes)."""
        if not self._done:
            self._done = True
            if error:
                self.root.error = error
            self.root.end()
        return self.root


@dataclass
class TracingParams:
    """Knobs for request-scoped tracing + tail sampling (JSON-loadable
    via ``ServingConfig.tracing`` / ``ServingParams.tracing``). On by
    default: the per-request cost is a handful of Span objects and
    clock reads, and the tail sampler keeps the ring bounded at fleet
    QPS."""

    enabled: bool = True
    # tail sampling: always keep error/deadline/shed/fallback traces
    # and anything at or above the rolling `slow_quantile` of request
    # latency; head-sample 1-in-`head_sample_every` of the rest
    slow_quantile: float = 0.95
    head_sample_every: int = 64
    # latency-quantile estimator: rolling sample buffer + the floor of
    # observations before "slow" judgments start (cold = head sampling
    # only, so a warmup burst can't define "slow" forever)
    latency_window: int = 2048
    min_latency_samples: int = 64

    _FIELDS = ("enabled", "slow_quantile", "head_sample_every",
               "latency_window", "min_latency_samples")

    def __post_init__(self):
        if not (0.0 < self.slow_quantile < 1.0):
            raise ValueError(
                f"slow_quantile must be in (0,1): {self.slow_quantile}")
        if self.head_sample_every < 1 or self.latency_window < 1 \
                or self.min_latency_samples < 1:
            raise ValueError("tracing windows/rates must be >= 1")

    @staticmethod
    def from_json(d: Optional[Dict[str, Any]]) -> "TracingParams":
        d = d or {}
        return TracingParams(**{k: d[k] for k in TracingParams._FIELDS
                                if k in d})

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._FIELDS}


class TailSampler:
    """Tail-based sampling decision for finished request traces.

    Head sampling decides at request START and throws the interesting
    traces away with the boring ones; tail sampling decides at the END,
    when the outcome is known: errors, sheds, deadline misses, degraded
    fallbacks and force-sampled contexts are ALWAYS kept, the slowest
    `slow_quantile` tail of latencies is kept, and a deterministic
    1-in-N head sample of the healthy fast majority survives as the
    baseline. Everything else is dropped BEFORE it reaches the process
    span ring, which is what makes always-on tracing affordable at
    fleet QPS. Thread-safe; counters land in the service registry as
    ``serving_trace_kept_total{reason=...}`` /
    ``serving_trace_dropped_total``."""

    def __init__(self, params: Optional[TracingParams] = None,
                 registry=None):
        self.params = params or TracingParams()
        self.registry = registry
        self._lock = threading.Lock()
        self._latencies: deque = deque(maxlen=self.params.latency_window)
        self._seen = 0
        self.kept = 0
        self.dropped = 0

    def _threshold(self) -> Optional[float]:
        vals = sorted(self._latencies)
        if len(vals) < self.params.min_latency_samples:
            return None
        return vals[min(len(vals) - 1,
                        int(self.params.slow_quantile * len(vals)))]

    def decide(self, latency_s: float, error: bool = False,
               forced: bool = False) -> Tuple[bool, str]:
        """(keep, reason) for one finished request. `error` covers every
        non-success outcome (scoring error, shed, deadline, fallback);
        `forced` is a caller-sampled wire context or in-process parent."""
        with self._lock:
            self._latencies.append(float(latency_s))
            self._seen += 1
            if error:
                keep, reason = True, "error"
            elif forced:
                keep, reason = True, "forced"
            else:
                thr = self._threshold()
                if thr is not None and latency_s >= thr:
                    keep, reason = True, "slow"
                elif self._seen % self.params.head_sample_every == 1 \
                        or self.params.head_sample_every == 1:
                    keep, reason = True, "head"
                else:
                    keep, reason = False, "dropped"
            if keep:
                self.kept += 1
            else:
                self.dropped += 1
        if self.registry is not None:
            if keep:
                self.registry.counter(
                    "serving_trace_kept_total",
                    "request traces kept by the tail sampler",
                    reason=reason).inc()
            else:
                self.registry.counter(
                    "serving_trace_dropped_total",
                    "request traces dropped by the tail sampler").inc()
        return keep, reason

    def observe(self, rt: RequestTrace, latency_s: float,
                error: bool = False,
                tracer: Optional[Tracer] = None) -> bool:
        """Finish-side entry point: decide, and on keep admit the
        request's span buffer into the process ring."""
        keep, reason = self.decide(latency_s, error=error,
                                   forced=rt.forced)
        if keep:
            rt.root.set(sampled=reason)
            (tracer or TRACER).collect(rt.spans)
        return keep
