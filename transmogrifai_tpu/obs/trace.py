"""Thread-safe hierarchical span tracer (the unified-timeline half of the
observability layer).

Every subsystem in this codebase already times itself — `RunProfile`
phases, `IngestStats` stage timers, serving latency histograms,
`RetraceMonitor` compile counts — but each island keeps its own clock
and none can be correlated into one timeline. A `Span` is the shared
currency: a named, attributed interval with a parent, so a retry
backoff inside an ingest worker inside a training run renders as ONE
nested tree (exported to Perfetto by `obs/export.py`, rolled into
goodput/badput buckets by `obs/goodput.py`).

Design constraints this module answers:

- **contextvar propagation**: the current span lives in a
  `contextvars.ContextVar`, so nesting works without threading a span
  handle through every call signature. Worker threads (ingest pool,
  serving batcher, selector families) do NOT inherit the caller's
  context — cross-thread parents are passed EXPLICITLY via
  ``tracer.span(..., parent=span)``, which also sets the contextvar in
  the worker for anything it calls (e.g. a `RetryPolicy` backoff span
  opened inside a worker chunk span).
- **two clocks**: span durations come from `time.perf_counter()`
  (monotonic — wall-clock steps must not corrupt durations; satellite
  of the same PR fixes `RunProfile` the same way), while each span also
  carries an epoch `start_at` for humans. Export timestamps derive from
  the perf clock against one process epoch, so they are monotonic and
  non-negative by construction.
- **bounded memory**: finished spans collect in a ring (default 64k);
  a long-lived serving process drops the oldest and counts the drops
  instead of growing without bound.

The tracer is always on: an un-exported span costs one object and two
clock reads, which is noise next to anything worth tracing here (file
IO, XLA dispatch, model fits).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["Span", "Tracer", "TRACER", "get_tracer", "current_span",
           "add_event", "new_run_id"]

# one process epoch for both clocks: export timestamps are
# perf_counter-relative to this origin, mapped onto the epoch origin
_EPOCH_PERF = time.perf_counter()
_EPOCH_TIME = time.time()

_span_ids = itertools.count(1)


def new_run_id() -> str:
    """Run-level correlation id: unique across processes, short enough
    to grep in a JSONL event log."""
    return uuid.uuid4().hex[:12]


class Span:
    """One named interval in a trace tree.

    `attributes` are set at open (`tracer.span(name, key=val)`) or later
    via `set()`; `events` are point-in-time markers inside the span
    (recompiles, journal resumes, injected faults). `end()` is
    idempotent; an un-ended span exports with "now" as its end so a
    live process can still dump a coherent trace.
    """

    __slots__ = ("name", "category", "span_id", "parent_id", "trace_id",
                 "start_s", "end_s", "start_at", "attributes", "events",
                 "thread_id", "thread_name", "error")

    def __init__(self, name: str, category: str = "span",
                 parent: Optional["Span"] = None,
                 trace_id: Optional[str] = None,
                 attributes: Optional[Dict[str, Any]] = None):
        self.name = name
        self.category = category
        self.span_id = next(_span_ids)
        self.parent_id = parent.span_id if parent is not None else None
        self.trace_id = trace_id or (
            parent.trace_id if parent is not None else new_run_id())
        self.start_s = time.perf_counter() - _EPOCH_PERF
        self.end_s: Optional[float] = None
        self.start_at = _EPOCH_TIME + self.start_s
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.events: List[Tuple[str, float, Dict[str, Any]]] = []
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self.error: Optional[str] = None

    # -- mutation ---------------------------------------------------------- #

    def set(self, **attributes: Any) -> "Span":
        self.attributes.update(attributes)
        return self

    def event(self, name: str, **attributes: Any) -> None:
        """Point-in-time marker inside this span (exported as a Perfetto
        instant event)."""
        self.events.append(
            (name, time.perf_counter() - _EPOCH_PERF, dict(attributes)))

    def end(self) -> None:
        if self.end_s is None:
            self.end_s = time.perf_counter() - _EPOCH_PERF

    # -- views ------------------------------------------------------------- #

    @property
    def duration_s(self) -> float:
        end = self.end_s if self.end_s is not None \
            else time.perf_counter() - _EPOCH_PERF
        return max(0.0, end - self.start_s)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name, "category": self.category,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start_at": round(self.start_at, 6),
            "duration_s": round(self.duration_s, 6),
            "thread": self.thread_name,
            "attributes": self.attributes,
            "events": [{"name": n, "offset_s": round(t - self.start_s, 6),
                        **a} for n, t, a in self.events],
            "error": self.error,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, {self.duration_s:.4f}s)")


class Tracer:
    """Process span collector + contextvar-based current-span tracking.

    One global instance (`TRACER`) serves the whole process; tests that
    need isolation construct their own or call `reset()`.
    """

    def __init__(self, max_spans: int = 65536):
        self._lock = threading.Lock()
        self._finished: deque = deque(maxlen=max_spans)
        self._live: Dict[int, Span] = {}
        self.dropped = 0
        # NOTE: a per-Tracer ContextVar would leak on tracer churn;
        # module scope is fine because tests always reset the global.
        self._current: contextvars.ContextVar[Optional[Span]] = \
            contextvars.ContextVar(f"obs_span_{id(self)}", default=None)

    # -- span lifecycle ---------------------------------------------------- #

    @contextlib.contextmanager
    def span(self, name: str, category: str = "span",
             parent: Optional[Span] = None, new_trace: bool = False,
             trace_id: Optional[str] = None,
             **attributes: Any) -> Iterator[Span]:
        """Open a child of `parent` (explicit, for cross-thread nesting)
        or of the calling context's current span. `new_trace=True` roots
        a fresh trace — under `trace_id` when given (the runner passes
        its run correlation id, so the trace, the profile, and the JSONL
        event log all share ONE id), else a fresh one. Exceptions —
        including BaseExceptions like an injected kill — are recorded on
        the span and re-raised."""
        if parent is None and not new_trace:
            parent = self._current.get()
        sp = Span(name, category=category,
                  parent=None if new_trace else parent,
                  trace_id=(trace_id or new_run_id()) if new_trace
                  else trace_id,
                  attributes=attributes)
        with self._lock:
            self._live[sp.span_id] = sp
        token = self._current.set(sp)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            self._current.reset(token)
            sp.end()
            with self._lock:
                self._live.pop(sp.span_id, None)
                if len(self._finished) == self._finished.maxlen:
                    self.dropped += 1
                self._finished.append(sp)

    def current(self) -> Optional[Span]:
        return self._current.get()

    # -- collection views --------------------------------------------------- #

    def spans(self) -> List[Span]:
        """Finished spans, oldest first (live spans excluded)."""
        with self._lock:
            return list(self._finished)

    def trace_spans(self, trace_id: str,
                    include_live: bool = True) -> List[Span]:
        """Every span of one trace (one runner invocation), finished and
        — by default — still-open, sorted by start time."""
        with self._lock:
            out = [s for s in self._finished if s.trace_id == trace_id]
            if include_live:
                out += [s for s in self._live.values()
                        if s.trace_id == trace_id]
        return sorted(out, key=lambda s: (s.start_s, s.span_id))

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._live.clear()
            self.dropped = 0


TRACER = Tracer()


def get_tracer() -> Tracer:
    return TRACER


def current_span() -> Optional[Span]:
    """The calling context's innermost open span on the global tracer."""
    return TRACER.current()


def add_event(name: str, **attributes: Any) -> bool:
    """Attach an instant event to the current span, if any. The no-span
    case is a cheap no-op so library code can emit unconditionally."""
    sp = TRACER.current()
    if sp is None:
        return False
    sp.event(name, **attributes)
    return True
