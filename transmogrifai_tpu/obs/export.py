"""Trace export: Chrome-trace/Perfetto JSON + a JSONL structured event log.

Two complementary sinks for the spans `obs/trace.py` collects:

- `chrome_trace(spans)` renders a span tree as the Chrome Trace Event
  format (the JSON flavor Perfetto and chrome://tracing both load):
  one ``"X"`` complete event per span (ts/dur in microseconds, args
  carrying span/parent/trace ids and attributes), one ``"i"`` instant
  event per span event (recompiles, injected faults, journal resumes),
  plus thread-name metadata so ingest workers, the serving batcher and
  selector family threads label their own rows. `write_chrome_trace`
  dumps it to the path the CLI's ``--trace-out`` names.
- `EventLog` appends one JSON object per line — machine-greppable
  structured events stamped with a run-level correlation id, written
  as they happen (flushed per record) so a killed run's log still ends
  at the kill. Retry attempts, fired fault injections, and journal
  resumes emit through the process-global `emit_event` hook, which is
  a no-op until a log is installed (the runner installs one next to
  the trace output).

`validate_chrome_trace` is the smoke/test gate: structural
well-formedness, non-negative monotonic-clock timestamps, and parented
spans (every parent exists; children start within their parent's
interval, modulo a clock-read epsilon).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Dict, IO, Iterable, List, Optional, Tuple

from transmogrifai_tpu.obs.trace import Span, add_event

__all__ = ["chrome_trace", "merge_chrome_traces", "write_chrome_trace",
           "validate_chrome_trace", "EventLog", "install_event_log",
           "uninstall_event_log", "emit_event", "active_event_log",
           "record_event"]


# -- Chrome trace / Perfetto -------------------------------------------------- #

def _args_jsonable(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = repr(v)
    return out


def chrome_trace(spans: Iterable[Span],
                 process_name: str = "transmogrifai_tpu",
                 pid: int = 0) -> Dict[str, Any]:
    """Render spans as a Chrome Trace Event JSON object.

    Timestamps are the spans' perf-counter offsets from the process
    trace epoch, in integer microseconds — monotonic and non-negative
    regardless of wall-clock steps. Unfinished spans export with "now"
    as their end so a live process can dump a coherent trace.

    `pid` labels the Perfetto process row; multi-process payloads (a
    fleet flight dump merged with another process's trace) concatenate
    each source's ``traceEvents`` under distinct pids plus their own
    ``process_name`` metadata events — `merge_chrome_traces` does the
    concatenation, `validate_chrome_trace` accepts the result.
    """
    spans = list(spans)
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    seen_tids = set()
    for sp in spans:
        if sp.thread_id not in seen_tids:
            seen_tids.add(sp.thread_id)
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": sp.thread_id, "args": {"name": sp.thread_name},
            })
        args = {
            "span_id": sp.span_id, "parent_id": sp.parent_id,
            "trace_id": sp.trace_id,
            **_args_jsonable(sp.attributes),
        }
        if sp.error:
            args["error"] = sp.error
        events.append({
            "ph": "X", "name": sp.name, "cat": sp.category,
            "ts": int(sp.start_s * 1e6),
            "dur": max(1, int(sp.duration_s * 1e6)),
            "pid": pid, "tid": sp.thread_id, "args": args,
        })
        for name, t_s, attrs in sp.events:
            events.append({
                "ph": "i", "name": name, "cat": sp.category,
                "ts": int(t_s * 1e6), "pid": pid, "tid": sp.thread_id,
                "s": "t",
                "args": {"span_id": sp.span_id, **_args_jsonable(attrs)},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def merge_chrome_traces(*traces: Dict[str, Any]) -> Dict[str, Any]:
    """Concatenate chrome_trace payloads from DISTINCT pids into one
    multi-process trace (the fleet flight dump merges the serving
    process's ring with any sidecar payloads this way). Events pass
    through untouched — each source already carries its own pid and
    process_name metadata."""
    events: List[Dict[str, Any]] = []
    for tr in traces:
        events.extend(tr.get("traceEvents") or [])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: Iterable[Span],
                       process_name: str = "transmogrifai_tpu") -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(spans, process_name=process_name), f)
    return path


def validate_chrome_trace(obj: Dict[str, Any]) -> List[str]:
    """Structural validation of a chrome_trace() payload; returns a list
    of problems (empty = valid). Checked: traceEvents shape, required
    keys per phase, non-negative ts / positive dur, span parenting
    (parents exist; a child starts inside its parent's interval), and —
    for multi-process payloads — that every pid carrying spans declares
    a ``process_name`` metadata event.

    Span ids are scoped PER PID: a merged multi-process trace (a fleet
    flight dump beside another process's run trace) may legitimately
    reuse span ids across pids, and a child's parent must live in its
    own process row."""
    problems: List[str] = []
    events = obj.get("traceEvents")
    if not isinstance(events, list) or not events:
        return ["traceEvents missing or empty"]
    # (pid, span_id) -> (ts, ts+dur); parent lookups stay inside the pid
    spans: Dict[Tuple[Any, int], Tuple[int, int]] = {}
    parents: List[Tuple[Any, int, Optional[int]]] = []
    named_pids = set()
    span_pids = set()
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev:
            problems.append(f"event {i}: not an object with 'ph'")
            continue
        ph = ev["ph"]
        if ph == "M":
            if ev.get("name") == "process_name":
                named_pids.add(ev.get("pid"))
            continue
        for key in ("name", "ts", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i} ({ph}): missing {key!r}")
        ts = ev.get("ts")
        if not isinstance(ts, int) or ts < 0:
            problems.append(f"event {i}: ts {ts!r} not a non-negative int")
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, int) or dur <= 0:
                problems.append(f"event {i}: dur {dur!r} not a positive int")
                continue
            sid = ev.get("args", {}).get("span_id")
            if isinstance(sid, int):
                pid = ev.get("pid")
                span_pids.add(pid)
                spans[(pid, sid)] = (ts, ts + dur)
                parents.append((pid, sid, ev["args"].get("parent_id")))
    for pid in sorted(span_pids - named_pids, key=repr):
        problems.append(
            f"pid {pid}: spans present but no process_name metadata")
    for pid, sid, parent_id in parents:
        if parent_id is None:
            continue
        if (pid, parent_id) not in spans:
            problems.append(
                f"span {sid} (pid {pid}): parent {parent_id} not in trace")
            continue
        p0, p1 = spans[(pid, parent_id)]
        c0, _ = spans[(pid, sid)]
        # 1ms grace: parent/child read the clock microseconds apart
        if c0 + 1000 < p0 or c0 > p1 + 1000:
            problems.append(
                f"span {sid} (pid {pid}): starts at {c0}us outside "
                f"parent {parent_id} interval [{p0}, {p1}]us")
    return problems


# -- JSONL structured event log ----------------------------------------------- #

class EventLog:
    """Append-only JSONL event sink with a run correlation id.

    Each record: ``{"ts": epoch, "run_id": ..., "kind": ..., **fields}``.
    Flushed per record so a preempted run's log is complete up to the
    kill; `close()` is idempotent. Thread-safe: retry hooks fire from
    ingest workers and selector family threads concurrently.
    """

    def __init__(self, path: str, run_id: str):
        self.path = path
        self.run_id = run_id
        self._lock = threading.Lock()
        self._fh: Optional[IO[str]] = open(path, "a", encoding="utf-8")

    def emit(self, kind: str, **fields: Any) -> None:
        rec = {"ts": round(time.time(), 6), "run_id": self.run_id,
               "kind": kind, **fields}
        line = json.dumps(rec, default=repr)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


_LOG_LOCK = threading.Lock()
_LOG: Optional[EventLog] = None


def install_event_log(log: EventLog) -> None:
    """Install the process-global event log (one per runner invocation;
    the correlation id lives on the log, not the call sites)."""
    global _LOG
    with _LOG_LOCK:
        _LOG = log


def uninstall_event_log(log: Optional[EventLog] = None) -> None:
    """Remove the active log (if `log` is given, only when it is the one
    installed — a nested scope must not clear an outer log)."""
    global _LOG
    with _LOG_LOCK:
        if log is None or _LOG is log:
            _LOG = None


def active_event_log() -> Optional[EventLog]:
    return _LOG


def emit_event(kind: str, **fields: Any) -> None:
    """Emit a structured event to the installed log; no-op when none is
    installed, so retry/fault/journal paths call it unconditionally."""
    log = _LOG
    if log is not None:
        log.emit(kind, **fields)


def record_event(name: str, **fields: Any) -> None:
    """Record one observability event in BOTH sinks — an instant event
    on the current trace span and a structured JSONL record — with the
    same name and fields, so the Perfetto timeline and the event log
    can never silently diverge. The single call site for every
    retry/fault/oom-redo/journal-resume emission. Events also land in
    the crash flight recorder's ring (obs/flight.py) when one is
    enabled, so a post-mortem dump carries the last N events even when
    no span/log was open."""
    add_event(name, **fields)
    emit_event(name, **fields)
    try:
        from transmogrifai_tpu.obs import flight
        flight.note_event(name, fields)
    except Exception:  # the recorder must never break an emitter
        logging.getLogger(__name__).debug(
            "flight note_event failed", exc_info=True)
