"""obs/ — unified observability: span tracing, trace export, goodput
accounting, and the process-wide metrics registry.

The reference's `OpSparkListener` gave every run one coherent per-phase
metrics story; this package is the port's version of that, grown to
cover what a TPU-first stack actually loses time to (ML Goodput line of
work, PAPERS.md):

- `trace`   — thread-safe hierarchical `Span` tracer with contextvar
              propagation; `RunProfile` phases, per-stage DAG fits,
              ingest workers, sweep blocks, retry backoffs, and serving
              batches all open spans on the global `TRACER`
- `export`  — Chrome-trace/Perfetto JSON exporter (+ validation) and a
              JSONL structured event log with run correlation ids
- `goodput` — `GoodputReport`: spans + events rolled into productive /
              recompile / retry-backoff / ingest-wait / OOM-redo
              buckets that sum to wall time
- `metrics` — Counter/Gauge/Histogram registry (promoted from
              `serving/metrics.py`, whose re-export shim now warns)
              with a process-global `REGISTRY` the serving `/metrics`
              surface exposes alongside each service's own, and
              per-bucket trace-id EXEMPLARS on histograms
- `flight`  — crash flight recorder: a bounded lock-free ring of recent
              span/event/metric records, dumped atomically (Chrome
              trace + JSONL tail) on breaker open / quarantine /
              watchdog restart / SIGTERM / `/debug/dump`
- `slo`     — declarative SLOs (availability/latency/staleness) with
              multi-window multi-burn-rate alerting over the live
              registries: `/slo`, `slo_*` gauges, `slo_alert` events,
              GoodputReport `slo` section
- `smoke`   — `make trace-smoke`: tiny train+score with `--trace-out`,
              validates the Perfetto JSON and the goodput rollup;
              `slo_smoke` (`make slo-smoke`) proves the request-tracing
              / tail-sampling / flight-dump / burn-rate-alert loop
              end to end

Request-scoped tracing lives in `trace` too: W3C ``traceparent``
parse/format, `TraceContext`, the `RequestTrace` span buffer serving
fills per request, and the `TailSampler` that keeps errors + the slow
tail while head-sampling the healthy majority.
"""

from transmogrifai_tpu.obs.export import (  # noqa: F401
    EventLog, chrome_trace, emit_event, install_event_log,
    merge_chrome_traces, uninstall_event_log, validate_chrome_trace,
    write_chrome_trace)
from transmogrifai_tpu.obs.flight import (  # noqa: F401
    FlightRecorder, get_recorder)
from transmogrifai_tpu.obs.goodput import (  # noqa: F401
    GoodputReport, build_report)
from transmogrifai_tpu.obs.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY, get_registry)
from transmogrifai_tpu.obs.slo import (  # noqa: F401
    SLO, SLOEngine, SLOParams)
from transmogrifai_tpu.obs.trace import (  # noqa: F401
    RequestTrace, Span, TRACER, TailSampler, TraceContext, Tracer,
    TracingParams, add_event, current_span, format_traceparent,
    get_tracer, new_run_id, parse_traceparent)

__all__ = [
    "Span", "Tracer", "TRACER", "add_event", "current_span", "get_tracer",
    "new_run_id",
    "RequestTrace", "TraceContext", "TracingParams", "TailSampler",
    "parse_traceparent", "format_traceparent",
    "EventLog", "chrome_trace", "merge_chrome_traces", "emit_event",
    "install_event_log", "uninstall_event_log", "validate_chrome_trace",
    "write_chrome_trace",
    "GoodputReport", "build_report",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "get_registry",
    "FlightRecorder", "get_recorder",
    "SLO", "SLOEngine", "SLOParams",
]
