"""obs/ — unified observability: span tracing, trace export, goodput
accounting, and the process-wide metrics registry.

The reference's `OpSparkListener` gave every run one coherent per-phase
metrics story; this package is the port's version of that, grown to
cover what a TPU-first stack actually loses time to (ML Goodput line of
work, PAPERS.md):

- `trace`   — thread-safe hierarchical `Span` tracer with contextvar
              propagation; `RunProfile` phases, per-stage DAG fits,
              ingest workers, sweep blocks, retry backoffs, and serving
              batches all open spans on the global `TRACER`
- `export`  — Chrome-trace/Perfetto JSON exporter (+ validation) and a
              JSONL structured event log with run correlation ids
- `goodput` — `GoodputReport`: spans + events rolled into productive /
              recompile / retry-backoff / ingest-wait / OOM-redo
              buckets that sum to wall time
- `metrics` — Counter/Gauge/Histogram registry (promoted from
              `serving/metrics.py`, which re-exports) with a
              process-global `REGISTRY` the serving `/metrics` surface
              exposes alongside each service's own
- `smoke`   — `make trace-smoke`: tiny train+score with `--trace-out`,
              validates the Perfetto JSON and the goodput rollup
"""

from transmogrifai_tpu.obs.export import (  # noqa: F401
    EventLog, chrome_trace, emit_event, install_event_log,
    uninstall_event_log, validate_chrome_trace, write_chrome_trace)
from transmogrifai_tpu.obs.goodput import (  # noqa: F401
    GoodputReport, build_report)
from transmogrifai_tpu.obs.metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY, get_registry)
from transmogrifai_tpu.obs.trace import (  # noqa: F401
    Span, TRACER, Tracer, add_event, current_span, get_tracer, new_run_id)

__all__ = [
    "Span", "Tracer", "TRACER", "add_event", "current_span", "get_tracer",
    "new_run_id",
    "EventLog", "chrome_trace", "emit_event", "install_event_log",
    "uninstall_event_log", "validate_chrome_trace", "write_chrome_trace",
    "GoodputReport", "build_report",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "get_registry",
]
