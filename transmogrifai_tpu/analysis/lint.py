"""AST-based JAX-pitfall linter over stage/kernel source.

Where `analysis/opcheck.py` validates a WIRED graph, this pass reads the
source of the stages themselves for the pitfalls that only show up as
silent slowness or nondeterminism once XLA is in the loop:

- ``L001 numpy-in-device``: ``np.``/``numpy.`` use inside a jittable
  stage's ``device_apply``/``device_apply_with`` body. Host numpy inside
  a traced function either breaks the trace or (worse) silently constant-
  folds per compile. Whitelisted: pure constants and dtype names
  (``np.inf``, ``np.pi``, ``np.float32``, ...).
- ``L002 traced-branch``: Python ``if``/``while`` (or ternary) testing a
  traced value inside a device body — a branch on the ``dev``/``enc``
  parameters or a value subscripted out of them. Under ``jax.jit`` this
  raises a ConcretizationTypeError or, with weak typing, silently bakes
  one branch into the compiled program. Testing the *container* itself
  (``if enc:``) is static and allowed.
- ``L003 unhashable-static``: a parameter listed in ``static_argnames``
  whose default value is a mutable literal (list/dict/set) — unhashable
  statics fail at call time, and mutable defaults silently share state
  between traces.
- ``L004 nondeterminism-in-fit``: wall-clock or global-RNG calls
  (``time.time``, ``datetime.now``, ``np.random.rand``, seedless
  ``default_rng()``, ``random.random``, ``uuid.uuid4``) inside ``fit``/
  ``fit_model``/``device_apply`` bodies. Fits must be replayable from
  the FitContext seed.
- ``L005 host-prepare-device-input``: ``host_prepare`` subscripting an
  input column whose declared ``in_types`` kind is device
  (scalar/vector/prediction) — the compiled scorer passes None for
  device-kind columns on the host phase, so that read crashes or
  silently degrades (the contract documented in stages/base.py).
- ``L006 fixed-batch-dim``: a ``reshape``/``broadcast_to`` inside a
  device body whose LEADING target dim is an int literal > 1. The
  serving batcher pads batches to a LADDER of bucket sizes and the
  streaming tail re-pads to the warm shape, so device code that bakes a
  specific leading batch dim into a shape is wrong the moment a
  different bucket arrives — derive it from ``x.shape[0]`` (or use
  ``-1``) instead.
- ``L007 serial-ingest``: a per-iteration ``jnp.asarray``/``jnp.array``/
  ``jax.device_put`` inside a Python ``for`` loop that iterates a chunk
  stream (an ``iter_chunks(...)``/``stream(...)`` call, or a plain
  ``chunks``/``batches`` iterable). One synchronous host→device
  transfer per loop body serializes host prep against the wire — the
  r5 bench burned 63% of its big-mode budget in exactly this pattern —
  and an un-depth-bounded ``device_put`` loop also lets dispatch run
  arbitrarily far ahead of real transfer, breaking deadline math.
  Route bulk uploads through ``data/pipeline.run_chunk_pipeline``
  (worker prepare + bounded-depth overlapped writes) instead.
- ``L008 unbounded-fault-handling``: the two anti-patterns the
  ``runtime/`` fault-tolerance layer replaces. (a) a broad swallow —
  bare ``except:`` / ``except Exception:`` whose body is ONLY
  ``pass``/``continue``/``...`` — hides the failure entirely: either
  narrow the exception type, handle it (even a ``log.debug`` with
  ``exc_info`` counts: the failure stays observable), or let it
  propagate into a ``runtime.retry.RetryPolicy``. (b) an unbounded
  ``while True`` retry loop — a handler inside the loop that neither
  re-raises, ``break``s, nor ``return``s, so a PERSISTENT error spins
  forever; bound it with ``RetryPolicy`` (attempts + backoff +
  transient classification) instead.

- ``L009 wallclock-duration``: subtraction arithmetic on a
  ``time.time()`` call — the wall-clock-for-durations bug. An NTP step
  or suspend/resume silently corrupts any interval measured as
  ``time.time() - t0`` (negative phase timings, goodput buckets that
  exceed wall time); use ``time.perf_counter()`` (or
  ``time.monotonic()`` for deadlines). Bare ``time.time()`` reads
  stay legal: an epoch TIMESTAMP (``started_at``, log stamps) is what
  the wall clock is for.

- ``L010 uncached-rebuild``: two or more device-matrix builder calls
  (``device_matrix`` / ``device_binned`` / ``dual_device_matrices``)
  on the SAME store variable inside one function scope with none of
  them carrying a ``cache=`` policy. Each uncached call re-streams the
  whole store host→device — at 10M×500 that is ~635 s per repeat
  (BENCH_r05) — while the content-addressed feature cache
  (`data/feature_cache.py`) replays the wire artifact with zero store
  reads. Pass ``cache=`` (a policy string or `FeatureCacheParams`) so
  the rebuild is a deliberate choice, not an accident.

- ``L011 per-device-dispatch``: the two host-in-the-loop multichip
  anti-patterns. (a) a Python ``for`` loop over the device list
  (``jax.devices()`` / ``jax.local_devices()`` / a ``devices``
  iterable) doing per-device ``device_put``/``jnp.asarray`` — one
  synchronous transfer per chip serializes what a single
  ``device_put(x, NamedSharding(mesh, spec))`` ships as one sharded
  placement (and the scheduler in `parallel/scheduler.py` exists so
  per-worker placement happens once per lane, not per dispatch).
  (b) a host callback (``jax.pure_callback`` / ``io_callback`` /
  ``jax.debug.callback`` / ``host_callback.call``) inside a function
  wrapped by ``shard_map``/``pjit`` — every shard's execution stalls
  on a host round-trip per step, turning an SPMD program into a
  host-bound serial one; move the host work outside the mapped
  computation (or into the scheduler's host-side worker loop).

- ``L012 legacy-global-rng``: any call through numpy's module-level
  legacy RNG surface (``np.random.rand`` / ``randn`` / ``normal`` /
  ``seed`` / ``shuffle`` / ...), or a seedless
  ``np.random.default_rng()``, ANYWHERE outside ``testkit/`` — not just
  inside fit bodies (that narrower case is L004). The module-level
  functions share ONE hidden global ``RandomState``: any import-order
  or thread-interleaving change silently reorders every draw, so drift
  sampling, refit shuffling, and journal-resumed continual cycles stop
  replaying deterministically. Use a seeded
  ``np.random.default_rng(seed)`` ``Generator`` instead (`testkit/` is
  exempt: test fixtures own their processes).

- ``L013 magic-knob``: a NEW module-level hand-set tuning knob — an
  ALL-CAPS constant whose name says it tunes throughput
  (``WORKERS``/``DEPTH``/``QUEUE``/``BATCH``/``WAIT``/``TIMEOUT``/
  ``BUDGET``/``TARGET``/``RETRIES``/``WIDTH``/``CHUNK``/``THREADS``)
  assigned a bare numeric literal in a ``data/``/``parallel/``/
  ``serving/`` hot path. The learned cost model (`perf/`) exists so
  these decisions come from measurements through the params/env
  plumbing; a fresh ``WORKERS = 4`` bypasses both and fossilizes one
  machine's guess. The documented env-tunable sites that predate the
  model are allowlisted (`_L013_ALLOW`); everything new must route
  through `PerfModelParams`/`OpParams`/an env knob instead.

- ``L014 per-request-service``: a ``ScoringService``/``FleetService``
  (or ``.from_path``) constructed inside a LOOP body or an HTTP
  request-handler method (``do_GET``/``do_POST``/``handle*``).
  Constructing a service is the expensive path by design — model load,
  compiled-scorer build, AOT warmup of every bucket, shared-program
  registration — so a per-request or per-iteration construction defeats
  the warmup AND the fleet's shared-program registry (every instance
  re-traces its own programs instead of adopting the resident ones).
  Construct once, `start()`, and route requests through it.

- ``L015 unnamed-thread``: a ``threading.Thread(...)`` constructed in
  package code (outside ``testkit/``/tests) without a ``name=``. The
  serving watchdog, hang diagnostics, and span attribution all key off
  thread names — an anonymous ``Thread-23`` in a stack dump or trace
  is unattributable exactly when a wedged scoring loop or supervisor
  needs diagnosing. Name every long-lived OR short-lived thread for
  what it does (``scoring-batcher-1``, ``fleet-watchdog``,
  ``continual-loop``).

- ``L016 closure-constant-array``: a ``device_apply``/``predict_arrays``
  body converting ``self.<attr>`` to a device array
  (``jnp.asarray(self.W)``) in a class WITHOUT ``device_constants()``.
  The converted array is a closure constant of the compiled scoring
  program: megabyte-scale fitted state gets value-baked into the XLA
  executable (every fleet tenant then compiles its own bucket programs
  instead of sharing one) and re-staged host→device on every dispatch
  through the serving tunnel. Route fitted arrays through
  ``device_constants()``/``device_apply_with`` — the known-small
  scalar/index sites are allowlisted in ``_L016_ALLOW``.

- ``L017 dynamic-event-name``: a span/event NAME built with an f-string
  or ``+`` concatenation at a tracing call site (``record_event`` /
  ``emit_event`` / ``add_event`` / ``.span(...)`` / ``.event(...)`` /
  ``.child(...)``). Event and span names are CARDINALITY keys: the
  flight-recorder ring, the goodput rollup's by-name buckets, and any
  Prometheus series derived from them all assume a small closed name
  set — a name interpolating a request id, tenant, or path mints
  unbounded distinct names and quietly breaks all three. Put the
  variable part in an ATTRIBUTE (``record_event("cache_hit",
  key=key)``), not the name. Bounded-by-construction dynamic names
  (worker lanes, run types, site labels) are allowlisted by their
  literal prefix in ``_L017_ALLOW_PREFIXES``.

- ``L018 per-row-serving-loop``: a Python ``for`` statement iterating a
  rows-shaped iterable (``rows`` / ``*_rows``) inside a serving
  hot-path function (name containing ``score``/``assemble``/``demux``/
  ``parse`` in a ``serving/`` module). The compiled row codec
  (`data/rowcodec.py`, allowlisted) exists precisely so the serving
  data plane never pays per-row Python — a fresh ``for r in rows:``
  dict loop on the request path reintroduces the parse cost PR 15
  removed (the pre-codec loop dominated the serving p50). Route rows
  through ``rowcodec.encode_rows``/``Dataset.from_rows`` (codec-backed)
  or operate on columns.

- ``L019 blocking-under-lock``: ``time.sleep`` or blocking file I/O
  (``open`` / ``os.makedirs`` / ``os.replace`` / ``os.fsync`` /
  ``Path.write_text``-family / ``json.dump`` / ``pickle.dump``)
  lexically inside a ``with <lock>:`` block. A lock's critical section
  prices every contender: one slow disk under ``self._lock`` stalls
  every thread that touches that lock — the serving watchdog reads this
  as a stall and restarts a healthy worker. Stage the data under the
  lock, do the I/O after release (see
  ``serving/resilience.py:_flush_flight_dumps`` for the pattern).
  Deliberately serialized writers (WAL appends, append-only logs) annotate
  the site ``# conc-ok: C003`` / ``# conc-ok: L019`` — the same escape
  hatch the whole-program auditor (``analysis/concurrency.py``, which
  also sees lock-holding CALLERS of the I/O) honors, so one annotation
  satisfies both tools. Smoke/chaos drivers and tests are allowlisted.

- ``L020 store-bypass-write``: a direct write (``open(..., "w")`` /
  ``np.save``/``np.savez`` / ``Path.write_text``-family) whose path
  expression is built from an artifact-store location (a call to
  ``path_of``/``default_cache_dir``/``cache_root``/``resolve_dir``/
  ``resolved_dir``/``resolved_corpus_dir``, or a ``cache_dir``/
  ``store_dir``/``artifact_dir`` variable). Artifacts in those
  namespaces carry sha256 manifests written LAST by
  ``store.ArtifactStore.put``/``seal_and_commit`` — a bypass write
  either lands an unverifiable file (readers reject the artifact) or
  mutates a sealed one (checksum mismatch on next load). Stage files
  and commit through the store; deliberate sidecars (access clocks,
  append-only shard logs, the store internals themselves) annotate the
  site ``# store-ok: <why>``. ``store/artifact.py``, smoke drivers and
  tests are allowlisted.

- ``L021 blind-poll-loop``: a constant-argument ``time.sleep`` lexically
  inside a ``while`` loop. A fixed-delay poll is wrong at both ends of
  the distribution — too fast, and K replicas hammering one shared cell
  (a ``store.state`` lease table, a journal dir, a readiness file) turn
  the store into a CAS storm that scales with fleet size; too slow, and
  a cross-host handoff (lease expiry, barrier release) eats the full
  period as idle wall time. Poll loops must derive their delay from the
  thing they wait on — a TTL/deadline (``min(next_expiry, ttl)``, the
  scheduler's ``_pod_takeover``), capped exponential backoff
  (``StateCell.update``), or an ``Event.wait(timeout=...)``/
  ``Condition.wait(timeout=...)`` that a writer can wake early.
  Computed delays pass by construction (only literal constants flag);
  a deliberate fixed-cadence loop annotates the site
  ``# conc-ok: L021``. Smoke/chaos drivers and tests are allowlisted.

- ``L022 unlogged-actuation``: a call to a serving actuation API
  (``rebucket``/``rearm_auto_rebucket``/``set_pressure``/
  ``set_fidelity_route``/``set_route_override``) outside the autopilot
  controller from a function that never emits a flight-recorder event
  (no ``record_event``/``request_dump`` call in scope). The serving
  control loop's audit trail is the flight recorder: every route flip,
  admission-threshold write, or ladder re-derivation must name the
  burn window/prediction (or the operator action) that justified it,
  or a post-incident dump cannot explain why traffic moved. Emit an
  event beside the call, or annotate a deliberate silent site with
  ``# autopilot-ok: <why>``. ``serving/autopilot.py``, smoke/chaos
  drivers and tests are allowlisted.

- ``L023 dropped-trace-context``: a span-opening or event-emission call
  (``TRACER.span``/``Span``/``RequestTrace``/``record_event``/
  ``emit_event``/``add_event``) in ``serving/``/``parallel/``/
  ``continual/`` that passes a MANUAL trace id — a string literal,
  f-string, concatenation, or a fresh ``new_run_id()``/
  ``new_trace_id()``/``uuid*`` — instead of joining the ambient
  contextvar parent. A hand-built trace id severs the cross-process
  stitch: the span lands in the trace shard under an id
  ``merge_fleet_trace`` will never be asked for, and the request's
  remote leg goes missing from the merged timeline. Join the current
  trace (omit ``trace_id``; pass a ``TraceContext``/parent span;
  ``new_trace=True`` roots deliberately), or annotate
  ``# trace-ok: <why>``. Smoke/chaos drivers and tests are
  allowlisted.

Classes that set ``jittable = False`` in their body are exempt from
L001/L002 (their device_apply runs eagerly on host, where numpy and
Python control flow are legal).

Run: ``python -m transmogrifai_tpu.lint <paths...>`` — exit 1 on GATING
findings (error-severity, unsuppressed); files that fail to parse are
reported as L000 warnings and do not gate. ``--json`` emits the same
envelope ``analysis/concurrency.py`` uses (file/line/rule/severity/
suppression). Also available via the ``lint`` subcommand of
``transmogrifai_tpu.cli``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

_DEVICE_FNS = ("device_apply", "device_apply_with")
_FIT_FNS = ("fit", "fit_model", "fit_arrays") + _DEVICE_FNS

_NP_CONST_WHITELIST = {
    "pi", "e", "inf", "nan", "newaxis", "euler_gamma",
    "float16", "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "bfloat16",
    "finfo", "iinfo",
}

# exact dotted names only: `random.x` must not match jax.random.x /
# np.random.x (keyed jax RNG is deterministic; np.random handled apart)
_NONDET_EXACT = {
    "random.random", "random.randint", "random.choice", "random.shuffle",
    "random.uniform", "random.randrange", "random.sample",
    "uuid.uuid4", "uuid.uuid1",
}
# suffix-matched (module aliases like `dt.datetime.now` still resolve)
_NONDET_SUFFIX = {
    "time.time", "time.time_ns",
    "datetime.now", "datetime.utcnow", "datetime.today", "date.today",
}
_NONDET_NP_RANDOM = {
    "rand", "randn", "randint", "random", "choice", "shuffle",
    "permutation", "uniform", "normal", "random_sample",
}

# L012: the full module-level legacy-RNG surface (shared hidden global
# RandomState) — everything L004 flags plus state management and the
# distribution samplers continual refit/drift code reaches for
_LEGACY_NP_RANDOM = _NONDET_NP_RANDOM | {
    "seed", "get_state", "set_state", "standard_normal", "sample",
    "exponential", "poisson", "beta", "gamma", "binomial", "multinomial",
    "bytes", "lognormal", "geometric",
}


def _rng_seedless(call: ast.Call) -> bool:
    """True when a `default_rng(...)` call visibly seeds from OS
    entropy: no args at all, or a LITERAL None seed (positional or
    `seed=None` — both are spelled-out nondeterminism). A `**kwargs`
    splat is statically unknowable and given the benefit of the
    doubt."""
    if call.args:
        a = call.args[0]
        return isinstance(a, ast.Constant) and a.value is None
    for kw in call.keywords:
        if kw.arg is None:      # **splat: unknowable, trusted
            return False
        if kw.arg == "seed":
            return isinstance(kw.value, ast.Constant) and \
                kw.value.value is None
    return True

_DEVICE_KINDS = ("scalar", "vector", "prediction")

# L007: chunk-stream iterators (call names / bare iterable names) and the
# per-iteration host→device transfer calls that serialize against them
_INGEST_ITER_CALLS = {"iter_chunks", "stream"}
_INGEST_ITER_NAMES = {"chunks", "batches"}
_SERIAL_UPLOAD_CALLS = {"jnp.asarray", "jnp.array", "jax.numpy.asarray",
                        "jax.numpy.array", "jax.device_put", "device_put"}

# L010: the out-of-core device-matrix builders the feature cache fronts
_MATRIX_BUILDER_CALLS = {"device_matrix", "device_binned",
                         "dual_device_matrices"}

# L011: device-list iterables (calls or bare names) and SPMD wrappers
_DEVICE_ITER_CALLS = {"devices", "local_devices"}
_DEVICE_ITER_NAMES = {"devices", "local_devices", "mesh_devices"}
_SPMD_WRAPPERS = {"shard_map", "pjit"}
# exact-suffix host-callback forms (a bare `.callback` method must not
# false-positive, so `callback` only matches under the jax.debug module)
_HOST_CALLBACK_LAST = {"pure_callback", "io_callback"}
_HOST_CALLBACK_DOTTED_SUFFIX = ("debug.callback", "host_callback.call")


@dataclass
class LintFinding:
    path: str
    line: int
    code: str
    message: str
    # "error" findings gate CI; "warning" (parse-skipped files) are
    # reported but never fail the run. `suppression` names the mechanism
    # ("annotation") when an escape hatch silenced an error finding.
    severity: str = "error"
    suppression: Optional[str] = None

    @property
    def gating(self) -> bool:
        return self.severity == "error" and self.suppression is None

    def __str__(self) -> str:
        s = f"{self.path}:{self.line}: {self.code} {self.message}"
        if self.suppression is not None:
            s += f" [suppressed: {self.suppression}]"
        elif self.severity != "error":
            s += f" [{self.severity}]"
        return s


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_mutable_literal(node: ast.AST) -> bool:
    return isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp))


def _own_jittable(cls: ast.ClassDef) -> Optional[bool]:
    """The class body's own `jittable = ...` value (Assign or AnnAssign),
    or None when it doesn't set one."""
    for stmt in cls.body:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == "jittable":
                if isinstance(value, ast.Constant) and \
                        isinstance(value.value, bool):
                    return value.value
                return None  # computed value: assume nothing
    return None


def _class_is_host(cls: ast.ClassDef,
                   classes: Optional[Dict[str, ast.ClassDef]] = None,
                   _seen: Tuple[str, ...] = ()) -> bool:
    """True when the stage is host-path: its body sets jittable=False, it
    subclasses HostTransformer, or a same-module base is itself host. An
    explicit jittable=True in the body overrides any inherited host-ness."""
    own = _own_jittable(cls)
    if own is not None:
        return own is False
    for base in cls.bases:
        dotted = _dotted(base)
        if dotted is None:
            continue
        last = dotted.rsplit(".", 1)[-1]
        if last == "HostTransformer":
            return True
        if classes is not None and last in classes and last not in _seen:
            if _class_is_host(classes[last], classes, _seen + (last,)):
                return True
    return False


def _class_in_types(cls: ast.ClassDef) -> Optional[List[Optional[str]]]:
    """Type NAMES from an `in_types = (T.X, T.Y)` class-body assignment;
    Ellipsis entries become '...'. None when undeclared/opaque."""
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "in_types":
                    v = stmt.value
                    if not isinstance(v, (ast.Tuple, ast.List)):
                        return None
                    out: List[Optional[str]] = []
                    for e in v.elts:
                        # the repo convention spells variadic as the NAME
                        # `Ellipsis` (parsed as ast.Name), literal `...`
                        # parses as a Constant — both mean variadic
                        if (isinstance(e, ast.Constant)
                                and e.value is Ellipsis) or \
                                (isinstance(e, ast.Name)
                                 and e.id == "Ellipsis"):
                            out.append("...")
                        else:
                            d = _dotted(e)
                            out.append(d.rsplit(".", 1)[-1] if d else None)
                    return out
    return None


def _kind_of_type_name(name: Optional[str]) -> Optional[str]:
    if name in (None, "..."):
        return None
    try:
        from transmogrifai_tpu import types as T
        from transmogrifai_tpu.data.columns import kind_of
        return kind_of(T.feature_type_by_name(name))
    except Exception:
        return None


def _static_argnames(fn: ast.FunctionDef) -> Set[str]:
    """static_argnames/static_argnums declared by jit decorators on `fn`."""
    names: Set[str] = set()
    params = [a.arg for a in fn.args.args]
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        target = _dotted(dec.func)
        calls = [dec]
        # @partial(jax.jit, static_argnames=...) nests the jit reference
        if target in ("partial", "functools.partial") and dec.args:
            inner = _dotted(dec.args[0])
            if inner not in ("jax.jit", "jit"):
                continue
        elif target not in ("jax.jit", "jit"):
            continue
        for call in calls:
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    for e in ast.walk(kw.value):
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            names.add(e.value)
                if kw.arg == "static_argnums":
                    for e in ast.walk(kw.value):
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, int) and \
                                0 <= e.value < len(params):
                            names.add(params[e.value])
    return names


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
        if d in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call) and d in ("partial",
                                               "functools.partial"):
            if dec.args and _dotted(dec.args[0]) in ("jax.jit", "jit"):
                return True
    return False


class _FileLinter(ast.NodeVisitor):
    def __init__(self, path: str,
                 classes: Optional[Dict[str, ast.ClassDef]] = None):
        self.path = path
        self.findings: List[LintFinding] = []
        self._class_stack: List[ast.ClassDef] = []
        self._classes = classes or {}  # module classes, for base resolution
        self._time_aliases = {"time"}  # `import time as _time` et al.

    def _emit(self, node: ast.AST, code: str, message: str) -> None:
        self.findings.append(LintFinding(
            self.path, getattr(node, "lineno", 0), code, message))

    # -- structure ------------------------------------------------------- #

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        cls = self._class_stack[-1] if self._class_stack else None
        host_class = cls is not None and _class_is_host(cls, self._classes)
        in_method = cls is not None
        if node.name in _DEVICE_FNS and in_method and not host_class:
            self._check_device_body(node)
        if node.name in _FIT_FNS and in_method:
            self._check_nondeterminism(node)
        if node.name == "host_prepare" and in_method and cls is not None \
                and not host_class:
            # host-path stages (jittable=False) always see materialized
            # columns — the None contract only binds device stages
            self._check_host_prepare(node, cls)
        statics = _static_argnames(node)
        if statics:
            self._check_static_defaults(node, statics)
        if _jit_decorated(node):
            self._check_traced_branches(
                node, traced_params={a.arg for a in node.args.args}
                - statics - {"self"})
        self._check_uncached_rebuild(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_For(self, node: ast.For) -> None:
        self._check_serial_ingest(node)
        self._check_per_device_loop(node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._check_swallowed_exception(node)
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        self._check_wallclock_duration(node)
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "time":
                self._time_aliases.add(alias.asname or "time")
        self.generic_visit(node)

    # -- L009 -------------------------------------------------------------- #

    def _is_walltime_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        dotted = _dotted(node.func)
        if dotted is None:
            return False
        # exact module call through any recorded alias of the `time`
        # module — NOT arbitrary `.time()` methods (datetime.time etc.
        # must not false-positive)
        parts = dotted.rsplit(".", 1)
        return (len(parts) == 2 and parts[0] in self._time_aliases
                and parts[1] in ("time", "time_ns")) or \
            dotted.endswith(".time.time")

    def _check_wallclock_duration(self, node: ast.BinOp) -> None:
        """Subtraction involving a `time.time()` call measures a
        DURATION on the wall clock: a clock step corrupts it. Timestamps
        (bare reads) are fine; interval math belongs on
        `time.perf_counter()`."""
        if not isinstance(node.op, ast.Sub):
            return
        if self._is_walltime_call(node.left) or \
                self._is_walltime_call(node.right):
            self._emit(
                node, "L009",
                "`time.time()` subtraction measures a duration on the "
                "wall clock — an NTP step/suspend corrupts it; use "
                "time.perf_counter() for intervals (keep time.time() "
                "for epoch timestamps only)")

    def visit_While(self, node: ast.While) -> None:
        self._check_unbounded_retry(node)
        self.generic_visit(node)

    # -- L008 -------------------------------------------------------------- #

    @staticmethod
    def _handler_is_broad(node: ast.ExceptHandler) -> bool:
        """bare `except:` or a clause catching Exception/BaseException."""
        if node.type is None:
            return True
        types = (node.type.elts if isinstance(node.type, ast.Tuple)
                 else [node.type])
        for t in types:
            dotted = _dotted(t)
            if dotted and dotted.rsplit(".", 1)[-1] in (
                    "Exception", "BaseException"):
                return True
        return False

    @staticmethod
    def _body_swallows(body: List[ast.stmt]) -> bool:
        """True when the handler body is ONLY pass/continue/`...`."""
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue)):
                continue
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Constant) and \
                    stmt.value.value is Ellipsis:
                continue
            return False
        return bool(body)

    def _check_swallowed_exception(self, node: ast.ExceptHandler) -> None:
        if self._handler_is_broad(node) and self._body_swallows(node.body):
            self._emit(
                node, "L008",
                "broad exception swallow (`except Exception: pass`) — the "
                "failure vanishes silently; narrow the type, record it "
                "(log with exc_info), or route the call through "
                "runtime.retry.RetryPolicy")

    @staticmethod
    def _handler_exits(handler: ast.ExceptHandler) -> bool:
        """Does the handler body (own scope only) raise/break/return?"""
        stack: List[ast.AST] = list(handler.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            if isinstance(sub, (ast.Raise, ast.Break, ast.Return)):
                return True
            stack.extend(ast.iter_child_nodes(sub))
        return False

    def _check_unbounded_retry(self, node: ast.While) -> None:
        """`while True:` containing a handler that never exits the loop:
        a persistent error retries forever with no attempt bound."""
        if not (isinstance(node.test, ast.Constant)
                and node.test.value is True):
            return
        stack: List[ast.AST] = list(node.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue  # nested scopes run on their own terms
            if isinstance(sub, ast.ExceptHandler):
                if not self._handler_exits(sub):
                    self._emit(
                        sub, "L008",
                        "unbounded `while True` retry: this handler "
                        "neither re-raises, breaks, nor returns, so a "
                        "persistent error loops forever — bound it with "
                        "runtime.retry.RetryPolicy (attempts + backoff + "
                        "transient classification)")
                continue  # handler internals already judged
            stack.extend(ast.iter_child_nodes(sub))

    # -- L010 -------------------------------------------------------------- #

    def _check_uncached_rebuild(self, fn: ast.FunctionDef) -> None:
        """Repeated device-matrix builds from the same store variable in
        one scope with no `cache=` policy on any of them: each repeat
        re-streams the whole store host→device when the feature cache
        would replay the built wire tape instead."""
        groups: Dict[str, List[Tuple[ast.Call, bool]]] = {}
        # own scope only: nested defs get their own visit (and their own
        # store bindings), so walking into them would double-report
        stack: List[ast.AST] = list(fn.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted(sub.func)
            if dotted is None or \
                    dotted.rsplit(".", 1)[-1] not in _MATRIX_BUILDER_CALLS:
                continue
            if not sub.args:
                continue
            store = _dotted(sub.args[0])
            if store is None:
                continue
            cached = any(kw.arg == "cache" for kw in sub.keywords)
            groups.setdefault(store, []).append((sub, cached))
        for store, calls in groups.items():
            uncached = [c for c, cached in sorted(
                calls, key=lambda p: p[0].lineno) if not cached]
            if len(uncached) < 2:
                continue
            for call in uncached[1:]:
                self._emit(
                    call, "L010",
                    f"repeated device-matrix build from `{store}` in "
                    f"`{fn.name}` with no cache= policy — every call "
                    "re-streams the whole store host→device; pass "
                    "cache= (policy string or FeatureCacheParams) so "
                    "repeats replay the data/feature_cache.py wire "
                    "artifact instead of re-uploading")

    # -- L011 (a): per-device upload loops ---------------------------------- #

    @staticmethod
    def _is_device_iter(it: ast.AST) -> bool:
        # unwrap enumerate(...) — `for i, d in enumerate(devices)`
        if isinstance(it, ast.Call) and _dotted(it.func) == "enumerate" \
                and it.args:
            it = it.args[0]
        if isinstance(it, ast.Call):
            dotted = _dotted(it.func)
            return dotted is not None and \
                dotted.rsplit(".", 1)[-1] in _DEVICE_ITER_CALLS
        dotted = _dotted(it)
        return dotted is not None and \
            dotted.rsplit(".", 1)[-1] in _DEVICE_ITER_NAMES

    def _check_per_device_loop(self, node: ast.For) -> None:
        """Per-device Python loops doing host→device transfers: N
        synchronous RPCs where one sharded `device_put` ships a single
        placement over the whole mesh."""
        if not self._is_device_iter(node.iter):
            return
        stack: List[ast.AST] = list(node.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, ast.For) and self._is_device_iter(sub.iter):
                continue  # nested device loops report on their own visit
            stack.extend(ast.iter_child_nodes(sub))
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted(sub.func)
            if dotted in _SERIAL_UPLOAD_CALLS:
                self._emit(
                    sub, "L011",
                    f"per-device `{dotted}` inside a loop over the "
                    "device list — one synchronous transfer per chip "
                    "serializes placement; ship it as ONE "
                    "`device_put(x, NamedSharding(mesh, spec))` (or let "
                    "parallel/scheduler.py place per worker lane, once)")

    # -- L007 -------------------------------------------------------------- #

    @staticmethod
    def _is_ingest_iter(it: ast.AST) -> bool:
        if isinstance(it, ast.Call):
            dotted = _dotted(it.func)
            return dotted is not None and \
                dotted.rsplit(".", 1)[-1] in _INGEST_ITER_CALLS
        return isinstance(it, ast.Name) and it.id in _INGEST_ITER_NAMES

    def _check_serial_ingest(self, node: ast.For) -> None:
        """Per-iteration host→device transfers inside a chunk-stream
        loop: the serial-ingest anti-pattern `data/pipeline.py` exists
        to replace."""
        if not self._is_ingest_iter(node.iter):
            return
        # skip NESTED chunk-stream loops: visit_For reaches them too,
        # and walking into their bodies here would report each transfer
        # twice
        stack: List[ast.AST] = list(node.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, ast.For) and self._is_ingest_iter(sub.iter):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted(sub.func)
            if dotted in _SERIAL_UPLOAD_CALLS:
                self._emit(
                    sub, "L007",
                    f"per-iteration `{dotted}` inside a chunk-stream "
                    "`for` loop — one synchronous (or un-depth-"
                    "bounded) host→device transfer per chunk "
                    "serializes host prep against the wire; route "
                    "the upload through data/pipeline."
                    "run_chunk_pipeline (bounded-depth overlapped "
                    "writes) instead")

    # -- L001 + L002 over device bodies ----------------------------------- #

    def _check_device_body(self, fn: ast.FunctionDef) -> None:
        params = [a.arg for a in fn.args.args if a.arg != "self"]
        # device_apply(self, enc, dev) / device_apply_with(self, c, enc, dev)
        traced = set(params)
        self._check_numpy_use(fn)
        self._check_traced_branches(fn, traced_params=traced)
        self._check_fixed_batch_dim(fn)

    def _check_numpy_use(self, fn: ast.FunctionDef) -> None:
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id in ("np", "numpy") and \
                    sub.attr not in _NP_CONST_WHITELIST:
                self._emit(
                    sub, "L001",
                    f"numpy call `{sub.value.id}.{sub.attr}` inside "
                    f"`{fn.name}` — host numpy breaks/escapes the XLA "
                    "trace; use jax.numpy, or move the work to "
                    "host_prepare")

    def _check_traced_branches(self, fn: ast.FunctionDef,
                               traced_params: Set[str]) -> None:
        if not traced_params:
            return
        tainted = set(traced_params)
        # one level of value flow: x = dev[0] / v = enc["k"] taints x/v
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Subscript):
                base = sub.value.value
                if isinstance(base, ast.Name) and base.id in traced_params:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            tainted.add(tgt.id)

        def test_is_traced(test: ast.AST) -> bool:
            # `if enc:` (container truthiness) and `if dev[0] is None:`
            # (identity vs None) are static under tracing — what breaks is
            # a VALUE comparison/read: `if x > 0`, `while dev[1]:` etc.
            exempt: set = set()
            for n in ast.walk(test):
                if isinstance(n, ast.Compare) and all(
                        isinstance(o, (ast.Is, ast.IsNot)) for o in n.ops):
                    for m in ast.walk(n):
                        exempt.add(id(m))
            for n in ast.walk(test):
                if id(n) in exempt:
                    continue
                if isinstance(n, ast.Subscript) and \
                        isinstance(n.value, ast.Name) and \
                        n.value.id in tainted:
                    return True
                if isinstance(n, ast.Compare):
                    for m in ast.walk(n):
                        if isinstance(m, ast.Name) and m.id in tainted:
                            return True
                # bare truthiness of a VALUE pulled out of a param
                # (`x = dev[0]` then `if x:`) raises
                # TracerBoolConversionError; bare truthiness of the param
                # itself stays exempt (container/pytree args are common)
                if isinstance(n, ast.Name) and n.id in tainted and \
                        n.id not in traced_params:
                    return True
            return False

        for sub in ast.walk(fn):
            if isinstance(sub, (ast.If, ast.While, ast.IfExp)) and \
                    test_is_traced(sub.test):
                kind = type(sub).__name__.lower()
                self._emit(
                    sub, "L002",
                    f"Python `{kind}` on a traced value inside "
                    f"`{fn.name}` — use jnp.where/lax.cond (branching on "
                    "tracers fails or bakes one path into the compile)")

    # -- L006 -------------------------------------------------------------- #

    _MODULE_RESHAPE_BASES = ("jnp", "np", "numpy", "jax", "lax")

    def _check_fixed_batch_dim(self, fn: ast.FunctionDef) -> None:
        """Flag reshape/broadcast_to whose leading TARGET dim is an int
        literal > 1 inside a device body: bucket padding varies the
        leading batch dim per dispatch, so a baked-in batch size either
        crashes on the first off-size bucket or silently mis-shapes."""
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call) or \
                    not isinstance(sub.func, ast.Attribute):
                continue
            attr = sub.func.attr
            if attr not in ("reshape", "broadcast_to"):
                continue
            base = sub.func.value
            module_form = (
                isinstance(base, ast.Name)
                and base.id in self._MODULE_RESHAPE_BASES) or \
                (isinstance(base, ast.Attribute)
                 and base.attr == "numpy")  # jax.numpy.reshape
            # method form x.reshape(shape...): shape is args[0];
            # module form jnp.reshape(x, shape): shape is args[1]
            idx = 1 if module_form else 0
            if attr == "broadcast_to" and not module_form:
                continue  # no ndarray method broadcast_to in jnp
            if len(sub.args) <= idx:
                continue
            shape = sub.args[idx]
            lead = (shape.elts[0]
                    if isinstance(shape, (ast.Tuple, ast.List))
                    and shape.elts else shape)
            if isinstance(lead, ast.Constant) and \
                    isinstance(lead.value, int) and lead.value > 1:
                self._emit(
                    sub, "L006",
                    f"`{attr}` in `{fn.name}` pins the leading dim to "
                    f"{lead.value} — device code must not assume a fixed "
                    "leading batch dim (bucket padding varies it); derive "
                    "it from x.shape[0] or use -1")

    # -- L003 -------------------------------------------------------------- #

    def _check_static_defaults(self, fn: ast.FunctionDef,
                               statics: Set[str]) -> None:
        args = fn.args.args
        defaults = fn.args.defaults
        offset = len(args) - len(defaults)
        pairs = [(args[offset + i].arg, d) for i, d in enumerate(defaults)]
        # keyword-only statics carry their defaults in kw_defaults
        pairs += [(a.arg, d) for a, d in zip(fn.args.kwonlyargs,
                                             fn.args.kw_defaults)
                  if d is not None]
        for name, d in pairs:
            if name in statics and _is_mutable_literal(d):
                self._emit(
                    d, "L003",
                    f"static arg `{name}` of `{fn.name}` has a mutable "
                    "default — statics must be hashable (tuple/frozenset/"
                    "scalar), and mutable defaults alias across traces")

    # -- L004 -------------------------------------------------------------- #

    def _check_nondeterminism(self, fn: ast.FunctionDef) -> None:
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted(sub.func)
            if dotted is None:
                continue
            if dotted in _NONDET_EXACT or dotted in _NONDET_SUFFIX or \
                    any(dotted.endswith("." + c) for c in _NONDET_SUFFIX):
                self._emit(
                    sub, "L004",
                    f"nondeterministic call `{dotted}` inside `{fn.name}` "
                    "— fits must replay from the FitContext seed")
                continue
            parts = dotted.split(".")
            if len(parts) >= 3 and parts[-2] == "random" and \
                    parts[0] in ("np", "numpy"):
                if parts[-1] in _NONDET_NP_RANDOM:
                    self._emit(
                        sub, "L004",
                        f"global-state RNG `{dotted}` inside `{fn.name}` "
                        "— use np.random.default_rng(ctx.seed)")
                elif parts[-1] == "default_rng" and _rng_seedless(sub):
                    self._emit(
                        sub, "L004",
                        f"seedless `{dotted}()` inside `{fn.name}` — pass "
                        "the FitContext seed")

    # -- L005 -------------------------------------------------------------- #

    def _check_host_prepare(self, fn: ast.FunctionDef,
                            cls: ast.ClassDef) -> None:
        in_types = _class_in_types(cls)
        if not in_types:
            return
        params = [a.arg for a in fn.args.args if a.arg != "self"]
        if not params:
            return
        cols_param = params[0]
        variadic = len(in_types) == 2 and in_types[1] == "..."
        for node in ast.walk(fn):
            # only DIRECT dereferences `cols[i].attr` violate the contract;
            # `c = cols[i]` followed by a None-guard is the sanctioned idiom
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Subscript)):
                continue
            sub = node.value
            if not (isinstance(sub.value, ast.Name)
                    and sub.value.id == cols_param):
                continue
            idx = sub.slice
            if not (isinstance(idx, ast.Constant)
                    and isinstance(idx.value, int)):
                continue
            i = idx.value
            tname = in_types[0] if variadic else (
                in_types[i] if 0 <= i < len(in_types) else None)
            kind = _kind_of_type_name(tname)
            if kind in _DEVICE_KINDS:
                self._emit(
                    sub, "L005",
                    f"host_prepare reads cols[{i}] which is declared "
                    f"{tname} ({kind} kind) — device-kind columns may be "
                    "None on the compiled host phase; read them in "
                    "device_apply via `dev` instead")


# -- L011 (b): host callbacks inside shard_map/pjit bodies ------------------ #

def _is_host_callback(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    if dotted.rsplit(".", 1)[-1] in _HOST_CALLBACK_LAST:
        return dotted
    if any(dotted == s or dotted.endswith("." + s)
           for s in _HOST_CALLBACK_DOTTED_SUFFIX):
        return dotted
    return None


def _spmd_wrapped_bodies(tree: ast.AST):
    """(wrapper_name, body_node) for every function an `shard_map(...)`/
    `pjit(...)` call or decorator wraps: inline lambdas, module/nested
    defs referenced by name, and decorated defs (incl. the
    `@partial(shard_map, ...)` form)."""
    fns = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.setdefault(n.name, n)
    seen: Set[int] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call):
            dotted = _dotted(n.func)
            # @partial(shard_map, mesh=...) nests the wrapper reference
            if dotted in ("partial", "functools.partial") and n.args:
                dotted = _dotted(n.args[0])
                args = n.args[1:]
            else:
                args = n.args
            if dotted is None or \
                    dotted.rsplit(".", 1)[-1] not in _SPMD_WRAPPERS:
                continue
            wrapper = dotted.rsplit(".", 1)[-1]
            for a in args[:1]:
                body = a if isinstance(a, ast.Lambda) else \
                    fns.get(a.id) if isinstance(a, ast.Name) else None
                if body is not None and id(body) not in seen:
                    seen.add(id(body))
                    yield wrapper, body
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in n.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                dotted = _dotted(d)
                if dotted in ("partial", "functools.partial") and \
                        isinstance(dec, ast.Call) and dec.args:
                    dotted = _dotted(dec.args[0])
                if dotted is not None and \
                        dotted.rsplit(".", 1)[-1] in _SPMD_WRAPPERS and \
                        id(n) not in seen:
                    seen.add(id(n))
                    yield dotted.rsplit(".", 1)[-1], n


def _check_spmd_callbacks(tree: ast.AST, path: str) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for wrapper, body in _spmd_wrapped_bodies(tree):
        name = getattr(body, "name", "<lambda>")
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            cb = _is_host_callback(sub)
            if cb is not None:
                findings.append(LintFinding(
                    path, getattr(sub, "lineno", 0), "L011",
                    f"host callback `{cb}` inside `{name}`, which "
                    f"`{wrapper}` maps over the mesh — every shard "
                    "stalls on a host round-trip per step, serializing "
                    "the SPMD program; move the host work outside the "
                    "mapped computation"))
    return findings


# -- L012: legacy global-RNG calls (file-wide, testkit-exempt) -------------- #

def _check_legacy_np_random(tree: ast.AST, path: str) -> List[LintFinding]:
    """Flag every call through numpy's module-level legacy RNG (and
    seedless `default_rng()`) anywhere in the file. `testkit/` files are
    exempt — fixtures own their process and seed at the call site."""
    if "testkit" in os.path.normpath(path).split(os.sep):
        return []
    findings: List[LintFinding] = []
    for sub in ast.walk(tree):
        if not isinstance(sub, ast.Call):
            continue
        dotted = _dotted(sub.func)
        if dotted is None:
            continue
        parts = dotted.split(".")
        if len(parts) < 3 or parts[-2] != "random" or \
                parts[0] not in ("np", "numpy"):
            continue
        if parts[-1] in _LEGACY_NP_RANDOM:
            findings.append(LintFinding(
                path, getattr(sub, "lineno", 0), "L012",
                f"legacy global-RNG call `{dotted}` — the module-level "
                "np.random functions share one hidden RandomState, so "
                "any import/thread reordering silently reshuffles every "
                "draw; use a seeded np.random.default_rng(seed) "
                "Generator"))
        elif parts[-1] == "default_rng" and _rng_seedless(sub):
            findings.append(LintFinding(
                path, getattr(sub, "lineno", 0), "L012",
                f"seedless `{dotted}()` — drift sampling and refit "
                "shuffling must replay deterministically across "
                "journal-resumed runs; pass an explicit seed"))
    return findings


# -- L013: hand-set magic tuning knobs in hot paths -------------------------- #

import re as _re

_L013_DIRS = ("data", "parallel", "serving")
_L013_KNOB_WORDS = ("WORKERS", "DEPTH", "QUEUE", "BATCH", "WAIT",
                    "TIMEOUT", "BUDGET", "TARGET", "RETRIES", "WIDTH",
                    "CHUNK", "THREADS", "POLL", "FEEDERS", "LADDER")
_L013_NAME_RE = _re.compile(r"^[A-Z][A-Z0-9_]*$")
# documented env-tunable sites that predate the cost model: each is
# overridable per call (builder kwargs) and via BENCH_*/TRANSMOGRIFAI_*
# env knobs, and the model now fills the unset axes — keyed by file
# basename so a rename forces a fresh look
_L013_ALLOW = {
    ("bigdata.py", "UPLOAD_CHUNK_ROWS"),
    ("bigdata.py", "HIST_CHUNK_ROWS"),
    ("bigdata.py", "UPLOAD_WORKERS"),
    ("bigdata.py", "UPLOAD_DEPTH"),
    ("columnar_store.py", "DEFAULT_CHUNK_ROWS"),
}


def _check_magic_knobs(tree: ast.AST, path: str) -> List[LintFinding]:
    """Flag new module-level numeric tuning-knob constants in the
    data//parallel//serving/ hot paths that bypass the params/env/cost-
    model plumbing (allowlisted: the documented env-tunable sites)."""
    parts = os.path.normpath(path).split(os.sep)
    if not any(d in parts for d in _L013_DIRS):
        return []
    base = os.path.basename(path)
    findings: List[LintFinding] = []

    def pairs(node):
        """(target Name, value node) pairs for plain, annotated, and
        tuple assignments — `WORKERS: int = 4` and
        `WORKERS, DEPTH = 4, 8` are the same knob in other spellings."""
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                yield node.target, node.value
            return
        if not isinstance(node, ast.Assign):
            return
        for target in node.targets:
            if isinstance(target, ast.Name):
                yield target, node.value
            elif isinstance(target, ast.Tuple) and \
                    isinstance(node.value, ast.Tuple) and \
                    len(target.elts) == len(node.value.elts):
                for t, v in zip(target.elts, node.value.elts):
                    if isinstance(t, ast.Name):
                        yield t, v

    for node in getattr(tree, "body", []):  # module top level only
        for target, v in pairs(node):
            name = target.id
            if not _L013_NAME_RE.match(name):
                continue
            if not any(w in name for w in _L013_KNOB_WORDS):
                continue
            if not (isinstance(v, ast.Constant)
                    and isinstance(v.value, (int, float))
                    and not isinstance(v.value, bool)):
                continue  # env-derived/computed values are the fix, not a hit
            if (base, name) in _L013_ALLOW:
                continue
            findings.append(LintFinding(
                path, node.lineno, "L013",
                f"hand-set tuning knob `{name} = {v.value!r}` in a hot "
                "path bypasses the params/env plumbing and the learned "
                "cost model (perf/) — thread it through "
                "PerfModelParams/OpParams or an env knob so "
                "measurements, not one machine's guess, drive it"))
    return findings


# -- L014: per-request/per-iteration service construction -------------------- #

_L014_SERVICES = ("ScoringService", "FleetService", "FleetMemberService")
_L014_HANDLER_RE = _re.compile(r"^(do_[A-Z]+|handle\w*)$")


def _l014_service_call(call: ast.Call) -> Optional[str]:
    """The service class name when `call` constructs one (direct
    constructor or the `from_path` classmethod), else None."""
    dotted = _dotted(call.func)
    if dotted is None:
        return None
    parts = dotted.split(".")
    if parts[-1] in _L014_SERVICES:
        return parts[-1]
    if len(parts) >= 2 and parts[-1] == "from_path" \
            and parts[-2] in _L014_SERVICES:
        return parts[-2]
    return None


def _check_service_construction(tree: ast.AST,
                                path: str) -> List[LintFinding]:
    """Flag ScoringService/FleetService construction inside loop bodies
    or request-handler methods — per-request service construction pays
    model load + compile + full-ladder AOT warmup on the latency path
    and bypasses the fleet's shared-program registry."""
    findings: List[LintFinding] = []

    def visit(node: ast.AST, loop_depth: int, handler: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def resets loop context (the loop runs the DEF,
            # not the construction) but keeps handler context only for
            # its own name
            handler = node.name if _L014_HANDLER_RE.match(node.name) \
                else None
            loop_depth = 0
        elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            loop_depth += 1
        elif isinstance(node, ast.Call):
            svc = _l014_service_call(node)
            if svc is not None and (loop_depth > 0 or handler):
                where = ("loop body" if loop_depth > 0
                         else f"request handler `{handler}`")
                findings.append(LintFinding(
                    path, getattr(node, "lineno", 0), "L014",
                    f"`{svc}(...)` constructed inside a {where} — "
                    "service construction loads the model, builds the "
                    "compiled scorer, and AOT-warms every bucket, so a "
                    "per-request/per-iteration instance defeats warmup "
                    "and the fleet's shared-program registry; construct "
                    "once outside and route requests through it"))
        for child in ast.iter_child_nodes(node):
            visit(child, loop_depth, handler)

    visit(tree, 0, None)
    return findings


# -- L015: unnamed threads in package code ----------------------------------- #

_L015_EXEMPT_DIRS = ("testkit", "tests")


def _check_unnamed_threads(tree: ast.AST, path: str) -> List[LintFinding]:
    """Flag `threading.Thread(...)` constructions missing `name=` in
    package code — unnamed threads make watchdog/hang diagnostics and
    span attribution useless (which thread is the wedged one?)."""
    parts = os.path.normpath(path).split(os.sep)
    if any(d in parts for d in _L015_EXEMPT_DIRS):
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted not in ("threading.Thread", "Thread"):
            continue
        if any(kw.arg == "name" for kw in node.keywords):
            continue
        if any(kw.arg is None for kw in node.keywords):
            continue  # **kwargs may carry the name; can't prove it doesn't
        findings.append(LintFinding(
            path, getattr(node, "lineno", 0), "L015",
            "`threading.Thread(...)` without a `name=` — unnamed "
            "threads make watchdog/hang diagnostics and span "
            "attribution useless; name it for what it runs "
            "(e.g. name=\"scoring-batcher\")"))
    return findings


# -- L016: closure-captured fitted arrays on the compiled scoring path ------- #

# known-small fitted state (a handful of scalars / (d,)-scale index
# vectors) where per-call staging is noise — everything NEW that
# converts `self.<attr>` to a device array inside a compiled-path body
# must either route through device_constants() or be allowlisted here
_L016_ALLOW = {
    # (class, attr): ~100-entry quantile table / kept-index vector —
    # kilobytes, not the megabyte tables the lint exists for
    ("PercentileCalibratorModel", "quantiles"),
    ("DropIndicesByTransformer", "_indices"),
    ("SanityCheckerModel", "indices"),
}
_L016_METHODS = ("device_apply", "predict_arrays")
_L016_CASTS = ("jnp.asarray", "jnp.array", "jax.numpy.asarray",
               "jax.numpy.array")


def _check_closure_constants(tree: ast.AST, path: str) -> List[LintFinding]:
    """Flag `jnp.asarray(self.X)` inside `device_apply`/`predict_arrays`
    bodies of Transformer classes that do NOT define
    `device_constants()`: the converted array is a closure constant of
    the compiled scoring program — megabyte-scale fitted state gets
    value-baked into the XLA executable (every tenant compiles its own
    program, serving/fleet.py) and re-staged host→device per dispatch
    through the serving tunnel. Route big fitted arrays through
    `device_constants()`/`device_apply_with` so they flow as traced jit
    arguments instead."""
    parts = os.path.normpath(path).split(os.sep)
    if any(d in parts for d in ("testkit", "tests")):
        return []
    findings: List[LintFinding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        method_names = {n.name for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        if "device_constants" in method_names:
            continue  # already lifted
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or fn.name not in _L016_METHODS:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                if _dotted(node.func) not in _L016_CASTS:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Attribute)
                        and isinstance(arg.value, ast.Name)
                        and arg.value.id == "self"):
                    continue
                if (cls.name, arg.attr) in _L016_ALLOW:
                    continue
                findings.append(LintFinding(
                    path, getattr(node, "lineno", 0), "L016",
                    f"`{cls.name}.{fn.name}` converts `self.{arg.attr}` "
                    f"to a device array inside a compiled-path body — a "
                    f"closure constant value-baked into the XLA program "
                    f"and re-staged per dispatch; route fitted arrays "
                    f"through device_constants()/device_apply_with (or "
                    f"allowlist known-small state in _L016_ALLOW)"))
    return findings


# -- L017: unbounded span/event name cardinality ------------------------------ #

# bare/dotted function names whose FIRST argument is an event name
_L017_FUNCS = ("record_event", "emit_event", "add_event")
# method names whose first argument is a span/event name (Tracer.span,
# Span.event, RequestTrace.child/child_at, RunProfile.phase)
_L017_METHODS = ("span", "event", "child", "child_at")
# bounded-by-construction dynamic name families: the interpolated part
# is a worker index, run type, retry/ingest site label, or profile
# phase — closed sets fixed at build time, not wire-derived values.
# Everything NEW must either use a literal name (variability goes in
# attributes) or extend this list with a justified prefix.
_L017_ALLOW_PREFIXES = (
    "retry:", "sweep:worker:", "sweep:family:", "ingest:", "run:",
    "phase:", "stage:",
)


def _l017_dynamic_name(arg: ast.AST) -> bool:
    """True when `arg` builds a string dynamically: an f-string with
    interpolation, or a ``+`` concatenation involving a string
    literal."""
    if isinstance(arg, ast.JoinedStr):
        return any(isinstance(v, ast.FormattedValue) for v in arg.values)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        sides = (arg.left, arg.right)
        return any(isinstance(s, ast.Constant) and isinstance(s.value, str)
                   for s in sides) or any(
            _l017_dynamic_name(s) for s in sides)
    return False


def _l017_literal_prefix(arg: ast.AST) -> str:
    """The leading literal text of a dynamic name (the f-string's first
    constant chunk / the concatenation's left literal), for the
    allowlist check."""
    if isinstance(arg, ast.JoinedStr) and arg.values:
        head = arg.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
        if isinstance(arg.left, ast.Constant) \
                and isinstance(arg.left.value, str):
            return arg.left.value
        return _l017_literal_prefix(arg.left)
    return ""


def _check_event_name_cardinality(tree: ast.AST,
                                  path: str) -> List[LintFinding]:
    """Flag span/event names built with f-strings or ``+`` concatenation
    outside the allowlisted bounded families — unbounded event-name
    cardinality breaks the flight-recorder ring's usefulness, the
    goodput by-name rollups, and Prometheus label hygiene."""
    parts = os.path.normpath(path).split(os.sep)
    if any(d in parts for d in ("testkit", "tests")):
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        dotted = _dotted(node.func)
        if dotted is None:
            continue
        leaf = dotted.split(".")[-1]
        is_attr = isinstance(node.func, ast.Attribute)
        if leaf in _L017_FUNCS:
            pass
        elif is_attr and leaf in _L017_METHODS:
            pass
        else:
            continue
        name_arg = node.args[0]
        if not _l017_dynamic_name(name_arg):
            continue
        # the literal head must fully CONTAIN an allowlist entry
        # (prefix.startswith(entry)); the reverse direction would let
        # any 1-char head that happens to start an entry (f"r{x}" vs
        # "retry:") smuggle unbounded names past the check
        prefix = _l017_literal_prefix(name_arg)
        if prefix and any(prefix.startswith(a)
                          for a in _L017_ALLOW_PREFIXES):
            continue
        findings.append(LintFinding(
            path, getattr(node, "lineno", 0), "L017",
            f"`{leaf}(...)` name built dynamically (f-string/`+` "
            f"concatenation) — span/event names key the flight-recorder "
            f"ring, goodput rollups, and Prometheus series, so an "
            f"interpolated name mints unbounded cardinality; use a "
            f"literal name and carry the variable part as an attribute "
            f"(or add a justified bounded prefix to "
            f"_L017_ALLOW_PREFIXES)"))
    return findings


# -- L018: per-row python on the serving hot path ----------------------------- #

# hot-path function-name markers within serving/ modules
_L018_HOT_NAMES = ("score", "assemble", "demux", "parse")
# rows-shaped iterable leaf names a hot-path For must not iterate
_L018_ROWS_NAMES = ("rows",)
# the codec module IS the sanctioned per-row implementation; smoke and
# chaos drivers are load generators, not the serving data plane
_L018_ALLOW_FILES = ("rowcodec.py",)


def _l018_rows_iter(node: ast.AST) -> bool:
    """True when a For's iterable is rows-shaped: the name ``rows`` (or
    ``*_rows``), possibly behind an attribute (``self.rows``), a
    subscript/slice (``rows[1:]``), or an ``enumerate(...)``."""
    if isinstance(node, ast.Call):
        fn = _dotted(node.func)
        if fn in ("enumerate", "reversed") and node.args:
            return _l018_rows_iter(node.args[0])
        return False
    if isinstance(node, ast.Subscript):
        return _l018_rows_iter(node.value)
    name = _dotted(node)
    if name is None:
        return False
    leaf = name.split(".")[-1]
    return leaf in _L018_ROWS_NAMES or leaf.endswith("_rows")


def _check_per_row_serving_loops(tree: ast.AST,
                                 path: str) -> List[LintFinding]:
    """Flag per-row ``for r in rows:`` loops inside serving hot-path
    functions — the host cost the compiled row codec exists to
    eliminate."""
    parts = os.path.normpath(path).split(os.sep)
    if "serving" not in parts or any(
            d in parts for d in ("testkit", "tests")):
        return []
    base = parts[-1]
    if base in _L018_ALLOW_FILES or base.endswith("_smoke.py") \
            or base in ("chaos.py", "smoke.py"):
        return []
    findings: List[LintFinding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        lname = fn.name.lower()
        if not any(m in lname for m in _L018_HOT_NAMES):
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.For) and _l018_rows_iter(node.iter):
                findings.append(LintFinding(
                    path, getattr(node, "lineno", 0), "L018",
                    f"per-row loop over rows in serving hot path "
                    f"`{fn.name}` — the request parse cost the "
                    f"compiled row codec removed; route rows through "
                    f"data/rowcodec.encode_rows (or operate "
                    f"columnar) instead of iterating request dicts"))
    return findings


# -- L019: blocking work inside a lock's critical section -------------------- #

# calls by dotted name that block on the clock or the disk
_L019_BLOCKING_DOTTED = {
    "time.sleep", "open", "io.open",
    "os.makedirs", "os.replace", "os.fsync", "os.remove", "os.rename",
    "json.dump", "json.load", "pickle.dump", "pickle.load",
    "shutil.copy", "shutil.copyfile", "shutil.move", "shutil.rmtree",
}
# method leaves that are file I/O regardless of receiver (pathlib)
_L019_BLOCKING_LEAVES = {"write_text", "read_text", "write_bytes",
                         "read_bytes"}
# same spelling the whole-program auditor (analysis/concurrency.py)
# accepts — one `# conc-ok: C003` annotation silences both tools, since
# both flag the same pattern (lint sees the lexical site, the auditor
# also sees lock-holding callers)
_L019_CONC_OK_RE = re.compile(r"#\s*conc-ok(?::\s*([A-Z0-9,\s]+))?")


def _l019_lockish(node: ast.AST) -> Optional[str]:
    """The dotted name of a with-item that names a lock (leaf contains
    'lock', or is 'cond'/'mutex'), else None. Name-based on purpose:
    the linter is single-file and cannot resolve types; the auditor
    does the type-resolved pass."""
    name = _dotted(node)
    if name is None:
        return None
    leaf = name.split(".")[-1].lower()
    if "lock" in leaf or leaf in ("cond", "mutex"):
        return name
    return None


def _l019_suppressed(lines: Sequence[str], lineno: int) -> bool:
    """True when the finding line (or the line above it) carries a
    ``# conc-ok`` annotation naming L019 or C003 (or bare)."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _L019_CONC_OK_RE.search(lines[ln - 1])
            if m:
                rules = m.group(1)
                if rules is None:
                    return True
                named = {r.strip() for r in rules.split(",")}
                if named & {"L019", "C003"}:
                    return True
    return False


def _check_blocking_under_lock(tree: ast.AST, path: str,
                               lines: Sequence[str]) -> List[LintFinding]:
    """Flag sleep/file-I/O calls lexically inside ``with <lock>:``."""
    parts = os.path.normpath(path).split(os.sep)
    base = parts[-1]
    if base.endswith("_smoke.py") or base in ("smoke.py", "chaos.py") \
            or "tests" in parts or "testkit" in parts:
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        lock_name = None
        for item in node.items:
            lock_name = _l019_lockish(item.context_expr)
            if lock_name is not None:
                break
        if lock_name is None:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = _dotted(sub.func)
            if fn is None:
                continue
            blocked = fn if fn in _L019_BLOCKING_DOTTED else None
            if blocked is None and "." in fn \
                    and fn.split(".")[-1] in _L019_BLOCKING_LEAVES:
                blocked = fn
            if blocked is None:
                continue
            lineno = getattr(sub, "lineno", 0)
            findings.append(LintFinding(
                path, lineno, "L019",
                f"blocking call `{blocked}` inside `with {lock_name}:` — "
                f"every thread contending {lock_name} stalls behind this "
                f"sleep/disk operation; stage data under the lock and do "
                f"the blocking work after release, or annotate a "
                f"deliberately-serialized writer with `# conc-ok: C003`",
                suppression=("annotation"
                             if _l019_suppressed(lines, lineno) else None)))
    return findings


# -- L020: direct writes into artifact-store namespaces ---------------------- #

# calls that RESOLVE a store/cache location: any path expression built
# on top of one of these is inside a manifest-verified namespace
_L020_DIR_FUNCS = {"path_of", "default_cache_dir", "cache_root",
                   "resolve_dir", "resolved_dir", "resolved_corpus_dir"}
# variable spellings that name a store/cache directory
_L020_DIR_NAME_RE = re.compile(
    r"^(cache|store|artifact)_?dir$|^(feature_cache|artifact_store)_dir$")
_L020_WRITE_MODES = re.compile(r"[wax+]")
_L020_NP_WRITERS = {"save", "savez", "savez_compressed", "savetxt"}
_L020_PATH_LEAVES = {"write_text", "write_bytes"}
_L020_STORE_OK_RE = re.compile(r"#\s*store-ok\b")


def _l020_storeish(expr: ast.AST) -> Optional[str]:
    """The dotted name of the store-location source inside a path
    expression, else None. Walks the whole expression so
    ``os.path.join(cache.path_of(k), "x")`` and f-strings match."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name and name.split(".")[-1] in _L020_DIR_FUNCS:
                return name
        elif isinstance(node, (ast.Name, ast.Attribute)):
            name = _dotted(node)
            if name and _L020_DIR_NAME_RE.match(name.split(".")[-1]):
                return name
    return None


def _l020_suppressed(lines: Sequence[str], lineno: int) -> bool:
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and _L020_STORE_OK_RE.search(lines[ln - 1]):
            return True
    return False


def _check_store_bypass_writes(tree: ast.AST, path: str,
                               lines: Sequence[str]) -> List[LintFinding]:
    """Flag writes whose destination path derives from an artifact-store
    location without going through ``ArtifactStore.put``."""
    parts = os.path.normpath(path).split(os.sep)
    base = parts[-1]
    if base.endswith("_smoke.py") or base in ("smoke.py", "chaos.py",
                                              "artifact.py") \
            or "tests" in parts or "testkit" in parts \
            or ("store" in parts and base == "state.py"):
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _dotted(node.func)
        if fn is None or not node.args:
            continue
        leaf = fn.split(".")[-1]
        target: Optional[ast.AST] = None
        if fn in ("open", "io.open"):
            mode = ""
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                mode = str(node.args[1].value)
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = str(kw.value.value)
            if _L020_WRITE_MODES.search(mode):
                target = node.args[0]
        elif "." in fn and leaf in _L020_NP_WRITERS:
            target = node.args[0]
        elif "." in fn and leaf in _L020_PATH_LEAVES:
            target = node.func.value  # receiver path expression
        if target is None:
            continue
        src_name = _l020_storeish(target)
        if src_name is None:
            continue
        lineno = getattr(node, "lineno", 0)
        findings.append(LintFinding(
            path, lineno, "L020",
            f"direct write via `{fn}` into an artifact-store namespace "
            f"(path built from `{src_name}`) — files in manifest-"
            f"verified directories must land through "
            f"`store.ArtifactStore.put`/`seal_and_commit` (the manifest "
            f"goes in LAST, so readers never see this file as part of a "
            f"verified artifact, or reject the artifact it mutated); "
            f"stage + commit through the store, or annotate a "
            f"deliberate sidecar with `# store-ok: <why>`",
            suppression=("annotation"
                         if _l020_suppressed(lines, lineno) else None)))
    return findings


# -- L021: constant-delay polling loops -------------------------------------- #

def _l021_suppressed(lines: Sequence[str], lineno: int) -> bool:
    """Same ``# conc-ok`` spelling as L019; accepts L021 (or bare)."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _L019_CONC_OK_RE.search(lines[ln - 1])
            if m:
                rules = m.group(1)
                if rules is None:
                    return True
                if {r.strip() for r in rules.split(",")} & {"L021"}:
                    return True
    return False


def _check_blind_poll_loops(tree: ast.AST, path: str,
                            lines: Sequence[str]) -> List[LintFinding]:
    """Flag ``time.sleep(<literal>)`` lexically inside a ``while`` loop:
    coordination waits must be TTL/backoff-derived or Event-woken (see
    module docstring). Only constant arguments flag — a computed delay
    is evidence the loop already derives its cadence from something."""
    parts = os.path.normpath(path).split(os.sep)
    base = parts[-1]
    if base.endswith("_smoke.py") or base in ("smoke.py", "chaos.py") \
            or "tests" in parts or "testkit" in parts:
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.While):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call) \
                    or _dotted(sub.func) != "time.sleep":
                continue
            if not sub.args or not isinstance(sub.args[0], ast.Constant):
                continue
            lineno = getattr(sub, "lineno", 0)
            findings.append(LintFinding(
                path, lineno, "L021",
                f"constant-delay `time.sleep({sub.args[0].value!r})` "
                f"inside a `while` polling loop — a fixed cadence either "
                f"hammers shared state (K replicas polling one "
                f"store/state cell scale the CAS load with fleet size) "
                f"or eats the whole period as idle wall on a cross-host "
                f"handoff; derive the delay from the wait (TTL/deadline, "
                f"capped exponential backoff) or block on "
                f"`Event.wait(timeout=...)` so a writer can wake the "
                f"loop early; annotate a deliberate fixed cadence with "
                f"`# conc-ok: L021`",
                suppression=("annotation"
                             if _l021_suppressed(lines, lineno) else None)))
    return findings


# -- L022: actuation-path calls without a flight-recorder event -------------- #

_L022_ACTUATORS = {"rebucket", "rearm_auto_rebucket", "set_pressure",
                   "set_fidelity_route", "set_route_override"}
_L022_EMITTERS = {"record_event", "request_dump"}
_L022_OK_RE = re.compile(r"#\s*autopilot-ok\b")


def _l022_suppressed(lines: Sequence[str], lineno: int) -> bool:
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and _L022_OK_RE.search(lines[ln - 1]):
            return True
    return False


def _check_unlogged_actuations(tree: ast.AST, path: str,
                               lines: Sequence[str]) -> List[LintFinding]:
    """Flag actuation-API calls outside the controller whose enclosing
    function never emits a flight-recorder event — see module
    docstring (L022)."""
    parts = os.path.normpath(path).split(os.sep)
    base = parts[-1]
    if base in ("autopilot.py", "smoke.py", "chaos.py") \
            or base.endswith("_smoke.py") \
            or "tests" in parts or "testkit" in parts:
        return []
    findings: List[LintFinding] = []
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # innermost-enclosing-function map: nested defs are visited too, so
    # sort outer-first and let inner functions overwrite their ranges
    for fn in funcs:
        emits = any(isinstance(sub, ast.Call)
                    and (_dotted(sub.func) or "").rsplit(".", 1)[-1]
                    in _L022_EMITTERS
                    for sub in ast.walk(fn))
        if emits:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            name = _dotted(sub.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in _L022_ACTUATORS:
                continue
            if fn.name == leaf:
                continue  # the definition module's own wrapper
            lineno = getattr(sub, "lineno", 0)
            findings.append(LintFinding(
                path, lineno, "L022",
                f"actuation call `{name}` outside the autopilot "
                f"controller with no flight-recorder event in "
                f"`{fn.name}` — route flips, admission-threshold "
                f"writes, and ladder re-derivations must record the "
                f"burn window/prediction (or operator action) that "
                f"justified them, or a post-incident flight dump "
                f"cannot explain why traffic moved; emit "
                f"`record_event(...)` beside the call or annotate "
                f"`# autopilot-ok: <why>`",
                suppression=("annotation"
                             if _l022_suppressed(lines, lineno)
                             else None)))
    return findings


# -- L023: manual trace ids that sever the ambient trace context ------------ #

_L023_OPENERS = {"span", "Span", "RequestTrace", "record_event",
                 "emit_event", "add_event"}
_L023_GENERATORS = {"new_run_id", "new_trace_id", "uuid1", "uuid3",
                    "uuid4", "uuid5", "hex", "token_hex"}
_L023_DIRS = {"serving", "parallel", "continual"}
_L023_OK_RE = re.compile(r"#\s*trace-ok\b")


def _l023_suppressed(lines: Sequence[str], lineno: int) -> bool:
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and _L023_OK_RE.search(lines[ln - 1]):
            return True
    return False


def _l023_manual_id(node: ast.AST) -> bool:
    """A trace-id VALUE that was hand-built rather than derived from
    live context: string literals/templates/concats and fresh
    id-generator calls flag; attribute reads (``rt.trace_id``,
    ``ctx.trace_id``) and plain names pass — they carry an id that
    already exists somewhere upstream."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, (ast.JoinedStr, ast.BinOp)):
        return True
    if isinstance(node, ast.Call):
        leaf = (_dotted(node.func) or "").rsplit(".", 1)[-1]
        return leaf in _L023_GENERATORS
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Call):
        # `uuid.uuid4().hex`: a fresh-id call dressed as an attribute
        # read — still hand-built, unlike `rt.trace_id` (Name-rooted)
        return _l023_manual_id(node.value)
    return False


def _check_dropped_trace_context(tree: ast.AST, path: str,
                                 lines: Sequence[str]
                                 ) -> List[LintFinding]:
    """Flag span/event calls that pass a manual trace-id string instead
    of the ambient contextvar parent — see module docstring (L023)."""
    parts = os.path.normpath(path).split(os.sep)
    base = parts[-1]
    if not _L023_DIRS.intersection(parts[:-1]):
        return []
    if base in ("smoke.py", "chaos.py") or base.endswith("_smoke.py") \
            or "tests" in parts or "testkit" in parts:
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = (_dotted(node.func) or "").rsplit(".", 1)[-1]
        if leaf not in _L023_OPENERS:
            continue
        for kw in node.keywords:
            if kw.arg != "trace_id" or not _l023_manual_id(kw.value):
                continue
            lineno = getattr(node, "lineno", 0)
            findings.append(LintFinding(
                path, lineno, "L023",
                f"`{leaf}(...)` passes a manual trace id instead of "
                f"the ambient trace context — a hand-built id severs "
                f"the cross-process stitch: the span lands in the "
                f"trace shard under an id merge_fleet_trace will "
                f"never be asked for, and the request's remote leg "
                f"goes missing from the merged timeline; join the "
                f"current trace (omit trace_id, pass a TraceContext/"
                f"parent span, or root deliberately with "
                f"new_trace=True) or annotate `# trace-ok: <why>`",
                suppression=("annotation"
                             if _l023_suppressed(lines, lineno)
                             else None)))
    return findings


# -- driver ----------------------------------------------------------------- #

def lint_source(src: str, path: str = "<string>") -> List[LintFinding]:
    """Lint one source string (unit-test entry point)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        # a file the linter cannot parse is surfaced, but must not fail
        # a CI gate the way a real finding does — warning severity
        return [LintFinding(path, e.lineno or 0, "L000",
                            f"syntax error: {e.msg}", severity="warning")]
    classes = {n.name: n for n in ast.walk(tree)
               if isinstance(n, ast.ClassDef)}
    linter = _FileLinter(path, classes)
    linter.visit(tree)
    linter.findings.extend(_check_spmd_callbacks(tree, path))
    linter.findings.extend(_check_legacy_np_random(tree, path))
    linter.findings.extend(_check_magic_knobs(tree, path))
    linter.findings.extend(_check_service_construction(tree, path))
    linter.findings.extend(_check_unnamed_threads(tree, path))
    linter.findings.extend(_check_closure_constants(tree, path))
    linter.findings.extend(_check_event_name_cardinality(tree, path))
    linter.findings.extend(_check_per_row_serving_loops(tree, path))
    linter.findings.extend(_check_blocking_under_lock(
        tree, path, src.splitlines()))
    linter.findings.extend(_check_store_bypass_writes(
        tree, path, src.splitlines()))
    linter.findings.extend(_check_blind_poll_loops(
        tree, path, src.splitlines()))
    linter.findings.extend(_check_unlogged_actuations(
        tree, path, src.splitlines()))
    linter.findings.extend(_check_dropped_trace_context(
        tree, path, src.splitlines()))
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.code))


def lint_file(path: str) -> List[LintFinding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def iter_py_files(paths: Sequence[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def lint_paths(paths: Sequence[str]) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m transmogrifai_tpu.lint",
        description="JAX-pitfall lint over stage/kernel source")
    parser.add_argument("paths", nargs="+",
                        help=".py files or directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="emit the shared analysis JSON envelope "
                             "(same shape as analysis.concurrency)")
    args = parser.parse_args(argv)
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not pass a CI gate as "0 findings"
        for p in missing:
            print(f"lint: path does not exist: {p}", file=sys.stderr)
        return 2
    findings: List[LintFinding] = []
    n_files = 0
    for path in iter_py_files(args.paths):
        n_files += 1
        findings.extend(lint_file(path))
    gating = [f for f in findings if f.gating]
    if args.json:
        from transmogrifai_tpu.analysis import report
        print(report.render_json("lint", [
            report.Finding(path=f.path, line=f.line, rule=f.code,
                           message=f.message, severity=f.severity,
                           suppression=f.suppression)
            for f in findings], {"files": n_files}))
    else:
        for f in findings:
            print(f)
        print(f"lint: {len(gating)} gating finding(s) "
              f"({len(findings) - len(gating)} warning/suppressed) "
              f"in {n_files} file(s)")
    # parse-skipped files (L000, warning severity) and annotated
    # escape-hatch findings are reported but never gate the exit code
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
