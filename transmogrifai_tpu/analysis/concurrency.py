"""Whole-program concurrency auditor for the serving plane.

The serving process runs ~10 cooperating thread roles — the scoring
loop, the watchdog, the continual supervisor, HTTP handler threads, the
SLO engine, rebucket/chaos workers — over dozens of lock sites and the
generation fence (`_generation` / `_live(gen)`) that keeps restarted
scoring loops from racing their stale predecessors. Every one of those
disciplines has, until now, been enforced by reviewer eyeball. This
pass turns them into checkable contracts (the same move
`analysis/opcheck.py` makes for the feature DAG): parse the whole
package, recover the thread roles and lock bindings, and flag the
places where the conventions are broken.

Rules
-----

- ``C001 mixed-guard write``: a class attribute reachable from >= 2
  thread roles whose non-``__init__`` writes are SOMETIMES inside
  ``with self._lock:`` and sometimes bare. Mixed guarding is the racy
  tell — either every write is guarded (shared state) or none is
  (single-owner state); a half-guarded attribute means one path forgot.
  Consistently-unguarded attributes do NOT fire (deliberately lock-free
  single-writer paths, e.g. the flight recorder's feed counters, stay
  legal). Helpers that are only ever called with the lock already held
  declare it with a ``# guarded-by: _lock`` comment on the ``def`` (or
  on the write line) — the annotation escape hatch.

- ``C002 lock-order cycle``: a cycle in the lock-acquisition order
  graph. Nodes are ``Class.lockattr``; an edge A -> B is recorded
  whenever B is acquired (lexically, or anywhere below a call made)
  while A is held. Any cycle is a potential deadlock — two threads
  entering the cycle from different edges can each hold the lock the
  other wants. The full lock path is reported.

- ``C003 blocking-under-lock``: a blocking operation reached while a
  lock is held — ``time.sleep``, file I/O (``open``/``write_text``/
  ``os.replace``/``json.dump``...), device dispatch (``score_padded``,
  ``device_put``, ``block_until_ready``), codec ``encode_aligned``/
  ``encode_rows``, thread joins, event/queue waits. Interprocedural:
  a call made under a held lock into a function that (transitively)
  blocks is flagged at the call site. ``Condition.wait`` on the lock
  actually held is exempt (the wait RELEASES that lock — the batcher's
  coalescing linger is the canonical legal case).

- ``C004 unfenced write``: generation-fence discipline. A function
  that read the generation (takes a ``gen`` parameter or snapshots
  ``self.generation``) runs on a fenceable thread; any write it makes
  to a fence-REGISTERED structure must be dominated by a re-check
  (``if self.generation != gen: return/continue``, or a positive
  ``if self._live(gen):`` branch). A structure becomes fence-registered
  by having at least one correctly re-checked store (the staging buffer
  map, resident batch pools); an unchecked store to it elsewhere is how
  a stale restarted loop clobbers state the live loop now owns.
  Functions that BUMP the generation are the fence owners and exempt;
  counter bumps (``+=``) are bookkeeping, not structure writes, and are
  ignored.

Thread roles
------------

Roles are recovered, not configured: every ``threading.Thread(
target=self._m)`` site roots a role at ``_m`` (named by the thread's
``name=`` when literal); every class defining ``do_GET``/``do_POST``/
``handle*`` methods contributes an HTTP-handler role; and every class
that owns a thread also gets a "callers" role over its public methods
(the external threads that call ``score()``/``reload()`` concurrently).
Reachability is a call-graph closure over ``self.m()`` calls (with
inheritance), calls through attributes whose class is inferred from
``self._x = ClassName(...)`` in ``__init__`` (or a property return
annotation), module functions, and module-level singletons
(``RECORDER = FlightRecorder()``).

Suppression
-----------

Two mechanisms, both surfaced in the shared report envelope
(`analysis/report.py`):

- in-source annotations — ``# guarded-by: <lock>`` asserts a guard the
  analysis cannot see; ``# conc-ok: C003`` (comma list, or bare
  ``# conc-ok``) suppresses specific rules on that line, for patterns
  that are blocking-by-design (a write-ahead journal serializing file
  appends under its lock).
- a reviewed BASELINE file (``--baseline conc_baseline.json``) keyed by
  ``(file, rule, symbol)`` — line-independent, so grandfathered
  findings survive unrelated edits. ``--write-baseline`` emits one.

Smoke/chaos drivers (``*_smoke.py``, ``smoke.py``, ``chaos.py``) and
test trees are parsed for type information but never reported on —
load generators hold no serving invariants.

Run: ``python -m transmogrifai_tpu.analysis.concurrency <paths...>``
(``--json`` for the envelope, ``--graph`` for the lock-order graph,
exit 1 only on non-suppressed findings). ``make conc-check`` gates CI.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from transmogrifai_tpu.analysis.lint import _dotted, iter_py_files
from transmogrifai_tpu.analysis.report import (
    WARNING, Finding, gating, render_human, render_json)

__all__ = ["audit_paths", "audit_source", "AuditResult", "main"]

# (class name, lock attr) — lock identity across the program; module-
# level locks use ("<module>:" + basename, name)
LockId = Tuple[str, str]
# (path, class name or "", function name)
FuncKey = Tuple[str, str, str]

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_CONC_OK_RE = re.compile(r"#\s*conc-ok(?::\s*([A-Z0-9,\s]+))?")

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
_MUTATORS = {"append", "appendleft", "extend", "add", "insert", "update",
             "setdefault", "pop", "popleft", "popitem", "remove",
             "discard", "clear"}
_ALLOW_BASENAMES = ("smoke.py", "chaos.py")


def _allowlisted(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    base = parts[-1]
    if base.endswith("_smoke.py") or base in _ALLOW_BASENAMES:
        return True
    return any(d in parts for d in ("tests", "testkit"))


def _lock_label(lock: LockId) -> str:
    return f"{lock[0]}.{lock[1]}"


def _blocking_label(call: ast.Call) -> Optional[str]:
    """Name of the blocking operation a call performs, or None."""
    d = _dotted(call.func)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    if d in ("time.sleep", "sleep"):
        return "time.sleep"
    if d == "open" or leaf in ("write_text", "read_text", "write_bytes",
                               "read_bytes") or d in (
            "os.replace", "os.fsync", "os.makedirs", "json.dump",
            "pickle.dump"):
        return f"file I/O ({leaf})"
    if leaf in ("encode_aligned", "encode_rows"):
        return f"codec {leaf}"
    if leaf in ("score_padded", "block_until_ready") or d in (
            "jax.device_put", "device_put"):
        return f"device dispatch ({leaf})"
    if leaf == "join" and "thread" in d.lower():
        return "thread join"
    if leaf == "wait":
        return "wait"          # condition-on-held-lock exempted by caller
    return None


def _gen_attr(d: Optional[str]) -> bool:
    return d is not None and (
        d in ("generation", "_generation")
        or d.endswith(".generation") or d.endswith("._generation"))


def _is_live_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (_dotted(node.func) or "").split(".")[-1] == "_live")


def _classify_fence_test(test: ast.AST,
                         gen_names: Set[str]) -> Optional[str]:
    """'neg' (body is the STALE branch), 'pos' (body is the verified
    branch), or None for a non-fence test."""
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return "neg" if _is_live_call(test.operand) else None
    if _is_live_call(test):
        return "pos"
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        ld = _dotted(test.left)
        rd = _dotted(test.comparators[0])
        paired = (_gen_attr(ld) and rd in gen_names) or \
                 (_gen_attr(rd) and ld in gen_names)
        if paired:
            if isinstance(test.ops[0], ast.NotEq):
                return "neg"
            if isinstance(test.ops[0], ast.Eq):
                return "pos"
    return None


def _body_exits(body: Sequence[ast.stmt]) -> bool:
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Continue,
                              ast.Break)) for s in body)


# --------------------------------------------------------------------------- #
# Source model                                                                #
# --------------------------------------------------------------------------- #

@dataclass
class Access:
    """One read/write of a (class, attr) pair inside a function."""
    cls: str
    attr: str
    kind: str                  # read | assign | subscript | aug | mutcall
    line: int
    held: FrozenSet[LockId] = frozenset()
    annotated: Optional[str] = None     # guard asserted via # guarded-by
    fence: str = "unchecked"            # unchecked | checked | stale


@dataclass
class FuncRecord:
    key: FuncKey
    node: ast.AST
    path: str
    cls: Optional[str]
    accesses: List[Access] = field(default_factory=list)
    # (lock, line, locks already held at the acquire)
    acquires: List[Tuple[LockId, int, FrozenSet[LockId]]] = \
        field(default_factory=list)
    # (raw dotted callee, call node, locks held at call)
    raw_calls: List[Tuple[str, ast.Call, FrozenSet[LockId]]] = \
        field(default_factory=list)
    calls: List[Tuple[FuncKey, int, FrozenSet[LockId]]] = \
        field(default_factory=list)
    blocking: List[Tuple[str, int, FrozenSet[LockId]]] = \
        field(default_factory=list)
    thread_sites: List[Tuple[ast.Call, int]] = field(default_factory=list)
    local_types: Dict[str, str] = field(default_factory=dict)
    gen_reader: bool = False
    fence_owner: bool = False
    guard_annot: Optional[str] = None   # # guarded-by on the def line


@dataclass
class ClassInfo:
    name: str
    path: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    locks: Dict[str, str] = field(default_factory=dict)   # attr -> kind
    aliases: Dict[str, str] = field(default_factory=dict)  # cond -> lock
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, FuncKey] = field(default_factory=dict)
    http_roots: List[str] = field(default_factory=list)
    owns_thread: bool = False


@dataclass
class Role:
    name: str
    roots: List[FuncKey] = field(default_factory=list)


@dataclass
class AuditResult:
    findings: List[Finding]
    roles: List[str]
    lock_edges: List[Dict[str, object]]
    cycles: List[List[str]]
    n_files: int
    n_locks: int
    elapsed_s: float

    @property
    def gating(self) -> List[Finding]:
        return gating(self.findings)


class Program:
    """Everything the rules need, recovered from a set of sources."""

    def __init__(self) -> None:
        self.classes: Dict[str, ClassInfo] = {}
        self.funcs: Dict[FuncKey, FuncRecord] = {}
        # module basename (no .py) -> {fn name -> FuncKey}
        self.module_fns: Dict[str, Dict[str, FuncKey]] = defaultdict(dict)
        # module basename -> {global name -> class name}
        self.globals_types: Dict[str, Dict[str, str]] = defaultdict(dict)
        # module basename -> {global lock name}
        self.module_locks: Dict[str, Set[str]] = defaultdict(set)
        self.lines: Dict[str, List[str]] = {}
        self.parse_errors: List[Tuple[str, int, str]] = []

    # -- lookups ----------------------------------------------------------- #

    def resolve_method(self, cls_name: str,
                       meth: str) -> Optional[FuncKey]:
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            ci = self.classes.get(name)
            if ci is None:
                continue
            if meth in ci.methods:
                return ci.methods[meth]
            stack.extend(ci.bases)
        return None

    def attr_type(self, cls_name: str, attr: str) -> Optional[str]:
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            ci = self.classes.get(name)
            if ci is None:
                continue
            if attr in ci.attr_types:
                return ci.attr_types[attr]
            stack.extend(ci.bases)
        return None

    def lock_for(self, cls_name: str, attr: str) -> Optional[LockId]:
        """Dealias `attr` to the lock it guards with, walking bases
        (Condition(self._lock) acquires _lock)."""
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            ci = self.classes.get(name)
            if ci is None:
                continue
            if attr in ci.aliases:
                return (cls_name, ci.aliases[attr])
            if attr in ci.locks:
                return (cls_name, attr)
            stack.extend(ci.bases)
        return None

    def annotation_at(self, path: str, line: int) -> Optional[str]:
        """# guarded-by: X on `line` (1-based) or the line above."""
        lines = self.lines.get(path) or []
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _GUARDED_BY_RE.search(lines[ln - 1])
                if m:
                    return m.group(1)
        return None

    def conc_ok_at(self, path: str, line: int, rule: str) -> bool:
        lines = self.lines.get(path) or []
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _CONC_OK_RE.search(lines[ln - 1])
                if m:
                    rules = m.group(1)
                    if rules is None:
                        return True
                    if rule in {r.strip() for r in rules.split(",")}:
                        return True
        return False


# --------------------------------------------------------------------------- #
# Per-function walk: guards, fence state, accesses, calls                     #
# --------------------------------------------------------------------------- #

class _FuncWalker:
    """Single in-order pass over one function body tracking the
    lexically-held lock set and the generation-fence state."""

    def __init__(self, program: Program, rec: FuncRecord,
                 mod: str) -> None:
        self.p = program
        self.rec = rec
        self.mod = mod
        self.cls = rec.cls
        self.held: Tuple[LockId, ...] = ()
        self.fence = "unchecked"
        self.gen_names: Set[str] = set()

    # -- setup ------------------------------------------------------------- #

    def prescan(self, fn: ast.AST) -> None:
        """Gen locals + fence ownership, before the stateful walk."""
        args = getattr(fn, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                if a.arg == "gen":
                    self.gen_names.add("gen")
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name) and _gen_attr(
                        _dotted(node.value)):
                    self.gen_names.add(t.id)
                if _gen_attr(_dotted(t)):
                    self.rec.fence_owner = True
            elif isinstance(node, ast.AugAssign):
                if _gen_attr(_dotted(node.target)):
                    self.rec.fence_owner = True
        self.rec.gen_reader = bool(self.gen_names)

    # -- lock resolution --------------------------------------------------- #

    def _lock_of(self, expr: ast.AST) -> Optional[LockId]:
        d = _dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 2 and parts[0] == "self" and self.cls:
            return self.p.lock_for(self.cls, parts[1])
        if len(parts) == 1:
            if parts[0] in self.p.module_locks.get(self.mod, set()):
                return (f"<module>:{self.mod}", parts[0])
        return None

    # -- statements -------------------------------------------------------- #

    def walk_body(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return                      # closures analyzed separately (not)
        if isinstance(s, ast.With):
            self._with(s)
        elif isinstance(s, ast.If):
            self._if(s)
        elif isinstance(s, (ast.While,)):
            self.expr(s.test)
            kind = _classify_fence_test(s.test, self.gen_names)
            saved = self.fence
            self.fence = "checked" if kind == "pos" else "unchecked"
            self.walk_body(s.body)
            self.fence = saved
            self.walk_body(s.orelse)
        elif isinstance(s, ast.For):
            self.expr(s.iter)
            self.walk_body(s.body)
            self.walk_body(s.orelse)
        elif isinstance(s, ast.Try):
            self.walk_body(s.body)
            entry = self.fence
            for h in s.handlers:
                self.fence = entry
                self.walk_body(h.body)
            self.fence = entry
            self.walk_body(s.orelse)
            self.walk_body(s.finalbody)
        elif isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(s)
        elif isinstance(s, (ast.Expr, ast.Return,)):
            v = getattr(s, "value", None)
            if v is not None:
                self.expr(v)
        elif isinstance(s, ast.Raise):
            if s.exc is not None:
                self.expr(s.exc)
        elif isinstance(s, ast.Assert):
            self.expr(s.test)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                self._record_store(t, "assign", s.lineno)

    def _with(self, s: ast.With) -> None:
        acquired: List[LockId] = []
        for item in s.items:
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self.rec.acquires.append(
                    (lock, item.context_expr.lineno,
                     frozenset(self.held)))
                acquired.append(lock)
            else:
                self.expr(item.context_expr)
        self.held = self.held + tuple(acquired)
        self.walk_body(s.body)
        if acquired:
            self.held = self.held[:-len(acquired)]

    def _if(self, s: ast.If) -> None:
        kind = _classify_fence_test(s.test, self.gen_names)
        self.expr(s.test)
        entry = self.fence
        if kind == "neg":
            self.fence = "stale"
            self.walk_body(s.body)
            # a stale branch that EXITS dominates everything after with
            # a verified fence; one that falls through verifies nothing
            self.fence = "checked" if _body_exits(s.body) else entry
            self.walk_body(s.orelse)
        elif kind == "pos":
            self.fence = "checked"
            self.walk_body(s.body)
            self.fence = entry
            self.walk_body(s.orelse)
        else:
            self.walk_body(s.body)
            after_body = self.fence
            self.fence = entry
            self.walk_body(s.orelse)
            # keep a fence verified in BOTH arms; else back to entry
            if not (after_body == "checked" and self.fence == "checked"):
                self.fence = entry

    # -- expressions and accesses ------------------------------------------ #

    def _assign(self, s: ast.stmt) -> None:
        value = getattr(s, "value", None)
        if value is not None:
            self.expr(value)
        targets = s.targets if isinstance(s, ast.Assign) else [s.target]
        kind = "aug" if isinstance(s, ast.AugAssign) else "assign"
        for t in targets:
            if isinstance(t, ast.Tuple):
                for el in t.elts:
                    self._record_store(el, kind, s.lineno)
            else:
                self._record_store(t, kind, s.lineno)
        if isinstance(s, ast.Assign) and len(s.targets) == 1 and \
                isinstance(s.targets[0], ast.Name) and \
                isinstance(value, ast.Call):
            leaf = (_dotted(value.func) or "").split(".")[-1]
            if leaf in self.p.classes:
                self.rec.local_types[s.targets[0].id] = leaf

    def _owner_of(self, base: ast.AST) -> Optional[str]:
        """Class owning an attribute rooted at `base` (self.X -> the
        current class, GLOBAL.X -> the singleton's class)."""
        if isinstance(base, ast.Name):
            if base.id == "self":
                return self.cls
            return self.p.globals_types.get(self.mod, {}).get(base.id)
        return None

    def _record_store(self, t: ast.AST, kind: str, line: int) -> None:
        node = t
        if isinstance(node, ast.Subscript):
            kind = "subscript" if kind == "assign" else kind
            node = node.value
        if isinstance(node, ast.Attribute):
            owner = self._owner_of(node.value)
            if owner is not None:
                self._access(owner, node.attr, kind, line)
            else:
                self.expr(node.value)
        elif isinstance(node, ast.Subscript):
            self.expr(node)

    def _access(self, owner: str, attr: str, kind: str,
                line: int) -> None:
        annot = self.p.annotation_at(self.rec.path, line) or \
            self.rec.guard_annot
        self.rec.accesses.append(Access(
            owner, attr, kind, line, frozenset(self.held), annot,
            self.fence))

    def expr(self, e: ast.AST) -> None:
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._call(node)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                owner = self._owner_of(node.value)
                if owner is not None:
                    self._access(owner, node.attr, "read", node.lineno)

    def _call(self, call: ast.Call) -> None:
        d = _dotted(call.func) or ""
        held = frozenset(self.held)
        # thread creation sites
        if d in ("threading.Thread", "Thread"):
            self.rec.thread_sites.append((call, call.lineno))
        # mutation-method writes (self._q.append(...), RECORDER.x.add())
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _MUTATORS and \
                isinstance(call.func.value, ast.Attribute):
            owner = self._owner_of(call.func.value.value)
            if owner is not None:
                self._access(owner, call.func.value.attr, "mutcall",
                             call.lineno)
        # blocking operations
        label = _blocking_label(call)
        if label == "wait":
            # Condition.wait on the held lock RELEASES it — legal
            obj_lock = None
            if isinstance(call.func, ast.Attribute):
                obj_lock = self._lock_of(call.func.value)
            if obj_lock is not None and obj_lock in held:
                label = None
            elif held:
                label = "wait"
            else:
                label = None
        if label is not None:
            self.rec.blocking.append((label, call.lineno, held))
        # raw call for later resolution
        if d and d not in ("threading.Thread", "Thread"):
            self.rec.raw_calls.append((d, call, held))


# --------------------------------------------------------------------------- #
# Program construction                                                        #
# --------------------------------------------------------------------------- #

def _mod_of(path: str) -> str:
    return os.path.basename(path)[:-3] if path.endswith(".py") \
        else os.path.basename(path)


def _collect_file(program: Program, path: str, src: str) -> None:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        program.parse_errors.append((path, e.lineno or 0,
                                     e.msg or "syntax error"))
        return
    program.lines[path] = src.splitlines()
    mod = _mod_of(path)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            _collect_class(program, path, mod, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = (path, "", node.name)
            program.module_fns[mod][node.name] = key
            program.funcs[key] = FuncRecord(key, node, path, None)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            name = node.targets[0].id
            leaf = (_dotted(node.value.func) or "").split(".")[-1]
            if leaf in _LOCK_CTORS:
                program.module_locks[mod].add(name)
            else:
                # module-level singleton; class resolution is deferred
                program.globals_types[mod][name] = leaf


def _collect_class(program: Program, path: str, mod: str,
                   node: ast.ClassDef) -> None:
    ci = ClassInfo(node.name, path, node,
                   bases=[(_dotted(b) or "").split(".")[-1]
                          for b in node.bases])
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        key = (path, node.name, item.name)
        ci.methods[item.name] = key
        rec = FuncRecord(key, item, path, node.name)
        rec.guard_annot = program.annotation_at(path, item.lineno) \
            if path in program.lines else None
        program.funcs[key] = rec
        if item.name.startswith("do_") or item.name.startswith("handle"):
            ci.http_roots.append(item.name)
        # property return annotations type the attribute
        if any((_dotted(dec) or "") == "property"
               for dec in item.decorator_list):
            ann = item.returns
            t = None
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                t = ann.value.split(".")[-1].strip("'\"")
            elif ann is not None:
                t = (_dotted(ann) or "").split(".")[-1] or None
            if t:
                ci.attr_types[item.name] = t
        # lock/ctor discovery (any method; __init__ in practice)
        for sub in ast.walk(item):
            if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Attribute)):
                continue
            tgt = sub.targets[0]
            if not (isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            if not isinstance(sub.value, ast.Call):
                continue
            leaf = (_dotted(sub.value.func) or "").split(".")[-1]
            if leaf in _LOCK_CTORS:
                ci.locks[tgt.attr] = leaf
            elif leaf in _COND_CTORS:
                arg = sub.value.args[0] if sub.value.args else None
                ad = _dotted(arg) if arg is not None else None
                if ad and ad.startswith("self."):
                    ci.aliases[tgt.attr] = ad.split(".", 1)[1]
                else:
                    ci.locks[tgt.attr] = leaf
            else:
                ci.attr_types.setdefault(tgt.attr, leaf)
    program.classes[node.name] = ci


def _build_program(sources: Dict[str, str]) -> Program:
    program = Program()
    for path in sorted(sources):
        program.lines[path] = sources[path].splitlines()
    for path in sorted(sources):
        _collect_file(program, path, sources[path])
    # drop singleton/attr "types" that aren't project classes
    for mod, d in program.globals_types.items():
        for name in list(d):
            if d[name] not in program.classes:
                del d[name]
    for ci in program.classes.values():
        for attr in list(ci.attr_types):
            if ci.attr_types[attr] not in program.classes:
                del ci.attr_types[attr]
        # re-read guard annotations now that every file's lines exist
        for meth, key in ci.methods.items():
            rec = program.funcs[key]
            rec.guard_annot = program.annotation_at(
                ci.path, rec.node.lineno)
    # the stateful walk, then call resolution
    for key, rec in program.funcs.items():
        walker = _FuncWalker(program, rec, _mod_of(rec.path))
        walker.prescan(rec.node)
        walker.walk_body(rec.node.body)  # type: ignore[attr-defined]
        rec.gen_reader = walker.rec.gen_reader
    for rec in program.funcs.values():
        _resolve_calls(program, rec)
    return program


def _resolve_calls(program: Program, rec: FuncRecord) -> None:
    mod = _mod_of(rec.path)
    for d, call, held in rec.raw_calls:
        key = _resolve_one(program, rec, mod, d)
        if key is not None:
            rec.calls.append((key, call.lineno, held))


def _resolve_one(program: Program, rec: FuncRecord, mod: str,
                 d: str) -> Optional[FuncKey]:
    parts = d.split(".")
    if parts[0] == "self" and rec.cls:
        if len(parts) == 2:
            return program.resolve_method(rec.cls, parts[1])
        if len(parts) == 3:
            t = program.attr_type(rec.cls, parts[1])
            if t:
                return program.resolve_method(t, parts[2])
        return None
    if len(parts) == 1:
        name = parts[0]
        if name in program.module_fns.get(mod, {}):
            return program.module_fns[mod][name]
        owners = [m for m, fns in program.module_fns.items()
                  if name in fns]
        if len(owners) == 1:
            return program.module_fns[owners[0]][name]
        return None
    if len(parts) == 2:
        base, meth = parts
        t = rec.local_types.get(base) or \
            program.globals_types.get(mod, {}).get(base)
        if t:
            return program.resolve_method(t, meth)
        if base in program.module_fns and \
                meth in program.module_fns[base]:
            return program.module_fns[base][meth]
    if len(parts) == 3:
        base, attr, meth = parts
        t = rec.local_types.get(base) or \
            program.globals_types.get(mod, {}).get(base)
        if t:
            t2 = program.attr_type(t, attr)
            if t2:
                return program.resolve_method(t2, meth)
    return None


# --------------------------------------------------------------------------- #
# Thread roles                                                                #
# --------------------------------------------------------------------------- #

def _thread_name(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "name":
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                return kw.value.value
            if isinstance(kw.value, ast.JoinedStr):
                lits = [v.value for v in kw.value.values
                        if isinstance(v, ast.Constant)]
                if lits:
                    return "".join(str(x) for x in lits).rstrip("-_") \
                        or None
    return None


def _thread_target(program: Program, rec: FuncRecord,
                   call: ast.Call) -> Optional[FuncKey]:
    for kw in call.keywords:
        if kw.arg != "target":
            continue
        d = _dotted(kw.value)
        if d is None:
            return None
        return _resolve_one(program, rec, _mod_of(rec.path), d)
    return None


def _build_roles(program: Program) -> List[Role]:
    roles: Dict[str, Role] = {}

    def add(name: str, root: FuncKey) -> None:
        roles.setdefault(name, Role(name)).roots.append(root)

    owner_classes: Set[str] = set()
    for rec in program.funcs.values():
        for call, _line in rec.thread_sites:
            target = _thread_target(program, rec, call)
            if target is None:
                continue
            tname = _thread_name(call) or (
                f"thread:{target[1] or _mod_of(target[0])}.{target[2]}")
            add(tname, target)
            if target[1]:
                owner_classes.add(target[1])
    for ci in program.classes.values():
        if ci.http_roots:
            for meth in ci.http_roots:
                add(f"http:{ci.name}", ci.methods[meth])
    for cls in sorted(owner_classes):
        ci = program.classes.get(cls)
        if ci is None:
            continue
        for meth, key in ci.methods.items():
            if not meth.startswith("_"):
                add(f"callers:{cls}", key)
    return list(roles.values())


def _closure(program: Program, roots: Sequence[FuncKey]) -> Set[FuncKey]:
    seen: Set[FuncKey] = set()
    stack = [r for r in roots if r in program.funcs]
    while stack:
        key = stack.pop()
        if key in seen:
            continue
        seen.add(key)
        for callee, _line, _held in program.funcs[key].calls:
            if callee not in seen and callee in program.funcs:
                stack.append(callee)
    return seen


# --------------------------------------------------------------------------- #
# Rules                                                                       #
# --------------------------------------------------------------------------- #

def _role_touch_map(program: Program, roles: Sequence[Role]
                    ) -> Dict[Tuple[str, str], Set[str]]:
    touched: Dict[Tuple[str, str], Set[str]] = defaultdict(set)
    for role in roles:
        for key in _closure(program, role.roots):
            for acc in program.funcs[key].accesses:
                touched[(acc.cls, acc.attr)].add(role.name)
    return touched


def _construction_only(program: Program,
                       roles: Sequence[Role]) -> Set[FuncKey]:
    """Functions reachable from an ``__init__`` and from NO thread
    role: construction-phase helpers (``journal._load`` style). Their
    writes happen before the object is shared — thread ``start()``
    publishes them — so they never race."""
    init_roots = [key for key in program.funcs
                  if key[2] in ("__init__", "__new__")]
    init_reach = _closure(program, init_roots)
    role_reach: Set[FuncKey] = set()
    for role in roles:
        role_reach |= _closure(program, role.roots)
    return init_reach - role_reach


def _check_c001(program: Program, roles: Sequence[Role]
                ) -> List[Finding]:
    touched = _role_touch_map(program, roles)
    construction = _construction_only(program, roles)
    writes: Dict[Tuple[str, str], List[Tuple[FuncRecord, Access]]] = \
        defaultdict(list)
    for rec in program.funcs.values():
        if rec.key[2] in ("__init__", "__new__") or \
                rec.key in construction:
            continue
        for acc in rec.accesses:
            if acc.kind != "read":
                writes[(acc.cls, acc.attr)].append((rec, acc))
    findings: List[Finding] = []
    for (cls, attr), sites in sorted(writes.items()):
        ci = program.classes.get(cls)
        if ci is None or attr in ci.locks or attr in ci.aliases:
            continue
        role_set = touched.get((cls, attr), set())
        if len(role_set) < 2:
            continue
        guarded = [(r, a) for r, a in sites if a.held or a.annotated]
        bare = [(r, a) for r, a in sites
                if not a.held and not a.annotated]
        if not guarded or not bare:
            continue
        locks = sorted({_lock_label(l) for _, a in guarded
                        for l in a.held} |
                       {f"{cls}.{a.annotated}" for _, a in guarded
                        if a.annotated})
        for rec, acc in bare:
            findings.append(Finding(
                rec.path, acc.line, "C001",
                f"write to `{cls}.{attr}` without the lock other "
                f"writers hold ({', '.join(locks)}); attribute is "
                f"reachable from {len(role_set)} thread roles "
                f"({', '.join(sorted(role_set))}) — guard the write or "
                f"annotate the call path `# guarded-by: <lock>`",
                symbol=f"{cls}.{attr}"))
    return findings


def _locks_below(program: Program) -> Dict[FuncKey, Set[LockId]]:
    below: Dict[FuncKey, Set[LockId]] = {
        key: {lock for lock, _l, _h in rec.acquires}
        for key, rec in program.funcs.items()}
    changed = True
    while changed:
        changed = False
        for key, rec in program.funcs.items():
            for callee, _line, _held in rec.calls:
                extra = below.get(callee, set()) - below[key]
                if extra:
                    below[key] |= extra
                    changed = True
    return below


def _blocking_below(program: Program
                    ) -> Dict[FuncKey, List[Tuple[str, str, int]]]:
    """(label, path, line) blocking sites in-or-below each function.

    Only sites NOT under a lock in their own function seed the map —
    locked sites are direct C003 findings at the site itself, and
    re-reporting them at every locked caller would drown the report."""
    below: Dict[FuncKey, List[Tuple[str, str, int]]] = {
        key: [(lbl, rec.path, line)
              for lbl, line, held in rec.blocking if not held]
        for key, rec in program.funcs.items()}
    changed = True
    while changed:
        changed = False
        for key, rec in program.funcs.items():
            have = set(below[key])
            for callee, _line, _held in rec.calls:
                for site in below.get(callee, []):
                    if site not in have:
                        below[key].append(site)
                        have.add(site)
                        changed = True
    return below


def _lock_graph(program: Program
                ) -> List[Dict[str, object]]:
    below = _locks_below(program)
    edges: Dict[Tuple[LockId, LockId], Dict[str, object]] = {}

    def add(a: LockId, b: LockId, path: str, line: int,
            via: str) -> None:
        if a == b:
            return                      # re-entry, not an ordering edge
        edges.setdefault((a, b), {
            "from": _lock_label(a), "to": _lock_label(b),
            "site": f"{path}:{line}", "via": via})

    for key, rec in program.funcs.items():
        where = f"{key[1] + '.' if key[1] else ''}{key[2]}"
        for lock, line, held in rec.acquires:
            for h in held:
                add(h, lock, rec.path, line, where)
        for callee, line, held in rec.calls:
            if not held:
                continue
            callee_where = \
                f"{callee[1] + '.' if callee[1] else ''}{callee[2]}"
            for lock in below.get(callee, set()):
                for h in held:
                    add(h, lock, rec.path, line,
                        f"{where} -> {callee_where}")
    return [edges[k] for k in sorted(edges, key=lambda e: (
        _lock_label(e[0]), _lock_label(e[1])))]


def _find_cycles(edge_list: List[Dict[str, object]]
                 ) -> List[List[str]]:
    adj: Dict[str, List[str]] = defaultdict(list)
    for e in edge_list:
        adj[str(e["from"])].append(str(e["to"]))
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in adj.get(node, []):
            if nxt == start and len(path) > 1:
                # canonicalize on the smallest rotation
                cyc = path + [start]
                base = path[:]
                k = base.index(min(base))
                canon = tuple(base[k:] + base[:k])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(cyc)
            elif nxt not in on_path and nxt > start:
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return cycles


def _check_c002(program: Program, edge_list: List[Dict[str, object]]
                ) -> Tuple[List[Finding], List[List[str]]]:
    cycles = _find_cycles(edge_list)
    by_pair = {(str(e["from"]), str(e["to"])): e for e in edge_list}
    findings: List[Finding] = []
    for cyc in cycles:
        first = by_pair.get((cyc[0], cyc[1]))
        site = str(first["site"]) if first else "?:0"
        path, _, line = site.partition(":")
        legs = []
        for a, b in zip(cyc, cyc[1:]):
            e = by_pair.get((a, b))
            legs.append(f"{a} -> {b}"
                        f" ({e['via']} at {e['site']})" if e else
                        f"{a} -> {b}")
        findings.append(Finding(
            path, int(line or 0), "C002",
            "lock-order cycle (potential deadlock): "
            + "; ".join(legs)
            + " — acquire these locks in one global order",
            symbol="->".join(cyc)))
    return findings, cycles


def _check_c003(program: Program) -> List[Finding]:
    findings: List[Finding] = []
    bbelow = _blocking_below(program)
    for key, rec in program.funcs.items():
        where = f"{key[1] + '.' if key[1] else ''}{key[2]}"
        for label, line, held in rec.blocking:
            if not held:
                continue
            locks = ", ".join(sorted(_lock_label(l) for l in held))
            findings.append(Finding(
                rec.path, line, "C003",
                f"blocking {label} while holding {locks} in "
                f"`{where}` — every other thread contending that lock "
                f"stalls behind it; move the blocking work outside "
                f"the critical section",
                symbol=f"{where}:{label}"))
        reported: Set[int] = set()
        for callee, line, held in rec.calls:
            if not held or line in reported:
                continue
            sites = bbelow.get(callee, [])
            own = {(l, ln) for l, ln, _h in rec.blocking}
            sites = [s for s in sites
                     if not (s[1] == rec.path and (s[0], s[2]) in own)]
            if not sites:
                continue
            lbl, spath, sline = sites[0]
            locks = ", ".join(sorted(_lock_label(l) for l in held))
            callee_where = \
                f"{callee[1] + '.' if callee[1] else ''}{callee[2]}"
            findings.append(Finding(
                rec.path, line, "C003",
                f"call to `{callee_where}` while holding {locks} "
                f"reaches blocking {lbl} ({spath}:{sline}) — the lock "
                f"is held across the blocking operation",
                symbol=f"{where}->{callee_where}"))
            reported.add(line)
    return findings


def _check_c004(program: Program) -> List[Finding]:
    registered: Set[Tuple[str, str]] = set()
    for rec in program.funcs.values():
        if not rec.gen_reader or rec.fence_owner:
            continue
        for acc in rec.accesses:
            if acc.kind in ("assign", "subscript") and \
                    acc.fence == "checked":
                registered.add((acc.cls, acc.attr))
    findings: List[Finding] = []
    for rec in sorted(program.funcs.values(), key=lambda r: r.key):
        if not rec.gen_reader or rec.fence_owner:
            continue
        if rec.key[2] in ("__init__", "__new__"):
            continue
        where = f"{rec.key[1] + '.' if rec.key[1] else ''}{rec.key[2]}"
        for acc in rec.accesses:
            if acc.kind not in ("assign", "subscript", "mutcall"):
                continue
            if (acc.cls, acc.attr) not in registered:
                continue
            if acc.fence != "unchecked":
                continue
            findings.append(Finding(
                rec.path, acc.line, "C004",
                f"write to fence-registered `{acc.cls}.{acc.attr}` in "
                f"`{where}` without a generation re-check — a stale "
                f"restarted loop would clobber state the live loop "
                f"owns; dominate the write with `if self.generation "
                f"!= gen: return` (or `if self._live(gen):`)",
                symbol=f"{acc.cls}.{acc.attr}"))
    return findings


# --------------------------------------------------------------------------- #
# Baseline + driver                                                           #
# --------------------------------------------------------------------------- #

def load_baseline(path: str) -> List[Dict[str, str]]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", []) if isinstance(doc, dict) else doc
    return [e for e in entries if isinstance(e, dict)]


def _apply_suppressions(program: Program, findings: List[Finding],
                        baseline: Sequence[Dict[str, str]]) -> None:
    for f in findings:
        if program.conc_ok_at(f.path, f.line, f.rule):
            f.suppression = "annotation"
            continue
        for e in baseline:
            if e.get("rule") != f.rule or e.get("symbol") != f.symbol:
                continue
            bf = str(e.get("file", ""))
            if f.path.endswith(bf) or bf.endswith(f.path):
                f.suppression = "baseline"
                break


def _audit(sources: Dict[str, str],
           baseline: Sequence[Dict[str, str]] = ()) -> AuditResult:
    t0 = time.monotonic()
    program = _build_program(sources)
    roles = _build_roles(program)
    edge_list = _lock_graph(program)
    findings: List[Finding] = []
    findings.extend(_check_c001(program, roles))
    c002, cycles = _check_c002(program, edge_list)
    findings.extend(c002)
    findings.extend(_check_c003(program))
    findings.extend(_check_c004(program))
    findings = [f for f in findings if not _allowlisted(f.path)]
    for path, line, msg in program.parse_errors:
        findings.append(Finding(path, line, "C000",
                                f"parse skipped: {msg}",
                                severity=WARNING))
    _apply_suppressions(program, findings, baseline)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    n_locks = sum(len(ci.locks) for ci in program.classes.values()) + \
        sum(len(v) for v in program.module_locks.values())
    return AuditResult(
        findings=findings,
        roles=sorted(r.name for r in _build_roles(program)),
        lock_edges=edge_list,
        cycles=cycles,
        n_files=len(program.lines),
        n_locks=n_locks,
        elapsed_s=time.monotonic() - t0)


def audit_paths(paths: Sequence[str],
                baseline: Sequence[Dict[str, str]] = ()) -> AuditResult:
    sources: Dict[str, str] = {}
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            sources[path] = f.read()
    return _audit(sources, baseline)


def audit_source(src: str, path: str = "<fixture>.py",
                 baseline: Sequence[Dict[str, str]] = ()) -> AuditResult:
    """Single-source entry point (unit tests)."""
    return _audit({path: src}, baseline)


def _graph_summary(result: AuditResult) -> str:
    lines = [f"lock-order graph: {len(result.lock_edges)} edge(s), "
             f"{len(result.cycles)} cycle(s)"]
    for e in result.lock_edges:
        lines.append(f"  {e['from']} -> {e['to']}  "
                     f"[{e['via']} at {e['site']}]")
    for cyc in result.cycles:
        lines.append("  CYCLE: " + " -> ".join(cyc))
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m transmogrifai_tpu.analysis.concurrency",
        description="whole-program concurrency audit (C001-C004)")
    parser.add_argument("paths", nargs="+",
                        help=".py files or directories to audit")
    parser.add_argument("--json", action="store_true",
                        help="emit the shared JSON report envelope")
    parser.add_argument("--baseline", default=None,
                        help="reviewed baseline file (grandfathered "
                             "findings, keyed file/rule/symbol)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="write current gating findings to "
                             "--baseline and exit 0")
    parser.add_argument("--graph", action="store_true",
                        help="print the lock-order graph summary")
    args = parser.parse_args(argv)
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        for p in missing:
            print(f"concurrency: path does not exist: {p}",
                  file=sys.stderr)
        return 2
    baseline: List[Dict[str, str]] = []
    if args.baseline and os.path.exists(args.baseline) and \
            not args.write_baseline:
        baseline = load_baseline(args.baseline)
    result = audit_paths(args.paths, baseline)
    if args.write_baseline:
        if not args.baseline:
            print("concurrency: --write-baseline needs --baseline",
                  file=sys.stderr)
            return 2
        entries = [{"file": f.path, "rule": f.rule,
                    "symbol": f.symbol or "",
                    "reason": "grandfathered (review me)"}
                   for f in result.gating]
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=2)
            f.write("\n")
        print(f"concurrency: wrote {len(entries)} baseline entrie(s) "
              f"to {args.baseline}")
        return 0
    if args.json:
        print(render_json("concurrency", result.findings, extra={
            "roles": result.roles,
            "lock_edges": result.lock_edges,
            "cycles": result.cycles,
        }))
    else:
        text = render_human(result.findings)
        if text:
            print(text)
        if args.graph:
            print(_graph_summary(result))
        n_gate = len(result.gating)
        n_sup = sum(1 for f in result.findings if f.suppression)
        print(f"concurrency: {n_gate} gating finding(s), {n_sup} "
              f"suppressed, {len(result.roles)} thread role(s), "
              f"{result.n_locks} lock(s), "
              f"{len(result.lock_edges)} order edge(s) across "
              f"{result.n_files} file(s) in {result.elapsed_s:.2f}s")
    return 1 if result.gating else 0


if __name__ == "__main__":
    sys.exit(main())
