"""Shared finding envelope for the static-analysis tools.

`analysis/lint.py` (AST pitfall lint, L-rules) and
`analysis/concurrency.py` (whole-program concurrency audit, C-rules)
grew up as separate CLIs with separate output shapes. Editors and CI
want ONE format: a finding is a finding regardless of which pass
produced it. This module is that contract — a tiny dataclass plus the
two renderers (JSON envelope, human text) both tools emit through.

Envelope shape (``--json``)::

    {
      "tool": "lint" | "concurrency",
      "version": 1,
      "findings": [
        {"file": "...", "line": 12, "rule": "C001",
         "severity": "error" | "warning",
         "message": "...",
         "suppression": null | "baseline" | "annotation"},
        ...
      ],
      "counts": {"error": 2, "warning": 1, "suppressed": 3}
    }

Severity semantics are shared too: only ``error`` findings gate a CI
exit code; ``warning`` (e.g. a parse-skipped file) is surfaced but
never fails the build; a non-null ``suppression`` names WHY a finding
is not gating (a reviewed baseline entry, or an in-source annotation
like ``# guarded-by: _lock``).

Import-light on purpose: both consumers must run without JAX.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["Finding", "render_json", "render_human", "gating"]

ERROR = "error"
WARNING = "warning"


@dataclass
class Finding:
    """One analyzer finding in the shared envelope shape."""

    path: str
    line: int
    rule: str
    message: str
    severity: str = ERROR
    # None = gating; "baseline" / "annotation" = suppressed (reported
    # but not counted against the exit code)
    suppression: Optional[str] = None
    # stable symbol the finding is about (e.g. "Class.attr") — what
    # baseline files key on, so entries survive line drift
    symbol: Optional[str] = None

    def to_json(self) -> Dict[str, Any]:
        out = {
            "file": self.path,
            "line": self.line,
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "suppression": self.suppression,
        }
        if self.symbol is not None:
            out["symbol"] = self.symbol
        return out

    def __str__(self) -> str:
        tail = ""
        if self.suppression:
            tail = f" [suppressed: {self.suppression}]"
        elif self.severity != ERROR:
            tail = f" [{self.severity}]"
        return f"{self.path}:{self.line}: {self.rule} {self.message}{tail}"


def gating(findings: Sequence[Finding]) -> List[Finding]:
    """The findings that should fail a CI gate: severity ``error`` and
    not suppressed."""
    return [f for f in findings
            if f.severity == ERROR and f.suppression is None]


def render_json(tool: str, findings: Sequence[Finding],
                extra: Optional[Dict[str, Any]] = None) -> str:
    """The shared JSON envelope (one line-delimited document)."""
    counts = {
        "error": sum(1 for f in findings
                     if f.severity == ERROR and f.suppression is None),
        "warning": sum(1 for f in findings
                       if f.severity == WARNING and f.suppression is None),
        "suppressed": sum(1 for f in findings if f.suppression is not None),
    }
    doc: Dict[str, Any] = {
        "tool": tool,
        "version": 1,
        "findings": [f.to_json() for f in findings],
        "counts": counts,
    }
    if extra:
        doc.update(extra)
    return json.dumps(doc, indent=2, sort_keys=False)


def render_human(findings: Sequence[Finding]) -> str:
    """One finding per line, sorted (file, line, rule) — the editors'
    grep format both CLIs print by default."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    return "\n".join(str(f) for f in ordered)
