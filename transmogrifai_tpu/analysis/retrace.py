"""Runtime retracing detector: count jit cache misses per labeled program.

Silent retracing is the canonical JAX perf bug: a jitted function whose
inputs change shape/dtype/static-arg value per call re-traces and
re-compiles every time, turning a microseconds dispatch into seconds of
XLA work — invisible except as mysterious slowness (per the TPU
performance-model line of work, graph-level analysis BEFORE compilation
is where TPU stacks win or lose; this is the dynamic complement to
`analysis/opcheck.py`'s static pass).

The trick: `jax.jit(f)` executes `f`'s *Python body* exactly once per
trace (cache miss). Wrapping the body with a counter therefore counts
traces, not calls:

    fn = instrumented_jit(seg_fn, label="compiled:segment0[OpLogReg]")
    fn(x)   # trace #1 (compile)
    fn(x)   # cached — no count
    fn(y)   # new shape -> trace #2

`workflow/compiled.py` labels each fused segment with its stage names and
`parallel/sweep.py` labels each sweep program with its family + static
group, so `MONITOR.counts()` attributes recompile churn to a specific
stage/program. When one label exceeds `warn_after` traces a warning is
logged once, naming the label — the usual culprits are per-batch shape
drift (pad batches to stable shapes) and unstable static args.
"""

from __future__ import annotations

import functools
import itertools
import logging
import threading
from typing import Any, Callable, Dict, Optional

log = logging.getLogger(__name__)

# monotonically unique wrapper ids (id(object()) would be reused after GC,
# silently merging two programs' per-instance trace counts)
_instance_ids = itertools.count(1)


class RetraceMonitor:
    """Process-wide trace accounting keyed by program label.

    `counts()` aggregates across every wrapper instance sharing a label
    (useful inventory of what compiled), but CHURN is judged per wrapper
    INSTANCE: seven workflows each compiling their own 'compiled:seg0[...]'
    once is seven healthy one-trace programs, not churn — only a single
    jitted program re-tracing past `warn_after` (per-call shape drift,
    unstable statics) trips the warning."""

    def __init__(self, warn_after: int = 6):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._instance: Dict[tuple, int] = {}  # (label, instance) -> traces
        self._trace_s: Dict[str, float] = {}   # label -> summed trace time
        self.warn_after = warn_after

    def record(self, label: str, instance: Optional[int] = None) -> int:
        with self._lock:
            n = self._counts.get(label, 0) + 1
            self._counts[label] = n
            key = (label, instance)
            n_inst = self._instance.get(key, 0) + 1
            self._instance[key] = n_inst
        if n_inst == self.warn_after + 1:
            log.warning(
                "retrace churn: %r traced %d times — each trace is a fresh "
                "XLA compile; check for per-call shape drift or unstable "
                "static args (pad batches to a fixed shape)", label, n_inst)
        return n

    def note_trace_s(self, label: str, seconds: float) -> None:
        """Account measured trace (Python body re-execution) time per
        label — the honest, directly measurable slice of recompile cost
        the goodput report can attribute (XLA backend compile time hides
        behind the first dispatch and is not separable here)."""
        with self._lock:
            self._trace_s[label] = self._trace_s.get(label, 0.0) + seconds

    def trace_seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._trace_s)

    def total_trace_s(self) -> float:
        with self._lock:
            return sum(self._trace_s.values())

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def count(self, label: str) -> int:
        with self._lock:
            return self._counts.get(label, 0)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def churning(self) -> Dict[str, int]:
        """label -> worst per-instance trace count, for labels where any
        single program instance re-traced past the warn threshold."""
        with self._lock:
            out: Dict[str, int] = {}
            for (label, _), n in self._instance.items():
                if n > self.warn_after:
                    out[label] = max(out.get(label, 0), n)
            return out

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time copy of per-label trace counts, for differential
        accounting around a scoped operation (e.g. the serving warmup
        attributes compiles to each shape bucket by diffing snapshots)."""
        return self.counts()

    def delta(self, before: Dict[str, int]) -> Dict[str, int]:
        """Traces recorded since `before` (a `snapshot()`), per label —
        labels with no new traces are omitted, so an empty dict means the
        jit cache fully absorbed the interval (zero recompiles)."""
        now = self.counts()
        out = {label: n - before.get(label, 0) for label, n in now.items()
               if n - before.get(label, 0) > 0}
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._instance.clear()
            self._trace_s.clear()

    def report(self) -> str:
        counts = self.counts()
        if not counts:
            return "retrace: no instrumented programs traced"
        churn = self.churning()
        lines = ["retrace: traces per program (1 = compiled once, ideal)"]
        for label, n in sorted(counts.items(), key=lambda kv: -kv[1]):
            flag = "  <-- CHURN" if label in churn else ""
            lines.append(f"  {n:4d}  {label}{flag}")
        return "\n".join(lines)


MONITOR = RetraceMonitor()

# Device-dispatch accounting, same snapshot()/delta() contract as the
# trace monitor but counting EXECUTIONS of compiled scoring segments
# (CompiledScorer._dispatch), not traces: `DISPATCHES.delta(before)`
# around one score call proves how many XLA programs it launched — the
# fused-plan invariant ("exactly ONE device dispatch per bucket per
# score call") that `make roofline-smoke` and tests assert. warn_after
# is effectively disabled: thousands of dispatches of one program are
# the healthy steady state, not churn.
DISPATCHES = RetraceMonitor(warn_after=1 << 62)


def instrumented_jit(fn: Callable, label: Optional[str] = None,
                     monitor: Optional[RetraceMonitor] = None,
                     **jit_kwargs: Any) -> Callable:
    """`jax.jit(fn, **jit_kwargs)` with trace counting under `label`.

    Drop-in for the jit entry points in workflow/compiled.py and
    parallel/sweep.py; `jit_kwargs` pass through (static_argnames, ...).
    """
    import jax

    mon = monitor or MONITOR
    lbl = label or getattr(fn, "__qualname__", repr(fn))
    inst = next(_instance_ids)  # churn is judged per wrapper, not per label

    @functools.wraps(fn)
    def traced(*args: Any, **kwargs: Any) -> Any:
        # this body runs ONLY on a jit cache miss, so everything here is
        # recompile accounting: count the trace, time the body
        # re-execution, and drop a `recompile` event on the current obs
        # span so the unified timeline and the goodput report both see
        # where compile churn happened
        import time as _time

        from transmogrifai_tpu.obs import trace as _obs_trace

        n = mon.record(lbl, inst)
        t0 = _time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            dt = _time.perf_counter() - t0
            mon.note_trace_s(lbl, dt)
            _obs_trace.add_event("recompile", label=lbl, n=n,
                                 trace_s=round(dt, 6))

    return jax.jit(traced, **jit_kwargs)
