"""Static feature-DAG validation (`opcheck`): catch bad pipelines before
paying for a fit or an XLA compile.

The reference framework's core value proposition is failing BEFORE the
expensive part (SanityChecker, RawFeatureFilter, typed Feature wiring —
PAPER.md §1). The JAX port adds a second expensive part the reference never
had: XLA compilation of the fused DAG. This module walks the lazy feature
graph — no data, no tracing — and reports every statically detectable
wiring defect as a structured `ValidationReport`:

errors (fail the train under ``strict=True``, the default):

- ``arity`` / ``type-mismatch``  — stage ``in_types`` vs. wired features,
  re-checked per edge (graphs built via `clone_graph`, deserialization, or
  direct `Feature(...)` construction bypass `set_input`'s eager check)
- ``duplicate-uid``              — two distinct Feature/Stage objects
  sharing one uid (breaks column keying and serialization)
- ``cycle``                      — cyclic wiring, with the full offending
  feature path in the message
- ``response-leakage``           — a response-rooted feature reachable as
  a predictor: either mixed with predictors by a stage that is not
  ``response_aware``, or an ancestor of a response-aware stage's
  feature-vector slot (the classic label leak)
- ``raw-not-generator``          — a parentless feature whose origin is
  not a FeatureGeneratorStage (the scheduler would place it in layer 0
  and crash at materialization)
- ``device-host-output``         — a jittable Transformer whose output
  feature has host kind (text/list/map): `Transformer._wrap` raises at
  the first transform
- ``device-host-input``          — a jittable Transformer wired to a
  host-kind input without a ``host_prepare`` override: ``device_apply``
  would receive None for that column in the compiled plan
- ``device-no-apply``            — a jittable Transformer implementing no
  ``device_apply``: the compiled planner places it in a device segment
  (a ``transform`` override only covers the eager path), so the first
  compiled scoring call raises NotImplementedError

warnings (never fail the train, reported for inspection):

- ``dead-stage``     — a feature in ``universe`` that is not an ancestor
  of any result feature (its stage fits for nothing)
- ``segment-split``  — a host stage consuming a device-produced feature:
  legal, but it splits the fused XLA program into segments and forces a
  device→host materialization (see workflow/compiled.py)
- ``wiring-drift``   — a feature's ``parents`` differ from its origin
  stage's ``input_features`` (stale ``get_output`` after a re-wire)

`Workflow.train()` and `WorkflowModel.score_compiled()` run this by
default; pass ``strict=False`` to downgrade errors to logged warnings.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from transmogrifai_tpu.data.columns import kind_of
from transmogrifai_tpu.stages.base import (
    HOST_KINDS, FeatureGeneratorStage, Transformer, is_host_stage)

log = logging.getLogger(__name__)

# -- issue codes ----------------------------------------------------------- #

E_ARITY = "arity"
E_TYPE = "type-mismatch"
E_DUP_UID = "duplicate-uid"
E_CYCLE = "cycle"
E_LEAKAGE = "response-leakage"
E_RAW = "raw-not-generator"
E_HOST_OUTPUT = "device-host-output"
E_HOST_INPUT = "device-host-input"
E_NO_APPLY = "device-no-apply"
W_DEAD = "dead-stage"
W_SPLIT = "segment-split"
W_WIRING = "wiring-drift"

@dataclass
class ValidationIssue:
    """One defect: machine-readable code + human hint, anchored to a stage."""

    code: str
    message: str
    stage_uid: Optional[str] = None
    feature: Optional[str] = None
    hint: Optional[str] = None

    def __str__(self) -> str:
        loc = f" [stage {self.stage_uid}]" if self.stage_uid else ""
        hint = f"\n    fix: {self.hint}" if self.hint else ""
        return f"[{self.code}]{loc} {self.message}{hint}"


@dataclass
class ValidationReport:
    errors: List[ValidationIssue] = field(default_factory=list)
    warnings: List[ValidationIssue] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors

    def issues(self, code: str) -> List[ValidationIssue]:
        return [i for i in self.errors + self.warnings if i.code == code]

    def raise_if_errors(self) -> "ValidationReport":
        if self.errors:
            raise GraphValidationError(self)
        return self

    def __str__(self) -> str:
        lines = [f"Feature-DAG validation: {len(self.errors)} error(s), "
                 f"{len(self.warnings)} warning(s)"]
        for i in self.errors:
            lines.append(f"  ERROR {i}")
        for i in self.warnings:
            lines.append(f"  WARN  {i}")
        return "\n".join(lines)


class GraphValidationError(RuntimeError):
    """Raised by strict validation; `.report` carries the structured issues."""

    def __init__(self, report: ValidationReport):
        super().__init__(str(report))
        self.report = report


# -- helpers ---------------------------------------------------------------- #

def _stage_kind(stage) -> str:
    """'host' | 'device' | 'other' — delegates to the compiled planner's
    own `is_host_stage` rule (stages/base.py) so the validator can never
    drift from the plan the scorer actually builds; estimators and
    generators are 'other'."""
    if isinstance(stage, Transformer):
        return "host" if is_host_stage(stage) else "device"
    return "other"


def _safe_kind(ftype) -> Optional[str]:
    try:
        return kind_of(ftype)
    except TypeError:
        return None


def _type_name(t) -> str:
    return getattr(t, "__name__", str(t))


class _Walker:
    """One DFS over stage edges collecting features/stages, detecting cycles
    and duplicate uids. Identity-based memoization: uid collisions between
    DISTINCT objects must be seen, not hidden."""

    def __init__(self):
        self.features: List = []          # in first-visit order
        self.stages: List = []
        self.feature_by_uid: Dict[str, object] = {}
        self.stage_by_uid: Dict[str, object] = {}
        self.issues: List[ValidationIssue] = []
        self._seen_f: set = set()         # id(feature)
        self._seen_s: set = set()         # id(stage)
        self._stack: List = []            # stage objects on the DFS path

    def visit_feature(self, f) -> None:
        if id(f) in self._seen_f:
            # re-entry through a memoized feature can still close a loop:
            # its origin stage being on the current DFS path IS the cycle
            s = f.origin_stage
            if s is not None and any(s is x for x in self._stack):
                self._report_cycle(s)
            return
        self._seen_f.add(id(f))
        prev = self.feature_by_uid.get(f.uid)
        if prev is None:
            self.feature_by_uid[f.uid] = f
        elif prev is not f:
            self.issues.append(ValidationIssue(
                E_DUP_UID,
                f"feature uid {f.uid!r} is shared by two distinct features "
                f"({prev.name!r} and {f.name!r})",
                feature=f.name,
                hint="uids key columns and serialization — regenerate one "
                     "of the features instead of reusing the uid"))
        self.features.append(f)
        if f.origin_stage is not None:
            self.visit_stage(f.origin_stage)

    def visit_stage(self, s) -> None:
        if id(s) in self._seen_s:
            if any(s is x for x in self._stack):
                self._report_cycle(s)
            return
        if any(s is x for x in self._stack):
            self._report_cycle(s)
            return
        self._seen_s.add(id(s))
        prev = self.stage_by_uid.get(s.uid)
        if prev is None:
            self.stage_by_uid[s.uid] = s
        elif prev is not s:
            self.issues.append(ValidationIssue(
                E_DUP_UID,
                f"stage uid {s.uid!r} is shared by two distinct "
                f"{type(prev).__name__}/{type(s).__name__} instances",
                stage_uid=s.uid,
                hint="construct stages without passing an explicit reused "
                     "uid (fitted models legitimately keep their "
                     "estimator's uid, but only one of the pair may be "
                     "wired into a graph)"))
        self.stages.append(s)
        self._stack.append(s)
        try:
            for p in s.input_features:
                self.visit_feature(p)
        finally:
            self._stack.pop()

    def _report_cycle(self, s) -> None:
        start = next(i for i, x in enumerate(self._stack) if x is s)
        path = [x.operation_name for x in self._stack[start:]] + \
               [s.operation_name]
        # one report per distinct cycle entry stage
        if any(i.code == E_CYCLE and i.stage_uid == s.uid
               for i in self.issues):
            return
        self.issues.append(ValidationIssue(
            E_CYCLE,
            "feature graph contains a cycle: " + " -> ".join(path),
            stage_uid=s.uid,
            hint="a feature cannot be (transitively) its own input; break "
                 "the loop at one of the listed stages"))
        self._seen_s.add(id(s))  # do not re-descend into the loop


# -- the checks ------------------------------------------------------------- #

def _check_arity_types(stage, out: List[ValidationIssue]) -> None:
    if isinstance(stage, FeatureGeneratorStage):
        return
    feats = stage.input_features
    if not feats:
        out.append(ValidationIssue(
            E_RAW,
            f"{stage.operation_name} has no inputs but is not a feature "
            "generator — the scheduler would place it in layer 0 and fail",
            stage_uid=stage.uid,
            hint="call set_input(...) before wiring its output, or use a "
                 "FeatureGeneratorStage for raw features"))
        return
    spec = stage.in_types
    if spec is None:
        return
    if len(spec) == 2 and spec[1] is Ellipsis:
        elem = spec[0]
        if elem is None:
            return
        for f in feats:
            if not issubclass(f.ftype, elem):
                out.append(ValidationIssue(
                    E_TYPE,
                    f"{stage.operation_name} requires inputs of type "
                    f"{_type_name(elem)}; input {f.name!r} is "
                    f"{_type_name(f.ftype)}",
                    stage_uid=stage.uid, feature=f.name,
                    hint=f"convert {f.name!r} to {_type_name(elem)} (or "
                         "drop it from this stage's inputs)"))
        return
    if len(feats) != len(spec):
        out.append(ValidationIssue(
            E_ARITY,
            f"{stage.operation_name} requires {len(spec)} input(s), got "
            f"{len(feats)} ({', '.join(f.name for f in feats)})",
            stage_uid=stage.uid,
            hint="re-wire with exactly the declared arity via set_input"))
        return
    for f, t in zip(feats, spec):
        if t is not None and not issubclass(f.ftype, t):
            out.append(ValidationIssue(
                E_TYPE,
                f"{stage.operation_name} input {f.name!r}: expected "
                f"{_type_name(t)}, got {_type_name(f.ftype)}",
                stage_uid=stage.uid, feature=f.name,
                hint=f"feed a {_type_name(t)}-typed feature into this "
                     "slot"))


def _check_host_device(stage, out_feature, errs: List[ValidationIssue],
                       warns: List[ValidationIssue]) -> None:
    kind = _stage_kind(stage)
    if kind == "device":
        # the compiled planner puts this stage in a DEVICE segment, where
        # only device_apply runs — a transform() override cannot save it
        # there (it would only cover the eager fit/score path)
        own_apply = (
            type(stage).device_apply is not Transformer.device_apply
            or type(stage).device_apply_with
            is not Transformer.device_apply_with)
        own_prepare = (type(stage).host_prepare
                       is not Transformer.host_prepare)
        if not own_apply:
            errs.append(ValidationIssue(
                E_NO_APPLY,
                f"{stage.operation_name} is jittable (device-planned) but "
                "implements no device_apply — the compiled scorer would "
                "raise NotImplementedError at the first scoring call",
                stage_uid=stage.uid,
                hint="implement device_apply(), or set jittable=False if "
                     "the stage is host-side numpy (transform overrides "
                     "only cover the eager path)"))
        out_kind = (_safe_kind(out_feature.ftype)
                    if out_feature is not None else None)
        if out_kind in HOST_KINDS:
            errs.append(ValidationIssue(
                E_HOST_OUTPUT,
                f"{stage.operation_name} is jittable but its output "
                f"{out_feature.name!r} has host kind {out_kind!r} — "
                "device segments cannot produce host-kind values "
                "(Transformer._wrap raises on the eager path too)",
                stage_uid=stage.uid, feature=out_feature.name,
                hint="set jittable=False and override transform() (or "
                     "subclass HostTransformer) for host-kind outputs"))
        if not own_prepare:
            for f in stage.input_features:
                k = _safe_kind(f.ftype)
                if k in HOST_KINDS:
                    errs.append(ValidationIssue(
                        E_HOST_INPUT,
                        f"{stage.operation_name} is jittable and consumes "
                        f"host-kind ({k}) input {f.name!r} but does not "
                        "override host_prepare — device_apply would "
                        "receive None for that column",
                        stage_uid=stage.uid, feature=f.name,
                        hint="encode the host column in host_prepare() and "
                             "read it from `enc` in device_apply()"))
    elif kind == "host":
        for f in stage.input_features:
            k = _safe_kind(f.ftype)
            if (k is not None and k not in HOST_KINDS and not f.is_raw
                    and _stage_kind(f.origin_stage) == "device"):
                warns.append(ValidationIssue(
                    W_SPLIT,
                    f"host stage {stage.operation_name} consumes "
                    f"device-produced feature {f.name!r} — the fused XLA "
                    "program splits into segments here and the feature "
                    "materializes device->host",
                    stage_uid=stage.uid, feature=f.name,
                    hint="if scoring throughput matters, move host-side "
                         "work upstream of the device stages or make this "
                         "stage jittable"))


def _response_taint(features: Sequence) -> Dict[str, bool]:
    """feature uid -> True when a response feature is reachable through
    parents WITHOUT passing a response-aware stage (whose outputs — e.g. a
    Prediction — are sanctioned, not leaks)."""
    taint: Dict[str, bool] = {}

    def visit(f) -> bool:
        if f.uid in taint:
            return taint[f.uid]
        taint[f.uid] = False  # breaks cycles; cycle itself reported apart
        if f.is_response:
            t = True
        elif f.origin_stage is not None and \
                getattr(f.origin_stage, "response_aware", False):
            t = False
        else:
            t = any(visit(p) for p in f.parents)
        taint[f.uid] = t
        return t

    for f in features:
        visit(f)
    return taint


def _leak_path(f, taint: Dict[str, bool]) -> List[str]:
    """Name path from a response ancestor down to `f` (for the fix hint)."""
    path: List[str] = []
    cur = f
    guard = 0
    while cur is not None and guard < 1000:
        guard += 1
        path.append(cur.name)
        if cur.is_response:
            break
        cur = next((p for p in cur.parents if taint.get(p.uid)), None)
    return list(reversed(path))


def _check_leakage(stage, taint: Dict[str, bool],
                   errs: List[ValidationIssue]) -> None:
    if isinstance(stage, FeatureGeneratorStage) or not stage.input_features:
        return
    feats = stage.input_features
    if getattr(stage, "response_aware", False):
        # slot 0 is the sanctioned label slot; predictor slots must be clean
        for f in feats[1:]:
            if taint.get(f.uid):
                errs.append(ValidationIssue(
                    E_LEAKAGE,
                    f"response feature leaks into the predictor input "
                    f"{f.name!r} of {stage.operation_name} "
                    f"(path: {' -> '.join(_leak_path(f, taint))})",
                    stage_uid=stage.uid, feature=f.name,
                    hint="remove the response (or anything derived from "
                         "it) from the feature-engineering inputs; only "
                         "the label slot may see it"))
        return
    flags = [bool(taint.get(f.uid)) for f in feats]
    if any(flags) and not all(flags):
        bad = next(f for f, t in zip(feats, flags) if t)
        errs.append(ValidationIssue(
            E_LEAKAGE,
            f"{stage.operation_name} mixes response-derived input "
            f"{bad.name!r} with predictors "
            f"(path: {' -> '.join(_leak_path(bad, taint))}) but is not a "
            "response-aware stage",
            stage_uid=stage.uid, feature=bad.name,
            hint="only response-aware stages (models, SanityChecker, "
                 "supervised bucketizers) may combine the label with "
                 "predictors"))


# -- entry point ------------------------------------------------------------ #

def validate_graph(result_features: Sequence,
                   universe: Optional[Sequence] = None) -> ValidationReport:
    """Validate the DAG reachable from `result_features` without touching
    data. `universe` (optional) is the full set of features the caller
    declared; members that are not ancestors of any result get a
    ``dead-stage`` warning. Never raises on a bad graph — returns the
    report (use `.raise_if_errors()` for strict behavior)."""
    walker = _Walker()
    for f in result_features:
        walker.visit_feature(f)

    errors: List[ValidationIssue] = [
        i for i in walker.issues]  # dup-uid + cycle from the walk
    warnings: List[ValidationIssue] = []

    taint = _response_taint(walker.features)
    out_by_stage: Dict[int, object] = {}
    for f in walker.features:  # first output feature wins, like the walk
        if f.origin_stage is not None:
            out_by_stage.setdefault(id(f.origin_stage), f)
    seen_stage_uids = set()
    for stage in walker.stages:
        if stage.uid in seen_stage_uids:
            continue
        seen_stage_uids.add(stage.uid)
        _check_arity_types(stage, errors)
        out_feature = out_by_stage.get(id(stage))
        _check_host_device(stage, out_feature, errors, warnings)
        _check_leakage(stage, taint, errors)
        if (out_feature is not None and stage.input_features
                and tuple(out_feature.parents)
                != tuple(stage.input_features)):
            warnings.append(ValidationIssue(
                W_WIRING,
                f"{stage.operation_name}: output feature "
                f"{out_feature.name!r} records different parents than the "
                "stage's current input_features (stale get_output after a "
                "re-wire?)",
                stage_uid=stage.uid, feature=out_feature.name,
                hint="call set_input(...) before get_output() and re-wire "
                     "downstream consumers of the old output"))

    if universe:
        reachable = set(walker.feature_by_uid)
        for f in universe:
            if f.uid not in reachable:
                warnings.append(ValidationIssue(
                    W_DEAD,
                    f"feature {f.name!r} "
                    f"({f.origin_stage.operation_name if f.origin_stage else 'raw'}) "
                    "is not an ancestor of any result feature — its stage "
                    "would fit for nothing",
                    stage_uid=(f.origin_stage.uid
                               if f.origin_stage is not None else None),
                    feature=f.name,
                    hint="wire it into a result feature or drop it"))

    return ValidationReport(errors=errors, warnings=warnings)
