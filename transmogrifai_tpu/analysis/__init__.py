"""Static + dynamic pipeline analysis: fail before the fit or the compile.

- `analysis.opcheck`  — static feature-DAG validator (wiring, types,
  cycles, response leakage, host/device contract), run by default from
  `Workflow.train()` and `WorkflowModel.score_compiled()`
- `analysis.lint`     — AST-based JAX-pitfall linter over stage source
  (`python -m transmogrifai_tpu.lint <paths>`)
- `analysis.retrace`  — runtime retracing detector wrapping the repo's
  jit entry points (recompile-churn accounting per stage/program)
"""

from transmogrifai_tpu.analysis.opcheck import (  # noqa: F401
    GraphValidationError, ValidationIssue, ValidationReport, validate_graph)
from transmogrifai_tpu.analysis.retrace import (  # noqa: F401
    DISPATCHES, MONITOR, RetraceMonitor, instrumented_jit)
