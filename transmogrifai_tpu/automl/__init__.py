from transmogrifai_tpu.automl.transmogrify import transmogrify, TransmogrifierDefaults

__all__ = ["transmogrify", "TransmogrifierDefaults"]
