"""SanityChecker & MinVarianceFilter: automated feature validation.

Reference parity: `core/.../preparators/SanityChecker.scala:232-656`
(sampling, Pearson/Spearman label correlations, full feature-feature
correlation matrix, categorical contingency stats — Cramér's V, pointwise
mutual information, mutual information, association-rule max confidence —
drop rules from `DerivedFeatureFilterUtils.scala:355-385`, defaults
`SanityChecker.scala:561-578`), statistics math from
`utils/.../stats/OpStatistics.scala:180-320`, and
`MinVarianceFilter.scala:58,145`.

TPU-first: moments, label correlation and the feature-feature Gram matrix
are ONE fused device pass over (n, d+1) — `Z^T Z` rides the MXU and every
term is a row-axis sum (`psum`-ready under a data-sharded mesh). Spearman
reuses the same pass over host-ranked columns. Contingency tables are
one-hot × one-hot matmuls. Drop decisions (data-dependent shapes) resolve
on host at fit time; the fitted model is a static-index column gather.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.nn
import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)

from transmogrifai_tpu import types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.data.metadata import VectorMetadata
from transmogrifai_tpu.stages.base import Estimator, FitContext, Transformer

# reference defaults (SanityChecker.scala:561-578)
CHECK_SAMPLE = 1.0
SAMPLE_LOWER_LIMIT = 1_000
SAMPLE_UPPER_LIMIT = 1_000_000
MAX_CORRELATION = 0.95
MAX_FEATURE_CORR = 0.99
MIN_CORRELATION = 0.0
MIN_VARIANCE = 1e-5
MAX_CRAMERS_V = 0.95
MAX_RULE_CONFIDENCE = 1.0       # 1.0/1.0 = rule-confidence check off
MIN_REQUIRED_RULE_SUPPORT = 1.0


@dataclass
class ColumnStats:
    name: str
    mean: float
    variance: float
    min: float
    max: float
    corr_label: float
    cramers_v: Optional[float]
    mutual_info: Optional[float] = None
    max_rule_confidence: Optional[float] = None
    support: Optional[float] = None
    dropped: List[str] = field(default_factory=list)

    def to_json(self) -> Dict:
        return {
            "name": self.name, "mean": self.mean, "variance": self.variance,
            "min": self.min, "max": self.max, "corrLabel": self.corr_label,
            "cramersV": self.cramers_v, "mutualInfo": self.mutual_info,
            "maxRuleConfidence": self.max_rule_confidence,
            "support": self.support, "dropped": self.dropped,
        }


@dataclass
class CategoricalGroupStats:
    """Per categorical group (OpStatistics.ContingencyStats analogue)."""

    group: str
    cramers_v: float
    mutual_info: float
    pointwise_mutual_info: Dict[str, List[float]]
    max_rule_confidences: List[float]
    supports: List[float]

    def to_json(self) -> Dict:
        return {
            "group": self.group, "cramersV": self.cramers_v,
            "mutualInfo": self.mutual_info,
            "pointwiseMutualInfo": self.pointwise_mutual_info,
            "maxRuleConfidences": self.max_rule_confidences,
            "supports": self.supports,
        }


@dataclass
class SanityCheckerSummary:
    """Persisted fit diagnostics (SanityCheckerMetadata analogue)."""

    n_rows: int
    stats: List[ColumnStats]
    kept_indices: List[int]
    dropped_indices: List[int]
    correlation_type: str = "pearson"
    sample_fraction: float = 1.0
    categorical_stats: List[CategoricalGroupStats] = field(default_factory=list)

    def to_json(self) -> Dict:
        return {
            "n_rows": self.n_rows,
            "stats": [s.to_json() for s in self.stats],
            "kept": self.kept_indices, "dropped": self.dropped_indices,
            "correlationType": self.correlation_type,
            "sampleFraction": self.sample_fraction,
            "categoricalStats": [c.to_json() for c in self.categorical_stats],
        }


def _column_reductions(X: jnp.ndarray, y: Optional[jnp.ndarray] = None):
    """One fused pass: per-column moments (+ label terms when y given —
    correlations now come from the `_corr_matrix` Gram pass, so the
    checker calls this with y=None).

    Every term is a sum over rows → shard the row axis, `psum` the sums.
    """
    n = X.shape[0]
    out = {"n": n, "sx": X.sum(0), "sxx": (X * X).sum(0),
           "min": X.min(0) if n else jnp.zeros(X.shape[1]),
           "max": X.max(0) if n else jnp.zeros(X.shape[1])}
    if y is not None:
        out.update({"sy": y.sum(), "syy": (y * y).sum(), "sxy": X.T @ y})
    return out


def _corr_matrix(Z: jnp.ndarray) -> np.ndarray:
    """Full correlation matrix of (n, k) via one Gram matmul (MXU path;
    rows sharded → psum). Columns with zero variance correlate as 0."""
    n = Z.shape[0]
    mean = Z.mean(0)
    Zc = Z - mean
    cov = np.asarray(Zc.T @ Zc) / max(n - 1, 1)
    sd = np.sqrt(np.maximum(np.diag(cov), 0.0))
    denom = np.outer(sd, sd)
    with np.errstate(divide="ignore", invalid="ignore"):
        corr = np.where(denom > 0, np.asarray(cov) / denom, 0.0)
    return corr


_WIDE_D = 8192  # feature count beyond which the (d, d) corr never materializes


def _corr_label_and_hits_blocked(Cx: jnp.ndarray, cy: jnp.ndarray,
                                 thr: float, block: Optional[int] = None):
    """Wide-feature-axis path (SURVEY.md §5.7): label-correlation vector +
    the SPARSE set of feature-feature pairs with |corr| > thr, computed in
    column blocks of the Gram product — the full (d, d) matrix (17G entries
    at the 2^17 hashing limit) never exists. Each block is one MXU matmul
    with the row axis `psum`-ready; hit pairs extract on device via a
    fixed-size nonzero so only O(hits) crosses back to host.

    Returns (corr_y (d,), {i: [(j, corr_ij), ...] with j < i}).
    """
    n, d = Cx.shape
    mean = Cx.mean(0)
    Zc = Cx - mean
    sd = jnp.sqrt(jnp.maximum((Zc * Zc).sum(0), 0.0))
    U = jnp.where(sd > 0, Zc / sd, 0.0)
    yc = cy - cy.mean()
    ysd = jnp.sqrt(jnp.maximum((yc * yc).sum(), 0.0))
    uy = jnp.where(ysd > 0, yc / ysd, 0.0)
    corr_y = np.asarray(U.T @ uy, dtype=np.float64)

    if block is None:  # ≤ ~128M-entry (512MB f32) block products
        block = max(128, min(d, (1 << 27) // max(d, 1)))
    cap = 16 * block  # duplicates are sparse; truncation is logged

    @jax.jit
    def block_hits(Ub, a):  # Ub (n, block), a = column offset
        C = Ub.T @ U  # (block, d)
        rows = a + jnp.arange(Ub.shape[1])[:, None]
        cols = jnp.arange(d)[None, :]
        mask = (jnp.abs(C) > thr) & (cols < rows)
        ri, ci = jnp.nonzero(mask, size=cap, fill_value=-1)
        return ri, ci, C[ri, ci], mask.sum()

    pairs: Dict[int, List[Tuple[int, float]]] = {}
    pad = (-d) % block
    Upad = jnp.pad(U, ((0, 0), (0, pad))) if pad else U
    for a in range(0, d, block):
        ri, ci, vals, total = block_hits(
            jax.lax.dynamic_slice_in_dim(Upad, a, block, 1), a)
        ri, ci, vals = np.asarray(ri), np.asarray(ci), np.asarray(vals)
        k = int((ri >= 0).sum())
        if int(total) > cap:
            log.warning(
                "feature-feature corr: %d hits in block %d..%d truncated "
                "to %d — raise max_feature_corr or lower the hash width",
                int(total), a, min(a + block, d), cap)
        for t in range(k):
            i, j = int(ri[t]) + a, int(ci[t])
            if i < d:  # pad columns are all-zero and never hit, but guard
                pairs.setdefault(i, []).append((j, float(vals[t])))
    for i in pairs:
        pairs[i].sort()
    return corr_y, pairs


def _rank_transform(A: np.ndarray) -> np.ndarray:
    """Average-tie ranks per column (Spearman = Pearson over ranks)."""
    import pandas as pd
    return pd.DataFrame(A).rank(method="average").to_numpy(dtype=np.float32)


def _label_onehot(y: np.ndarray, max_card: int,
                  force: Optional[bool] = None) -> Optional[np.ndarray]:
    """One-hot label for contingency tests, or None if not categorical.
    `force=True` treats the (rounded) label as categorical regardless of
    the integrality/cardinality heuristics (categoricalLabel param)."""
    if force is False:
        return None
    yi = np.round(y).astype(np.int64)
    if force is not True and not np.allclose(y, yi, atol=1e-6):
        return None
    levels = np.unique(yi)
    if len(levels) < 2 or (force is not True and len(levels) > max_card):
        return None
    lut = {v: i for i, v in enumerate(levels.tolist())}
    idx = np.array([lut[v] for v in yi.tolist()])
    oh = np.zeros((len(y), len(levels)), dtype=np.float32)
    oh[np.arange(len(y)), idx] = 1.0
    return oh


def cramers_v(contingency: np.ndarray) -> float:
    """Cramér's V from a levels × labels count table, empty rows/cols
    filtered first (OpStatistics.chiSquaredTest, OpStatistics.scala:188)."""
    cont = contingency[contingency.sum(1) > 0][:, contingency.sum(0) > 0]
    if cont.shape[0] < 2 or cont.shape[1] < 2:
        return 0.0
    n = cont.sum()
    if n == 0:
        return 0.0
    row = cont.sum(axis=1, keepdims=True)
    col = cont.sum(axis=0, keepdims=True)
    expected = row @ col / n
    with np.errstate(divide="ignore", invalid="ignore"):
        chi2 = np.where(expected > 0,
                        (cont - expected) ** 2 / expected, 0.0).sum()
    denom = n * (min(cont.shape) - 1)
    return float(np.sqrt(chi2 / denom)) if denom > 0 else 0.0


def contingency_stats(cont: np.ndarray) -> Dict:
    """PMI / mutual info / association-rule confidences from a levels ×
    labels table (OpStatistics.mutualInfo:234-276, maxConfidences:280-296).
    """
    total = cont.sum()
    row = cont.sum(axis=1)          # per level
    col = cont.sum(axis=0)          # per label
    with np.errstate(divide="ignore", invalid="ignore"):
        pmi = np.where(
            (cont > 0) & (row[:, None] > 0) & (col[None, :] > 0),
            np.log2(np.maximum(cont, 1e-99) * total
                    / np.maximum(row[:, None] * col[None, :], 1e-99)),
            0.0)
        mi = float((pmi * cont / max(total, 1)).sum())
        conf = np.where(row > 0, cont.max(axis=1) / np.maximum(row, 1), 0.0)
    supports = (row / max(total, 1)).tolist()
    pmi_map = {str(j): pmi[:, j].tolist() for j in range(cont.shape[1])}
    return {"cramers_v": cramers_v(cont), "mutual_info": mi,
            "pmi": pmi_map, "max_confidences": conf.tolist(),
            "supports": supports}


class SanityCheckerModel(Transformer):
    """Fitted checker: static column gather of the kept indices."""

    out_type = T.OPVector
    response_aware = True  # inputs stay (label, vector) post-fit

    def __init__(self, indices: Sequence[int], meta: Optional[Dict] = None,
                 summary: Optional[Dict] = None, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.indices = list(int(i) for i in indices)
        self._meta_json = (meta.to_json() if isinstance(meta, VectorMetadata)
                           else meta)
        self.summary = summary

    def device_apply(self, enc, dev):
        X = jnp.asarray(dev[-1])
        return X[:, jnp.asarray(self.indices, dtype=jnp.int32)]

    def output_meta(self) -> Optional[VectorMetadata]:
        if self._meta_json is None:
            return None
        return VectorMetadata.from_json(self._meta_json)

    def get_params(self):
        return {"indices": self.indices, "meta": self._meta_json,
                "summary": self.summary}


class SanityChecker(Estimator):
    """BinaryEstimator(RealNN label, OPVector) → cleaned OPVector.

    Drop rules (DerivedFeatureFilterUtils.scala:355-385): variance below
    `min_variance`; |corr(feature, label)| above `max_correlation`
    (leakage) or below `min_correlation`; |corr| with an EARLIER feature
    column above `max_feature_corr` (duplicates — later column dropped);
    categorical-group Cramér's V above `max_cramers_v`; association-rule
    confidence above `max_rule_confidence` at support above
    `min_required_rule_support`.
    """

    in_types = (T.RealNN, T.OPVector)
    out_type = T.OPVector
    response_aware = True  # slot 0 is the label

    def __init__(self, max_correlation: float = MAX_CORRELATION,
                 min_correlation: float = MIN_CORRELATION,
                 max_feature_corr: float = MAX_FEATURE_CORR,
                 min_variance: float = MIN_VARIANCE,
                 max_cramers_v: float = MAX_CRAMERS_V,
                 max_rule_confidence: float = MAX_RULE_CONFIDENCE,
                 min_required_rule_support: float = MIN_REQUIRED_RULE_SUPPORT,
                 correlation_type: str = "pearson",
                 check_sample: float = CHECK_SAMPLE,
                 sample_lower_limit: int = SAMPLE_LOWER_LIMIT,
                 sample_upper_limit: int = SAMPLE_UPPER_LIMIT,
                 sample_seed: int = 42,
                 remove_bad_features: bool = True,
                 categorical_label: Optional[bool] = None,
                 categorical_label_max_card: int = 30,
                 uid: Optional[str] = None):
        if correlation_type not in ("pearson", "spearman"):
            raise ValueError("correlation_type must be pearson or spearman")
        super().__init__(
            uid=uid, max_correlation=max_correlation,
            min_correlation=min_correlation, max_feature_corr=max_feature_corr,
            min_variance=min_variance, max_cramers_v=max_cramers_v,
            max_rule_confidence=max_rule_confidence,
            min_required_rule_support=min_required_rule_support,
            correlation_type=correlation_type, check_sample=check_sample,
            sample_lower_limit=sample_lower_limit,
            sample_upper_limit=sample_upper_limit, sample_seed=sample_seed,
            remove_bad_features=remove_bad_features,
            categorical_label=categorical_label,
            categorical_label_max_card=categorical_label_max_card)
        self.max_correlation = max_correlation
        self.min_correlation = min_correlation
        self.max_feature_corr = max_feature_corr
        self.min_variance = min_variance
        self.max_cramers_v = max_cramers_v
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.correlation_type = correlation_type
        self.check_sample = check_sample
        self.sample_lower_limit = sample_lower_limit
        self.sample_upper_limit = sample_upper_limit
        self.sample_seed = sample_seed
        self.remove_bad_features = remove_bad_features
        self.categorical_label = categorical_label
        self.categorical_label_max_card = categorical_label_max_card

    # ------------------------------------------------------------------ #

    def _sample_rows(self, n: int) -> Optional[np.ndarray]:
        """Row subsample for the statistics pass (checkSample/limits,
        SanityChecker.scala:60-92); None = use everything."""
        target = n
        if self.check_sample < 1.0:
            target = int(n * self.check_sample)
        target = min(target, self.sample_upper_limit)
        target = max(target, min(n, self.sample_lower_limit))
        if target >= n:
            return None
        rng = np.random.default_rng(self.sample_seed)
        return np.sort(rng.choice(n, size=target, replace=False))

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        label_col, vec_col = cols
        y_np = np.asarray(label_col.data["value"], dtype=np.float64)
        X_np = np.asarray(vec_col.device_value())
        n_total = X_np.shape[0]

        sample_idx = self._sample_rows(n_total)
        if sample_idx is not None:
            X_np = X_np[sample_idx]
            y_np = y_np[sample_idx]
        n, d = X_np.shape

        # Spearman = Pearson over average-tie ranks (host rank transform
        # feeding the identical device passes); `Cx/cy` are the correlation
        # inputs, raw-X moments are reported in the stats either way
        spearman = self.correlation_type == "spearman"
        X_dev = jnp.asarray(X_np)
        if spearman:
            Cx = jnp.asarray(_rank_transform(X_np))
            cy = jnp.asarray(_rank_transform(y_np[:, None])[:, 0])
        else:
            Cx = X_dev
            cy = jnp.asarray(y_np.astype(np.float32))

        need_ff = self.max_feature_corr < 1.0
        if need_ff:  # corr comes from the Gram pass; only raw moments here
            red = {k: np.asarray(v)
                   for k, v in _column_reductions(X_dev).items()}
        else:        # label terms ride the same single reduction pass
            redc = {k: np.asarray(v)
                    for k, v in _column_reductions(Cx, cy).items()}
            red = ({k: np.asarray(v)
                    for k, v in _column_reductions(X_dev).items()}
                   if spearman else redc)
        mean = red["sx"] / max(n, 1)
        var = (red["sxx"] - n * mean ** 2) / max(n - 1, 1)
        var = np.maximum(var, 0.0)
        hit_pairs: Dict[int, List[Tuple[int, float]]] = {}
        if need_ff and d > _WIDE_D:
            # wide-X: blocked Gram — label corr + sparse duplicate pairs,
            # no (d, d) materialization (SURVEY.md §5.7)
            corr, hit_pairs = _corr_label_and_hits_blocked(
                Cx, cy, self.max_feature_corr)
            feat_corr = None
        elif need_ff:
            # full corr matrix of [X | y]: ONE Gram matmul on the MXU
            corr_all = _corr_matrix(jnp.concatenate([Cx, cy[:, None]], 1))
            corr = corr_all[:d, d]
            feat_corr = corr_all[:d, :d]
        else:
            # duplicates check disabled → O(n·d) label terms suffice
            cmean = redc["sx"] / max(n, 1)
            cvar = np.maximum(
                (redc["sxx"] - n * cmean ** 2) / max(n - 1, 1), 0.0)
            y_mean = redc["sy"] / max(n, 1)
            y_var = max(
                (redc["syy"] - n * y_mean ** 2) / max(n - 1, 1), 0.0)
            cov = (redc["sxy"] - n * cmean * y_mean) / max(n - 1, 1)
            denom = np.sqrt(cvar * y_var)
            with np.errstate(divide="ignore", invalid="ignore"):
                corr = np.where(denom > 0, cov / denom, 0.0)
            feat_corr = None

        meta = vec_col.meta
        names = (meta.column_names() if meta is not None
                 else [f"col_{i}" for i in range(d)])

        # categorical groups → contingency stats vs a categorical label
        group_stats: Dict[int, Tuple[str, Dict]] = {}
        cat_groups: List[CategoricalGroupStats] = []
        if meta is not None:
            oh = _label_onehot(y_np, self.categorical_label_max_card,
                               force=self.categorical_label)
            if oh is not None:
                groups: Dict[str, List[int]] = {}
                for i, c in enumerate(meta.columns):
                    if c.indicator_value is not None:
                        groups.setdefault(c.grouping_key(), []).append(i)
                Xh = X_np  # the sampled host matrix (no device round-trip)
                for key, idxs in groups.items():
                    cont = Xh[:, idxs].T.astype(np.float64) @ oh
                    cs = contingency_stats(cont)
                    cat_groups.append(CategoricalGroupStats(
                        group=key, cramers_v=cs["cramers_v"],
                        mutual_info=cs["mutual_info"],
                        pointwise_mutual_info=cs["pmi"],
                        max_rule_confidences=cs["max_confidences"],
                        supports=cs["supports"]))
                    for li, i in enumerate(idxs):
                        group_stats[i] = (key, {
                            "cramers_v": cs["cramers_v"],
                            "mutual_info": cs["mutual_info"],
                            "conf": cs["max_confidences"][li],
                            "support": cs["supports"][li]})

        # feature-feature duplicates: vectorized candidate pairs, then the
        # "later column drops" scan ("dropping the later features",
        # DerivedFeatureFilterUtils:376). The wide path already produced
        # `hit_pairs`; the dense path extracts them from the matrix.
        if feat_corr is not None and self.max_feature_corr < 1.0 and d > 1:
            hit = np.abs(np.tril(feat_corr, k=-1)) > self.max_feature_corr
            for i in np.flatnonzero(hit.any(axis=1)):
                hit_pairs[int(i)] = [(int(j), float(feat_corr[i, j]))
                                     for j in np.flatnonzero(hit[i])]

        stats: List[ColumnStats] = []
        kept: List[int] = []
        dropped_so_far: set = set()
        for i in range(d):
            reasons: List[str] = []
            if var[i] < self.min_variance:
                reasons.append(f"variance {var[i]:.2e} < {self.min_variance}")
            ac = abs(float(corr[i]))
            if ac > self.max_correlation:
                reasons.append(f"label corr {ac:.3f} > {self.max_correlation}")
            elif self.min_correlation > 0 and ac < self.min_correlation:
                reasons.append(f"label corr {ac:.3f} < {self.min_correlation}")
            for j, cij in hit_pairs.get(i, ()):
                if j not in dropped_so_far:
                    reasons.append(
                        f"corr {cij:.3f} with column "
                        f"{names[j]!r} > {self.max_feature_corr}")
                    break
            gs = group_stats.get(i)
            gv = mi = conf = sup = None
            if gs is not None:
                key, s = gs
                gv, mi = s["cramers_v"], s["mutual_info"]
                conf, sup = s["conf"], s["support"]
                if gv > self.max_cramers_v:
                    reasons.append(f"cramersV {gv:.3f} > {self.max_cramers_v}")
                if (conf > self.max_rule_confidence
                        and sup > self.min_required_rule_support):
                    reasons.append(
                        f"rule confidence {conf:.3f} > "
                        f"{self.max_rule_confidence} at support {sup:.3f}")
            stats.append(ColumnStats(
                name=names[i], mean=float(mean[i]), variance=float(var[i]),
                min=float(red["min"][i]), max=float(red["max"][i]),
                corr_label=float(corr[i]), cramers_v=gv, mutual_info=mi,
                max_rule_confidence=conf, support=sup, dropped=reasons))
            if not reasons or not self.remove_bad_features:
                kept.append(i)
            elif reasons:
                dropped_so_far.add(i)

        if not kept:  # never drop everything (reference keeps result usable)
            kept = list(range(d))
            for s in stats:
                s.dropped.append("retained: all columns flagged")

        kept_set = set(kept)
        summary = SanityCheckerSummary(
            n_rows=n, stats=stats, kept_indices=kept,
            dropped_indices=[i for i in range(d) if i not in kept_set],
            correlation_type=self.correlation_type,
            sample_fraction=n / max(n_total, 1),
            categorical_stats=cat_groups)
        sel_meta = meta.select(kept) if meta is not None else None
        return SanityCheckerModel(kept, meta=sel_meta, summary=summary.to_json())


class MinVarianceFilterModel(SanityCheckerModel):
    pass


class MinVarianceFilter(Estimator):
    """Unary OPVector → OPVector: drop near-constant columns
    (MinVarianceFilter.scala — the unlabeled SanityChecker)."""

    in_types = (T.OPVector,)
    out_type = T.OPVector

    def __init__(self, min_variance: float = 1e-5, uid: Optional[str] = None):
        super().__init__(uid=uid, min_variance=min_variance)
        self.min_variance = min_variance

    def fit_model(self, cols: Sequence[Column], ctx: FitContext) -> Transformer:
        vec_col = cols[0]
        X = jnp.asarray(vec_col.device_value())
        n, d = X.shape
        mean = np.asarray(X.mean(0))
        var = np.asarray(((X - mean) ** 2).sum(0)) / max(n - 1, 1)
        kept = [i for i in range(d) if var[i] >= self.min_variance]
        if not kept:
            kept = list(range(d))
        meta = vec_col.meta
        sel_meta = meta.select(kept) if meta is not None else None
        summary = {"n_rows": int(n), "kept": kept,
                   "dropped": [i for i in range(d) if var[i] < self.min_variance]}
        return MinVarianceFilterModel(kept, meta=sel_meta, summary=summary)
